"""Host-level (DCN) collective groups: a zero-copy pipelined data plane.

Design notes (vs the reference's NCCL/Gloo groups,
/root/reference/python/ray/util/collective/collective_group/):

- Rendezvous rides the GCS KV (the reference uses a named actor store):
  rank 0 publishes a per-incarnation **nonce** that namespaces every
  address key (``collective/<group>/<nonce>/<rank>``), so re-creating a
  group with a previously-used name can never rendezvous against a dead
  incarnation's stale address; each rank publishes its listening
  address + node id and polls for the full ring.
- allreduce/reducescatter/allgather use the bandwidth-optimal ring
  algorithm (2*(N-1) steps, each moving 1/N of the data), the same
  schedule NCCL uses — **pipelined**: tensors are segmented into
  ``collective_chunk_bytes`` pieces chained per segment, so step k+1's
  send overlaps step k's recv+reduce (docs/collective.md).
- Transports (ray_tpu/util/collective/transport.py): same-node ranks
  exchange segments over shared-memory ring channels; cross-node pairs
  use receiver-driven TCP pull links whose replies land via
  ``recv_into`` buffer sinks directly in the consumer's accumulator /
  output buffer (zero-copy, docs/rpc_fastpath.md).
- Small tensors (<= ``collective_small_max_bytes``) take a latency-
  optimal recursive-doubling path; colocated ranks take a hierarchical
  two-level path (intra-node shm reduce -> inter-node leader ring ->
  intra-node shm broadcast); large ``broadcast()`` payloads ride the
  multi-source object-transfer plane (docs/object_transfer.md), every
  completed rank becoming an additional source.
- Tensors are numpy arrays (JAX arrays are converted on the way in and
  returned as numpy; callers on the hot path should use in-graph
  collectives instead — see :mod:`ray_tpu.util.collective.ici`).
"""

from __future__ import annotations

import json
import pickle
import queue
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu._private import rpc
from ray_tpu._private import runtime_metrics as rtm
from ray_tpu._private.config import CONFIG
from ray_tpu.runtime.core_worker import get_global_worker
from ray_tpu.util.collective import quant as _quant
from ray_tpu.util.collective.transport import (_M_TCP_BYTES, ServeBoard,
                                               ShmArena, ShmLink, TcpLink,
                                               Window, _chunk_bounds,
                                               _remaining, count_wire,
                                               tag_seq)


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


_REDUCERS = {
    ReduceOp.SUM: np.add,
    ReduceOp.PRODUCT: np.multiply,
    ReduceOp.MIN: np.minimum,
    ReduceOp.MAX: np.maximum,
}

_groups: Dict[str, Any] = {}
_groups_lock = threading.Lock()
# slot sentinel held while a _Group is under construction: the duplicate-
# name check and the insert form one atomic claim, so two racing
# init_collective_group calls can never both construct (and leak) a group
_PENDING = object()

# per-op/per-algorithm telemetry (docs/collective.md)
_BYTES_BOUNDARIES = tuple(float(1 << s) for s in range(10, 31, 2))
_M_OP_MS = rtm.histogram_family(
    "ray_tpu_collective_op_ms",
    "collective op latency by op/algorithm (ms)", tag_key="op")
_M_OP_BYTES = rtm.histogram_family(
    "ray_tpu_collective_op_bytes",
    "collective op tensor payload bytes by op/algorithm", tag_key="op",
    boundaries=_BYTES_BOUNDARIES)
_M_BCAST_STORE = rtm.counter(
    "ray_tpu_collective_bcast_store_total",
    "broadcasts routed over the multi-source object-transfer plane")
# backward-overlap accounting (docs/collective.md): per async op, how
# long the wire work ran vs how long the caller actually blocked in
# ``result()`` — the difference is comm time hidden behind compute
_M_OVERLAP_HIDDEN = rtm.histogram(
    "ray_tpu_collective_overlap_hidden_ms",
    "per async collective op: comm time hidden behind caller compute "
    "(op wall time minus time blocked in result())")
_M_OVERLAP_WAIT = rtm.histogram(
    "ray_tpu_collective_overlap_wait_ms",
    "per async collective op: time the caller blocked in result()")

# COLLECTIVE timeline slices: cap per group so chatty training loops
# can't grow the GCS task table without bound (same rationale as the
# 256-instants-per-stream cap, docs/observability.md)
_TIMELINE_OPS_CAP = 256


def _as_numpy(tensor: Any) -> np.ndarray:
    if isinstance(tensor, np.ndarray):
        return tensor
    return np.asarray(tensor)


# _remaining / _chunk_bounds come from transport.py: both endpoints of
# every link must derive identical segmentation, so there is exactly
# one definition




class AsyncWork:
    """Completion handle for a collective op enqueued with
    ``allreduce_async`` (the chained-completion API backward-overlapped
    gradient sync rides, docs/collective.md).

    ``result()`` blocks until the op ran on the group's async worker
    thread and returns (or re-raises) its outcome.  The first
    ``result()`` call also settles the overlap telemetry: the op's wall
    time minus the time actually spent blocked here is comm that was
    hidden behind the caller's compute."""

    def __init__(self):
        self._ev = threading.Event()
        self._res: Any = None
        self._exc: Optional[BaseException] = None
        self._t0 = rtm.now()          # enqueue time
        self._t_done = 0.0
        self._observed = False

    def done(self) -> bool:
        return self._ev.is_set()

    def comm_ms(self) -> Optional[float]:
        """Enqueue-to-completion wall time; None while in flight."""
        if not self._ev.is_set():
            return None
        return (self._t_done - self._t0) * 1000.0

    def result(self, timeout: Optional[float] = None) -> Any:
        t0 = rtm.now()
        if not self._ev.wait(timeout):
            raise TimeoutError("collective async op result timed out")
        if not self._observed:
            self._observed = True
            wait_ms = (rtm.now() - t0) * 1000.0
            _M_OVERLAP_WAIT.observe(wait_ms)
            _M_OVERLAP_HIDDEN.observe(
                max(0.0, (self.comm_ms() or 0.0) - wait_ms))
        if self._exc is not None:
            raise self._exc
        return self._res

    def _finish(self, res: Any, exc: Optional[BaseException]) -> None:
        self._res, self._exc = res, exc
        self._t_done = rtm.now()
        self._ev.set()


class _StagingPool:
    """``depth`` reusable receive buffers for in-flight reduce segments.

    Slot rotation is safe because the Window processes completions in
    issue order: slot j is handed out again only after item j-depth has
    been fully consumed."""

    def __init__(self, depth: int, seg_elems: int, dtype):
        self._bufs = [np.empty(seg_elems, dtype) for _ in range(depth)]
        self._i = 0

    def take(self, elems: int) -> np.ndarray:
        buf = self._bufs[self._i % len(self._bufs)]
        self._i += 1
        return buf[:elems]


class _Mailbox:
    """Incoming push messages keyed by (src_rank, tag).

    Hygiene (ISSUE 6): queues are deques (O(1) pop), and messages whose
    tag belongs to an op older than the group's current op sequence are
    dropped on arrival — a recv that timed out can no longer leave its
    late-arriving message queued forever to poison the next op that
    reuses the (src, tag) slot.  Unsequenced tags (p2p) are exempt."""

    def __init__(self, group: str = "", rank: int = -1):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._msgs: Dict[Tuple[int, str], deque] = {}
        self._floor = 0
        self._closed = False
        self._group = group
        self._rank = rank

    def put(self, src: int, tag: str, payload: Any) -> None:
        seq = tag_seq(tag)
        with self._cv:
            if self._closed:
                return
            if seq is not None and seq < self._floor:
                return  # stale: its op already finished or timed out
            self._msgs.setdefault((src, tag), deque()).append(payload)
            self._cv.notify_all()

    def get(self, src: int, tag: str, timeout: float) -> Any:
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                if self._closed:
                    raise RuntimeError("collective group destroyed")
                q = self._msgs.get((src, tag))
                if q:
                    msg = q.popleft()
                    if not q:
                        del self._msgs[(src, tag)]
                    return msg
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # the likeliest cause is ``src`` dying mid-op: emit
                    # the rank-death event before unwinding so the
                    # cluster event table explains the op failure
                    # (docs/observability.md)
                    from ray_tpu._private import cluster_events as cev
                    cev.emit(cev.COLLECTIVE_RANK_DEATH,
                             f"group {self._group!r} rank {self._rank}: "
                             f"recv from rank {src} timed out "
                             f"(tag={tag}) — peer dead or stalled",
                             severity="ERROR", group=self._group,
                             rank=self._rank, src_rank=src)
                    raise TimeoutError(
                        f"collective recv (src={src}, tag={tag}) timed out")
                self._cv.wait(remaining)

    def expire_below(self, seq_floor: int) -> None:
        with self._cv:
            self._floor = seq_floor
            for key in [k for k in self._msgs
                        if (tag_seq(k[1]) or seq_floor) < seq_floor]:
                del self._msgs[key]

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._msgs.clear()
            self._cv.notify_all()


class _Group:
    def __init__(self, name: str, world_size: int, rank: int,
                 timeout: float = 60.0):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.timeout = timeout
        worker = get_global_worker()
        self._worker = worker
        self._store = getattr(worker, "store", None)
        self._node = getattr(worker, "node_id", "")
        self._mailbox = _Mailbox(name, rank)
        self._board = ServeBoard()
        # "msg" never blocks (mailbox append): inline on the reader.
        # "take" stays POOLED: an already-published entry resolves its
        # reply inside the handler, and that send can block on a
        # saturated socket — blocking the reader thread would deadlock
        # a full-duplex ring under load.
        self._server = rpc.Server(self._handle,
                                  fast_methods=("msg", "rdv"))
        self._conns: Dict[int, rpc.Connection] = {}
        self._conns_lock = threading.Lock()
        self._links: Dict[int, Any] = {}
        self._links_lock = threading.Lock()
        self._seq = 0
        self._op_lock = threading.Lock()
        self._op_count = 0
        self._destroyed = threading.Event()
        # backward-overlap engine: ops enqueued with allreduce_async run
        # FIFO on one worker thread (started lazily), so every rank
        # executes async ops in enqueue order — the cross-rank op-order
        # agreement the tag protocol requires
        self._async_q: Optional[queue.Queue] = None
        self._async_thread: Optional[threading.Thread] = None
        self._async_lock = threading.Lock()
        # intra-slice in-graph reduction hook (register_ici_mesh): when
        # set, the topology schedule reduces SUM ops across the slice
        # inside a compiled program instead of over host links
        self._ici_reduce = None
        try:
            self._rendezvous()
        except BaseException:
            self._server.stop()
            raise

    # ------------------------------------------------------------ plumbing
    def _handle(self, conn: rpc.Connection, method: str, p: Any) -> Any:
        if method == "msg":
            self._mailbox.put(p["src"], p["tag"], p["data"])
            return True
        if method == "take":
            return self._board.take(p["src"], p["tag"])
        if method == "rdv":
            # rendezvous confirmation: a joiner accepts a collected
            # address set only after rank 0 (always part of the live
            # incarnation) acknowledges the nonce — a dead
            # incarnation's complete key set can't satisfy this (its
            # rank 0 is gone or answers with a different nonce)
            return p.get("nonce") == self.nonce
        raise rpc.RpcError(f"collective: unknown method {method}")

    def _rendezvous(self) -> None:
        gcs = self._worker.gcs
        base = f"collective/{self.name}"
        deadline = time.monotonic() + self.timeout
        if self.rank == 0:
            # fresh incarnation: sweep every key of prior incarnations
            # FIRST (their addresses may belong to dead ranks), then
            # publish the nonce that namespaces this one's keys
            try:
                for k in gcs.kv_keys(base + "/"):
                    gcs.kv_del(k)
            except Exception:
                pass
            self.nonce = uuid.uuid4().hex[:12]
            gcs.kv_put(f"{base}/nonce", self.nonce.encode())
        else:
            self.nonce = self._poll_nonce(gcs, base, deadline)
        # each rank publishes its slice label alongside the address: the
        # topology scheduler groups ranks by slice without extra control
        # traffic (the label mirrors the raylet's "slice" node label,
        # docs/collective.md)
        me = json.dumps([self._server.address[0],
                         int(self._server.address[1]), self._node,
                         CONFIG.tpu_slice_name])
        gcs.kv_put(f"{base}/{self.nonce}/{self.rank}", me.encode())
        self._addrs: Dict[int, Tuple[str, int]] = {}
        self._nodes: Dict[int, str] = {}
        self._slices: Dict[int, str] = {}
        while len(self._addrs) < self.world_size:
            for r in range(self.world_size):
                if r in self._addrs:
                    continue
                raw = gcs.kv_get(f"{base}/{self.nonce}/{r}")
                if raw is not None:
                    vals = json.loads(raw.decode())
                    host, port, node = vals[0], vals[1], vals[2]
                    self._addrs[r] = (host, int(port))
                    self._nodes[r] = node
                    self._slices[r] = vals[3] if len(vals) > 3 else ""
            if len(self._addrs) == self.world_size:
                if self.rank == 0 or self._confirm_rank0():
                    break
                # a complete-looking key set under a dead incarnation's
                # nonce: rank 0 never confirmed it — rejoin below
                self._addrs.clear()
                self._nodes.clear()
                self._slices.clear()
            if self.rank != 0:
                # a rank that read a dead incarnation's leftover nonce
                # migrates the moment rank 0 publishes the fresh one
                raw = gcs.kv_get(f"{base}/nonce")
                cur = raw.decode() if raw is not None else None
                if cur is not None and cur != self.nonce:
                    gcs.kv_del(f"{base}/{self.nonce}/{self.rank}")
                    self.nonce = cur
                    gcs.kv_put(f"{base}/{cur}/{self.rank}", me.encode())
                    self._addrs.clear()
                    self._nodes.clear()
                    self._slices.clear()
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"collective group {self.name!r}: only "
                    f"{len(self._addrs)}/{self.world_size} ranks showed")
            time.sleep(0.05)

    def _confirm_rank0(self) -> bool:
        """Joiner-side rendezvous confirmation (see the ``rdv``
        handler): True only when the rank-0 address we collected
        answers AND acknowledges our nonce."""
        try:
            conn = rpc.connect(self._addrs[0], timeout=2.0)
        except (OSError, ConnectionError):
            return False
        try:
            return bool(conn.call("rdv", {"nonce": self.nonce},
                                  timeout=5.0))
        except Exception:
            return False
        finally:
            conn.close()

    def _poll_nonce(self, gcs, base: str, deadline: float) -> str:
        while True:
            raw = gcs.kv_get(f"{base}/nonce")
            if raw is not None:
                return raw.decode()
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"collective group {self.name!r}: rank 0 never "
                    f"published the rendezvous nonce")
            time.sleep(0.05)

    def _conn_to(self, peer: int) -> rpc.Connection:
        with self._conns_lock:
            conn = self._conns.get(peer)
            if conn is None or conn.closed:
                conn = rpc.connect(self._addrs[peer])
                self._conns[peer] = conn
            return conn

    def _link(self, peer: int):
        with self._links_lock:
            ln = self._links.get(peer)
            if ln is None:
                win = max(1, CONFIG.collective_inflight_segments)
                if (CONFIG.collective_shm_enabled
                        and self._store is not None
                        and self._nodes.get(peer) == self._node):
                    ln = ShmLink(
                        self._store, self.name, self.nonce, self.rank,
                        peer,
                        capacity=self._seg_bytes() + 4096,
                        nslots=max(CONFIG.collective_shm_slots, win + 2),
                        # waits pump EVERY shm outbox of the group: the
                        # segment a parked peer needs may be queued on a
                        # different link than the one being waited on
                        pump_all=self._pump_shm_outboxes)
                else:
                    ln = TcpLink(self, peer)
                self._links[peer] = ln
            return ln

    def _pump_shm_outboxes(self) -> None:
        """Non-blocking: move queued segments of EVERY shm link into
        their rings while credit lasts (called from wait slices and the
        op-end drain; single op thread, so no cross-link locking)."""
        with self._links_lock:
            links = list(self._links.values())
        for ln in links:
            if isinstance(ln, ShmLink):
                ln._pump_outbox()

    # ------------------------------------------------------- op lifecycle
    def _begin(self) -> Tuple[int, float, float]:
        if self._destroyed.is_set():
            raise RuntimeError(
                f"collective group {self.name!r} is destroyed")
        self._seq += 1
        seq = self._seq
        # hygiene: anything still parked/queued for older ops belongs to
        # a peer that timed out — fail/drop it instead of letting it
        # poison this op's tag space
        self._mailbox.expire_below(seq)
        self._board.sweep_below(seq)
        with self._links_lock:
            links = list(self._links.values())
        for ln in links:
            if isinstance(ln, ShmLink):
                ln.drop_stashed_below(seq)
        deadline = time.monotonic() + CONFIG.collective_op_timeout_s
        return seq, deadline, rtm.now()

    def _end(self, op: str, algo: str, nbytes: int, deadline: float,
             t0: float) -> None:
        # shm links: release the last read slot and drain outboxed
        # segments peers are still parked on
        with self._links_lock:
            links = list(self._links.values())
        for ln in links:
            ln.finish_op(deadline)
        # published stable frames reference this op's buffers: wait for
        # every peer to collect AND for the frames to drain to the
        # socket before the caller may mutate/free them
        self._board.wait_clear(deadline)
        label = f"{op}/{algo}"
        _M_OP_MS.observe_since(label, t0)
        _M_OP_BYTES.observe(label, float(nbytes))
        self._timeline(op, algo, nbytes, t0)

    def _timeline(self, op: str, algo: str, nbytes: int,
                  t0: float) -> None:
        if self._op_count >= _TIMELINE_OPS_CAP:
            return
        self._op_count += 1
        events = getattr(self._worker, "events", None)
        if events is None:
            return
        try:
            events.record(
                f"col-{self.name}-r{self.rank}", "COLLECTIVE",
                name=f"collective:{self.name}",
                dur_ms=round((rtm.now() - t0) * 1000.0, 3),
                bytes=int(nbytes), op=op, algo=algo,
                world=self.world_size, node_id=self._node,
                worker_id=self._worker.worker_id.hex())
        except Exception:
            pass

    def _seg_bytes(self) -> int:
        """Segment size for this group's ops: the configured chunk,
        capped at the shm slot size when any ranks are colocated (shm
        ring slots are sized for the cap, and both endpoints of every
        pair must derive the same segmentation)."""
        chunk = CONFIG.collective_chunk_bytes
        if (CONFIG.collective_shm_enabled and self._store is not None
                and len(set(self._nodes.values())) < self.world_size):
            return min(chunk, CONFIG.collective_shm_slot_bytes)
        return chunk

    def _seg_elems_of(self, itemsize: int) -> int:
        return max(1, self._seg_bytes() // max(1, itemsize))

    def _arena(self) -> ShmArena:
        if getattr(self, "_arena_inst", None) is None:
            self._arena_inst = ShmArena(
                self._store, self.name, self.nonce, self.rank,
                list(range(self.world_size)))
        return self._arena_inst

    def _flat_shm_ok(self, nbytes: int) -> bool:
        """Deterministic across ranks: config + topology + the shared
        segment's fixed capacity (identical on every local rank), never
        current occupancy.  Occupancy blindness is backstopped at slab
        allocation: a failing rank poisons the arena (peers unwind in
        seconds) and every rank flips to the ring for later ops."""
        if getattr(self, "_arena_broken", False):
            return False
        if not (CONFIG.collective_shm_enabled and CONFIG.collective_flat_shm
                and self._store is not None
                and len(set(self._nodes.values())) == 1):
            return False
        try:
            cap = self._store.stats()["capacity"]
        except Exception:
            return False
        return (self.world_size + 1) * nbytes * 2.5 <= cap

    def _hier_worthwhile(self, reducer=None) -> bool:
        """Hierarchy only pays when the topology collapses ranks:
        several groups AND colocated ranks cut DCN traffic, or a
        registered in-graph (ICI) reducer can absorb a multi-rank slice
        entirely (SUM ops; ``register_ici_mesh`` must run on every rank
        of the group so all ranks reach the same verdict).  A flat
        single-node group is better off on the ring — funneling every
        byte through one leader process serializes the reduction work
        the ring spreads across ranks."""
        groups = self._topo_groups()
        if (self._ici_reduce is not None and reducer is np.add
                and bool(CONFIG.collective_topology)
                and len(groups) < self.world_size):
            return True
        return 1 < len(groups) < self.world_size

    def _topo_engaged(self) -> bool:
        """True when the slice-aware schedule actually differs from the
        2-level node grouping (slice labels collapse nodes, or an ICI
        reducer is registered)."""
        if not CONFIG.collective_topology:
            return False
        if self._ici_reduce is not None:
            return True
        return len(self._topo_groups()) != len(set(self._nodes.values()))

    # ------------------------------------------------- small-tensor plane
    def _small_send(self, peer: int, tag: str, arr: np.ndarray,
                    deadline: float) -> None:
        ln = self._link(peer)
        if isinstance(ln, ShmLink):
            ln.publish(tag, arr, deadline)
            return
        conn = self._conn_to(peer)
        conn.call_async("msg",
                        {"src": self.rank, "tag": tag, "data": arr})
        _M_TCP_BYTES.inc(arr.nbytes)

    def _small_recv(self, peer: int, tag: str,
                    deadline: float) -> np.ndarray:
        ln = self._link(peer)
        if isinstance(ln, ShmLink):
            arr, _ = ln.wait(tag, deadline)
            # shm wait returns a ring-slot view valid only until the
            # next link op; small-path values are retained (rd
            # accumulators, headers) so own them here
            return np.array(arr, copy=True)
        data = self._mailbox.get(peer, tag, _remaining(deadline))
        arr = _as_numpy(data)
        _M_TCP_BYTES.inc(arr.nbytes)
        return arr

    # ------------------------------------------------------- ring engines
    # NOTE: the windowed pipelined-ring pattern below (segs helper, lazy
    # init deque, done closures, drain) recurs with schedule-offset
    # variations in reducescatter/allgather/_ring_broadcast_recv.  The
    # offsets differ subtly per op (see each docstring); factoring one
    # parameterized engine is deliberate future work — change the
    # pump/publish discipline in ALL FOUR places or in none.
    def _ring_allreduce(self, flat: np.ndarray, participants: List[int],
                        reducer, seq: int, deadline: float,
                        codec=None) -> None:
        """Pipelined ring allreduce over ``participants``, in place on
        ``flat``: reduce-scatter then allgather, each chunk segmented
        into ``collective_chunk_bytes`` pieces chained per segment —
        receiving segment (k, s) immediately reduces and publishes
        segment (k+1, s), so successive ring steps overlap (the NCCL
        schedule, full duplex).

        With a ``codec`` (quantize="int8"), every segment is encoded
        before the wire and decoded into the fp32 master accumulator
        ``flat`` on arrival: reduce-scatter hops re-encode the running
        partial sum (one bounded rounding error per hop), allgather
        hops forward the encoded bytes verbatim (zero added error) —
        see quant.py for the numerics contract."""
        m = len(participants)
        if m == 1 or flat.size == 0:
            return
        i = participants.index(self.rank)
        plink = self._link(participants[(i - 1) % m])
        nlink = self._link(participants[(i + 1) % m])
        bounds = _chunk_bounds(flat.size, m)
        se = self._seg_elems_of(flat.itemsize)
        win = Window(CONFIG.collective_inflight_segments, deadline)
        max_seg = min(se, max(1, flat.size))
        if codec is None:
            staging = _StagingPool(win.depth, max_seg, flat.dtype)
        else:
            # staging receives WIRE bytes; decode owns the payload, so
            # slot rotation stays safe under the same issue-order rule
            staging = _StagingPool(win.depth, codec.wire_nbytes(max_seg),
                                   np.uint8)

        def segs(c):
            a, b = bounds[c]
            return [(s, min(s + se, b)) for s in range(a, b, se)]

        def pub(tag, rng):
            if codec is None:
                count_wire("fp32", rng.nbytes, rng.nbytes)
                nlink.publish(tag, rng, deadline)
            else:
                wire = codec.encode(rng)
                count_wire(codec.name, wire.nbytes, rng.nbytes)
                nlink.publish(tag, wire, deadline)

        def dest_of(a, b):
            if codec is None:
                return staging.take(b - a)
            return staging.take(codec.wire_nbytes(b - a))

        # own chunk's initial publishes go out lazily, one per request
        # issued below, so a bounded shm ring can never absorb a whole
        # chunk's burst before its reader starts consuming
        init = deque((f"{seq}:rs0:{a}", flat[a:b]) for a, b in segs(i))

        def pump_init():
            if init:
                tag, arr = init.popleft()
                pub(tag, arr)

        last = m - 2

        def rs_done(k, a, b):
            def done(arr, in_place):
                rng = flat[a:b]
                if codec is not None:
                    arr = codec.decode(arr, b - a, flat.dtype)
                reducer(rng, arr, out=rng)
                if k < last:
                    pub(f"{seq}:rs{k + 1}:{a}", rng)
                else:
                    pub(f"{seq}:ag0:{a}", rng)
            return done

        def ag_done(k, a, b):
            def done(arr, in_place):
                rng = flat[a:b]
                if codec is not None:
                    if k < last:
                        # forward the encoded bytes verbatim: the copy
                        # owns them (arr may view a rotating staging
                        # slot or a shm ring slot) and no re-encode
                        # means allgather adds no per-hop error
                        fwd = np.array(arr, copy=True)
                        count_wire(codec.name, fwd.nbytes, rng.nbytes)
                        nlink.publish(f"{seq}:ag{k + 1}:{a}", fwd,
                                      deadline)
                    codec.decode(arr, b - a, flat.dtype, out=rng)
                    return
                if not in_place:
                    np.copyto(rng, arr)
                if k < last:
                    pub(f"{seq}:ag{k + 1}:{a}", rng)
            return done

        for k in range(m - 1):
            for a, b in segs((i - k - 1) % m):
                pump_init()
                win.push(plink, f"{seq}:rs{k}:{a}", dest_of(a, b),
                         rs_done(k, a, b))
        for k in range(m - 1):
            for a, b in segs((i - k) % m):
                pump_init()
                # fp32 allgather segments land straight in their final
                # position in the output buffer (recv_into zero-copy);
                # quantized ones land in wire staging and decode out
                win.push(plink, f"{seq}:ag{k}:{a}",
                         flat[a:b] if codec is None else dest_of(a, b),
                         ag_done(k, a, b))
        while init:
            pump_init()
        win.drain()

    def _topo_groups(self) -> Dict[str, List[int]]:
        """Topology grouping for this group's ranks, computed per op:
        ranks carrying a slice label (published at rendezvous) group by
        slice when ``collective_topology`` is on; unlabeled ranks group
        by node id, so an unlabeled cluster degenerates to the classic
        node-boundary grouping."""
        topo = bool(CONFIG.collective_topology)
        by: Dict[str, List[int]] = {}
        for r in range(self.world_size):
            s = self._slices.get(r, "") if topo else ""
            key = ("s:" + s) if s else ("n:" + self._nodes.get(r, ""))
            by.setdefault(key, []).append(r)
        return by

    def _hier_allreduce(self, flat: np.ndarray, reducer, seq: int,
                        deadline: float, codec=None) -> np.ndarray:
        """Topology-scheduled hierarchical allreduce
        (docs/collective.md).  Three levels, each engaged only where
        the topology collapses ranks:

        1. intra-node reduce to a per-node leader (shm links);
        2. intra-slice allreduce among the slice's node leaders — via
           the registered in-graph (ICI) reducer when one exists (SUM
           ops reduce across the whole slice inside a compiled program
           and level 1 is skipped entirely), else a host-link ring;
        3. a DCN ring among slice leaders only;

        then the result fans back out (slice leader -> node leaders ->
        node members).  Unlabeled clusters run exactly the former
        2-level node-boundary schedule (every node is its own slice,
        level 2 is empty)."""
        by_slice = self._topo_groups()
        my_slice: List[int] = []
        for rs in by_slice.values():
            if self.rank in rs:
                my_slice = sorted(rs)
                break
        slice_leaders = sorted(min(rs) for rs in by_slice.values())
        slice_leader = my_slice[0]
        by_node: Dict[str, List[int]] = {}
        for r in my_slice:
            by_node.setdefault(self._nodes.get(r, ""), []).append(r)
        local = sorted(by_node[self._nodes.get(self.rank, "")])
        leader = local[0]
        node_leaders = sorted(min(rs) for rs in by_node.values())
        use_ici = (self._ici_reduce is not None and reducer is np.add
                   and len(my_slice) > 1
                   and bool(CONFIG.collective_topology))
        se = self._seg_elems_of(flat.itemsize)
        segs = [(a, min(a + se, flat.size))
                for a in range(0, flat.size, se)]
        max_seg = min(se, max(1, flat.size))

        def pool(depth):
            if codec is None:
                return _StagingPool(depth, max_seg, flat.dtype)
            return _StagingPool(depth, codec.wire_nbytes(max_seg),
                                np.uint8)

        def fan_out(tag_fn, targets):
            """Publish every segment to every target; quantized
            payloads are encoded ONCE per segment and the same wire
            array rides every link."""
            if not targets:
                return
            links = [self._link(t) for t in targets]
            for a, b in segs:
                rng = flat[a:b]
                payload = rng if codec is None else codec.encode(rng)
                name = "fp32" if codec is None else codec.name
                for ln in links:
                    count_wire(name, payload.nbytes, rng.nbytes)
                    ln.publish(tag_fn(a), payload, deadline)

        def recv_into(win, ln, tag, a, b, staging):
            """Window-push a receive that lands (decoded) in
            ``flat[a:b]``."""
            if codec is None:
                def done(arr, in_place, a=a, b=b):
                    if not in_place:
                        np.copyto(flat[a:b], arr)
                win.push(ln, tag, flat[a:b], done)
            else:
                def done(arr, in_place, a=a, b=b):
                    codec.decode(arr, b - a, flat.dtype, out=flat[a:b])
                win.push(ln, tag, staging.take(codec.wire_nbytes(b - a)),
                         done)

        def recv_reduce(win, ln, tag, a, b, staging):
            if codec is None:
                def done(arr, in_place, a=a, b=b):
                    rng = flat[a:b]
                    reducer(rng, arr, out=rng)
                win.push(ln, tag, staging.take(b - a), done)
            else:
                def done(arr, in_place, a=a, b=b):
                    rng = flat[a:b]
                    reducer(rng, codec.decode(arr, b - a, flat.dtype),
                            out=rng)
                win.push(ln, tag, staging.take(codec.wire_nbytes(b - a)),
                         done)

        if use_ici:
            # level 1+2 collapse into one in-graph reduction: every
            # slice rank contributes and receives the slice sum with
            # zero host-link bytes
            reduced = self._ici_reduce(flat)
            np.copyto(flat, np.asarray(reduced,
                                       dtype=flat.dtype).reshape(-1))
            if len(slice_leaders) > 1:
                if self.rank == slice_leader:
                    self._ring_allreduce(flat, slice_leaders, reducer,
                                         seq, deadline, codec)
                    fan_out(lambda a: f"{seq}:hb:{a}",
                            [r for r in my_slice if r != self.rank])
                else:
                    win = Window(CONFIG.collective_inflight_segments,
                                 deadline)
                    staging = pool(win.depth)
                    ln = self._link(slice_leader)
                    for a, b in segs:
                        recv_into(win, ln, f"{seq}:hb:{a}", a, b,
                                  staging)
                    win.drain()
            return flat

        if self.rank != leader:
            # node member: contribute to my node leader, receive the
            # finished result back
            ln = self._link(leader)
            fan_out(lambda a: f"{seq}:hr{self.rank}:{a}", [leader])
            win = Window(CONFIG.collective_inflight_segments, deadline)
            staging = pool(win.depth)
            for a, b in segs:
                recv_into(win, ln, f"{seq}:hb:{a}", a, b, staging)
            win.drain()
            return flat
        # level 1: star-reduce my node's members
        if local[1:]:
            win = Window(CONFIG.collective_inflight_segments, deadline)
            staging = pool(win.depth)
            for a, b in segs:
                for mr in local[1:]:
                    recv_reduce(win, self._link(mr),
                                f"{seq}:hr{mr}:{a}", a, b, staging)
            win.drain()
        # level 2: intra-slice ring among this slice's node leaders
        # (host links; disjoint from the DCN ring's link set, so the
        # shared per-op tag space cannot collide)
        if len(node_leaders) > 1:
            self._ring_allreduce(flat, node_leaders, reducer, seq,
                                 deadline, codec)
        # level 3: DCN ring among slice leaders only
        if self.rank == slice_leader and len(slice_leaders) > 1:
            self._ring_allreduce(flat, slice_leaders, reducer, seq,
                                 deadline, codec)
        # fan back out: slice leader -> other node leaders of my slice
        if len(slice_leaders) > 1 and len(node_leaders) > 1:
            if self.rank == slice_leader:
                fan_out(lambda a: f"{seq}:hs:{a}",
                        [r for r in node_leaders if r != self.rank])
            else:
                win = Window(CONFIG.collective_inflight_segments,
                             deadline)
                staging = pool(win.depth)
                ln = self._link(slice_leader)
                for a, b in segs:
                    recv_into(win, ln, f"{seq}:hs:{a}", a, b, staging)
                win.drain()
        # node leader -> node members
        fan_out(lambda a: f"{seq}:hb:{a}", local[1:])
        return flat

    def _rd_allreduce(self, flat: np.ndarray, reducer, seq: int,
                      deadline: float) -> np.ndarray:
        """Latency-optimal recursive doubling for small tensors:
        log2(N) whole-tensor exchange rounds (non-power-of-2 handled by
        folding the extra ranks into the power-of-2 core first)."""
        n, r = self.world_size, self.rank
        p = 1 << (n.bit_length() - 1)
        extra = n - p
        acc = flat
        if r >= p:
            self._small_send(r - p, f"{seq}:rdi", acc, deadline)
            return self._small_recv(r - p, f"{seq}:rdo", deadline)
        if r < extra:
            inc = self._small_recv(r + p, f"{seq}:rdi", deadline)
            acc = reducer(acc, inc)
        k = 1
        while k < p:
            partner = r ^ k
            self._small_send(partner, f"{seq}:rdx{k}", acc, deadline)
            inc = self._small_recv(partner, f"{seq}:rdx{k}", deadline)
            acc = reducer(acc, inc)
            k <<= 1
        if r < extra:
            self._small_send(r + p, f"{seq}:rdo", acc, deadline)
        return acc

    # ---------------------------------------------------------- primitives
    def allreduce(self, tensor: Any, op: str = ReduceOp.SUM,
                  quantize: Optional[str] = None) -> np.ndarray:
        x = _as_numpy(tensor)
        # resolve the codec FIRST so an unknown name fails loudly even
        # on sizes that would bypass quantization
        codec = _quant.get_codec(quantize, CONFIG.collective_quant_block)
        if codec is not None and not np.issubdtype(x.dtype, np.floating):
            raise ValueError(
                f"quantize={quantize!r} requires a floating dtype, "
                f"got {x.dtype} (integer reductions must stay exact)")
        if self.world_size == 1:
            return x.copy()
        if codec is not None and x.nbytes <= max(
                CONFIG.collective_quant_min_bytes,
                CONFIG.collective_small_max_bytes):
            # too small to amortize encode + scale overhead; the
            # threshold is config + tensor size, so every rank nulls
            # the codec identically (callers must pass the same
            # quantize= on every rank, like op=)
            codec = None
        reducer = _REDUCERS[op]
        with self._op_lock:
            seq, deadline, t0 = self._begin()
            if codec is None \
                    and x.nbytes > CONFIG.collective_small_max_bytes \
                    and self._flat_shm_ok(x.nbytes):
                # the arena reads the input slab-side: no private
                # working copy needed
                algo = "flatshm"
                src = np.ascontiguousarray(x).reshape(-1)
                out = np.empty_like(src)
                try:
                    self._arena().allreduce(src, out, reducer, deadline)
                except Exception:
                    # slab allocation failure / poison: THIS op fails on
                    # every rank (the poison propagates), later ops take
                    # the ring — all ranks converge on the same verdict
                    self._arena_broken = True
                    raise
                self._end("allreduce", algo, x.nbytes, deadline, t0)
                return out.reshape(x.shape)
            flat = np.array(x, copy=True).reshape(-1)
            if flat.nbytes <= CONFIG.collective_small_max_bytes:
                algo = "rd"  # codec is always None here (size gate)
                out = self._rd_allreduce(flat, reducer, seq, deadline)
            elif CONFIG.collective_hierarchical \
                    and self._hier_worthwhile(reducer):
                algo = "topo" if self._topo_engaged() else "hier"
                out = self._hier_allreduce(flat, reducer, seq, deadline,
                                           codec)
            else:
                algo = "ring"
                self._ring_allreduce(flat, list(range(self.world_size)),
                                     reducer, seq, deadline, codec)
                out = flat
            if codec is not None:
                algo = f"{algo}-{codec.name}"
            self._end("allreduce", algo, x.nbytes, deadline, t0)
        if not out.flags.writeable:
            out = out.copy()
        return out.reshape(x.shape)

    def reducescatter(self, tensor: Any,
                      op: str = ReduceOp.SUM) -> np.ndarray:
        """Each rank gets its reduced 1/N shard (pipelined ring
        reduce-scatter; schedule offset -1 vs allreduce's so rank r
        finishes owning chunk r, matching allgather's index==rank
        convention)."""
        x = _as_numpy(tensor)
        n, i = self.world_size, self.rank
        if n == 1:
            return x.copy()
        reducer = _REDUCERS[op]
        with self._op_lock:
            seq, deadline, t0 = self._begin()
            flat = np.array(x, copy=True).reshape(-1)
            bounds = _chunk_bounds(flat.size, n)
            se = self._seg_elems_of(flat.itemsize)
            plink = self._link((i - 1) % n)
            nlink = self._link((i + 1) % n)
            win = Window(CONFIG.collective_inflight_segments, deadline)
            staging = _StagingPool(win.depth, min(se, max(1, flat.size)),
                                   flat.dtype)

            def segs(c):
                a, b = bounds[c]
                return [(s, min(s + se, b)) for s in range(a, b, se)]

            init = deque((f"{seq}:rs0:{a}", flat[a:b])
                         for a, b in segs((i - 1) % n))
            last = n - 2

            def rs_done(k, a, b):
                def done(arr, in_place):
                    rng = flat[a:b]
                    reducer(rng, arr, out=rng)
                    if k < last:
                        nlink.publish(f"{seq}:rs{k + 1}:{a}", rng,
                                      deadline)
                return done

            for k in range(n - 1):
                for a, b in segs((i - k - 2) % n):
                    if init:
                        tag, arr = init.popleft()
                        nlink.publish(tag, arr, deadline)
                    win.push(plink, f"{seq}:rs{k}:{a}",
                             staging.take(b - a), rs_done(k, a, b))
            while init:
                tag, arr = init.popleft()
                nlink.publish(tag, arr, deadline)
            win.drain()
            a, b = bounds[i]
            out = flat[a:b].copy()
            self._end("reducescatter", "ring", x.nbytes, deadline, t0)
        return out

    def allgather(self, tensor: Any) -> List[np.ndarray]:
        x = _as_numpy(tensor)
        n, i = self.world_size, self.rank
        if n == 1:
            return [x.copy()]
        with self._op_lock:
            seq, deadline, t0 = self._begin()
            flat = np.ascontiguousarray(x).reshape(-1)
            sz = flat.size
            out = np.empty(n * sz, flat.dtype)
            np.copyto(out[i * sz:(i + 1) * sz], flat)
            se = self._seg_elems_of(flat.itemsize)
            plink = self._link((i - 1) % n)
            nlink = self._link((i + 1) % n)
            win = Window(CONFIG.collective_inflight_segments, deadline)

            def segs(c):
                a, b = c * sz, (c + 1) * sz
                return [(s, min(s + se, b)) for s in range(a, b, se)]

            init = deque((f"{seq}:ag0:{a}", out[a:b])
                         for a, b in segs(i))
            last = n - 2

            def ag_done(k, a, b):
                def done(arr, in_place):
                    rng = out[a:b]
                    if not in_place:
                        np.copyto(rng, arr)
                    if k < last:
                        nlink.publish(f"{seq}:ag{k + 1}:{a}", rng,
                                      deadline)
                return done

            for k in range(n - 1):
                for a, b in segs((i - k - 1) % n):
                    if init:
                        tag, arr = init.popleft()
                        nlink.publish(tag, arr, deadline)
                    win.push(plink, f"{seq}:ag{k}:{a}", out[a:b],
                             ag_done(k, a, b))
            while init:
                tag, arr = init.popleft()
                nlink.publish(tag, arr, deadline)
            win.drain()
            self._end("allgather", "ring", x.nbytes, deadline, t0)
        return [out[k * sz:(k + 1) * sz].reshape(x.shape)
                for k in range(n)]

    def broadcast(self, tensor: Any, src: int) -> np.ndarray:
        x = _as_numpy(tensor)
        if self.world_size == 1:
            return x
        with self._op_lock:
            seq, deadline, t0 = self._begin()
            # the source decides the route and ships it (with shape/
            # dtype, and the ObjectRef on the store route) down a chain
            # of small header messages
            n, pos = self.world_size, (self.rank - src) % self.world_size
            nxt = (self.rank + 1) % n
            prv = (self.rank - 1) % n
            if self.rank == src:
                # the store route pays off when ranks are on OTHER
                # nodes (multi-source striped pulls, every completed
                # rank another source); a same-node-only group is
                # faster on the pipelined shm ring chain
                use_store = (
                    x.nbytes >= CONFIG.collective_bcast_store_min_bytes
                    and len(set(self._nodes.values())) > 1)
                ref = None
                if use_store:
                    import ray_tpu
                    ref = ray_tpu.put(np.ascontiguousarray(x))
                meta = (list(x.shape), x.dtype.str,
                        "store" if use_store else "ring",
                        pickle.dumps(ref) if use_store else b"")
                hdr = np.frombuffer(pickle.dumps(meta), np.uint8)
                self._small_send(nxt, f"{seq}:bch", hdr, deadline)
                algo = "store" if use_store else "ring"
                if use_store:
                    _M_BCAST_STORE.inc()
                    out = x
                else:
                    flat = np.ascontiguousarray(x).reshape(-1)
                    self._ring_broadcast_src(flat, seq, deadline)
                    out = x
            else:
                hdr = self._small_recv(prv, f"{seq}:bch", deadline)
                shape, dtype_str, route, refb = pickle.loads(
                    bytes(hdr))
                if nxt != src:
                    self._small_send(nxt, f"{seq}:bch", hdr, deadline)
                algo = route
                if route == "store":
                    _M_BCAST_STORE.inc()
                    out = self._bcast_pull(refb, shape, dtype_str,
                                           deadline)
                else:
                    size = int(np.prod(shape)) if shape else 1
                    flat = np.empty(size, np.dtype(dtype_str))
                    self._ring_broadcast_recv(flat, pos, seq, deadline)
                    out = flat.reshape(shape)
            if algo == "store":
                # keep the source's ref alive until every rank pulled
                # (each completed rank becomes an additional source for
                # the stripers behind it)
                self._rd_allreduce(np.zeros(1, np.float32), np.add, seq,
                                   deadline)
            self._end("broadcast", algo, x.nbytes, deadline, t0)
        return out

    def _bcast_pull(self, refb: bytes, shape, dtype_str,
                    deadline: float) -> np.ndarray:
        import ray_tpu
        ref = pickle.loads(refb)
        val = ray_tpu.get(ref, timeout=_remaining(deadline))
        out = np.array(val, copy=True)
        del val, ref
        return out.reshape(shape)

    def _ring_broadcast_src(self, flat: np.ndarray, seq: int,
                            deadline: float) -> None:
        nlink = self._link((self.rank + 1) % self.world_size)
        se = self._seg_elems_of(flat.itemsize)
        for a in range(0, flat.size, se):
            b = min(a + se, flat.size)
            nlink.publish(f"{seq}:bc:{a}", flat[a:b], deadline)

    def _ring_broadcast_recv(self, flat: np.ndarray, pos: int, seq: int,
                             deadline: float) -> None:
        """Pipelined chain forward: each landed segment is immediately
        republished to the next hop while later segments are still in
        flight."""
        n = self.world_size
        plink = self._link((self.rank - 1) % n)
        forward = pos < n - 1
        nlink = self._link((self.rank + 1) % n) if forward else None
        se = self._seg_elems_of(flat.itemsize)
        win = Window(CONFIG.collective_inflight_segments, deadline)
        for a in range(0, flat.size, se):
            b = min(a + se, flat.size)

            def done(arr, in_place, a=a, b=b):
                rng = flat[a:b]
                if not in_place:
                    np.copyto(rng, arr)
                if forward:
                    nlink.publish(f"{seq}:bc:{a}", rng, deadline)
            win.push(plink, f"{seq}:bc:{a}", flat[a:b], done)
        win.drain()

    def reduce(self, tensor: Any, dst: int,
               op: str = ReduceOp.SUM) -> np.ndarray:
        """Reduce to ``dst`` (windowed chunked star gather)."""
        x = _as_numpy(tensor)
        if self.world_size == 1:
            return x.copy()
        reducer = _REDUCERS[op]
        with self._op_lock:
            seq, deadline, t0 = self._begin()
            flat = np.ascontiguousarray(x).reshape(-1)
            se = self._seg_elems_of(flat.itemsize)
            segs = [(a, min(a + se, flat.size))
                    for a in range(0, flat.size, se)]
            if self.rank != dst:
                ln = self._link(dst)
                for a, b in segs:
                    ln.publish(f"{seq}:red{self.rank}:{a}", flat[a:b],
                               deadline)
                self._end("reduce", "gather", x.nbytes, deadline, t0)
                return x
            acc = np.array(flat, copy=True)
            win = Window(CONFIG.collective_inflight_segments, deadline)
            staging = _StagingPool(win.depth,
                                   min(se, max(1, flat.size)), flat.dtype)
            for a, b in segs:
                for r in range(self.world_size):
                    if r == dst:
                        continue

                    def done(arr, in_place, a=a, b=b):
                        rng = acc[a:b]
                        reducer(rng, arr, out=rng)
                    win.push(self._link(r), f"{seq}:red{r}:{a}",
                             staging.take(b - a), done)
            win.drain()
            self._end("reduce", "gather", x.nbytes, deadline, t0)
        return acc.reshape(x.shape)

    def send(self, tensor: Any, dst: int, tag: str = "p2p") -> None:
        # p2p deliberately stays on the push/mailbox path even for
        # same-node peers: it may run CONCURRENTLY with collectives
        # (no _op_lock), and the shm links' single-writer rings and
        # lock-free outbox pump are only safe under the op lock's
        # serialization.  The conn/mailbox path is thread-safe.
        x = _as_numpy(tensor)
        self._conn_to(dst).call(
            "msg", {"src": self.rank, "tag": tag, "data": x},
            timeout=CONFIG.collective_op_timeout_s)
        _M_TCP_BYTES.inc(x.nbytes)

    def recv(self, src: int, tag: str = "p2p") -> np.ndarray:
        data = self._mailbox.get(src, tag,
                                 CONFIG.collective_op_timeout_s)
        arr = _as_numpy(data)
        _M_TCP_BYTES.inc(arr.nbytes)
        return arr

    def barrier(self) -> None:
        self.allreduce(np.zeros(1, np.float32))

    # -------------------------------------------------- async (overlap)
    def allreduce_async(self, tensor: Any, op: str = ReduceOp.SUM,
                        quantize: Optional[str] = None) -> AsyncWork:
        """Enqueue an allreduce and return immediately with an
        :class:`AsyncWork` handle — the chained-completion API that
        lets a training step kick gradient sync for early buckets while
        later gradients are still being computed.

        Ops run on a single per-group worker thread in enqueue order,
        so every rank executes async collectives in the same sequence
        (the tag protocol requires cross-rank op-order agreement).
        Corollary: do NOT issue sync collectives on this group while
        async ops are in flight — fence with ``wait_all`` first.  The
        caller must not mutate ``tensor`` until the handle resolves."""
        h = AsyncWork()
        with self._async_lock:
            if self._destroyed.is_set():
                raise RuntimeError(f"group {self.name!r} destroyed")
            if self._async_q is None:
                self._async_q = queue.Queue()
                self._async_thread = threading.Thread(
                    target=self._async_main,
                    name=f"col-async-{self.name}", daemon=True)
                self._async_thread.start()
            self._async_q.put((tensor, op, quantize, h))
        return h

    def _async_main(self) -> None:
        while True:
            item = self._async_q.get()
            if item is None:
                return
            tensor, op, quantize, h = item
            try:
                h._finish(self.allreduce(tensor, op, quantize=quantize),
                          None)
            except BaseException as e:  # handle owns delivery
                h._finish(None, e)

    def destroy(self) -> None:
        self._destroyed.set()
        with self._async_lock:
            q, t = self._async_q, self._async_thread
            self._async_q = self._async_thread = None
        if q is not None:
            q.put(None)
            if t is not None:
                t.join(timeout=5.0)
            # fail anything still queued behind the sentinel so no
            # waiter blocks forever on a dead group
            while True:
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    break
                if item is not None:
                    item[3]._finish(None, RuntimeError(
                        f"group {self.name!r} destroyed"))
        try:
            gcs = self._worker.gcs
            base = f"collective/{self.name}"
            gcs.kv_del(f"{base}/{self.nonce}/{self.rank}")
            if self.rank == 0:
                # sweep the incarnation's remaining keys so a future
                # same-name group can't even see them — but only delete
                # the nonce key if it is still OURS: a newer same-name
                # incarnation may already have published its own, and
                # deleting that would wedge its joiners' nonce poll
                raw = gcs.kv_get(f"{base}/nonce")
                if raw is not None and raw.decode() == self.nonce:
                    gcs.kv_del(f"{base}/nonce")
                for k in gcs.kv_keys(f"{base}/{self.nonce}/"):
                    gcs.kv_del(k)
        except Exception:
            pass
        self._board.close()
        self._mailbox.close()
        if getattr(self, "_arena_inst", None) is not None:
            try:
                self._arena_inst.close()
            except Exception:
                pass
            self._arena_inst = None
        with self._links_lock:
            links, self._links = list(self._links.values()), {}
        for ln in links:
            try:
                ln.close()  # poisons shm rings: blocked peers unwind
            except Exception:
                pass
        with self._conns_lock:
            for conn in self._conns.values():
                try:
                    conn.close()
                except Exception:
                    pass
            self._conns.clear()
        self._server.stop()


# -------------------------------------------------------------- public API
def init_collective_group(world_size: int, rank: int,
                          backend: str = "dcn",
                          group_name: str = "default",
                          timeout: Optional[float] = None) -> None:
    """Join a collective group. Every participating process calls this with
    its own rank; returns once the full ring has rendezvoused.

    ``timeout`` bounds the rendezvous; None takes
    ``CONFIG.collective_rendezvous_timeout_s`` (a timeout_scale-scaled
    flag, so loaded CI boxes stretch the patience without per-call
    plumbing)."""
    if timeout is None:
        timeout = CONFIG.collective_rendezvous_timeout_s
    if backend not in ("dcn", "gloo", "ring"):
        raise ValueError(
            f"backend {backend!r} not supported; TPU in-graph collectives "
            "are compiled via pjit (see ray_tpu.util.collective.ici)")
    if not (0 <= rank < world_size):
        raise ValueError(f"rank {rank} out of range [0, {world_size})")
    with _groups_lock:
        if group_name in _groups:
            raise RuntimeError(f"group {group_name!r} already initialized")
        _groups[group_name] = _PENDING  # claim the slot atomically
    try:
        g = _Group(group_name, world_size, rank, timeout)
    except BaseException:
        with _groups_lock:
            if _groups.get(group_name) is _PENDING:
                del _groups[group_name]
        raise
    with _groups_lock:
        _groups[group_name] = g


def _get(group_name: str) -> _Group:
    with _groups_lock:
        g = _groups.get(group_name)
    if g is None or g is _PENDING:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized")
    return g


def is_group_initialized(group_name: str = "default") -> bool:
    with _groups_lock:
        g = _groups.get(group_name)
    return g is not None and g is not _PENDING


def destroy_collective_group(group_name: str = "default") -> None:
    with _groups_lock:
        g = _groups.get(group_name)
        if g is None or g is _PENDING:
            return
        del _groups[group_name]
    g.destroy()


def get_rank(group_name: str = "default") -> int:
    return _get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _get(group_name).world_size


def allreduce(tensor: Any, group_name: str = "default",
              op: str = ReduceOp.SUM,
              quantize: Optional[str] = None) -> np.ndarray:
    return _get(group_name).allreduce(tensor, op, quantize=quantize)


def allreduce_async(tensor: Any, group_name: str = "default",
                    op: str = ReduceOp.SUM,
                    quantize: Optional[str] = None) -> AsyncWork:
    """Non-blocking allreduce; see :meth:`_Group.allreduce_async`."""
    return _get(group_name).allreduce_async(tensor, op,
                                            quantize=quantize)


def wait_all(handles: Sequence[AsyncWork],
             timeout: Optional[float] = None) -> List[np.ndarray]:
    """Fence: block until every handle resolves, returning results in
    order.  The first failed op raises (after all have settled or the
    per-handle timeout lapses)."""
    return [h.result(timeout=timeout) for h in handles]


def register_ici_mesh(mesh, axis: str = "data",
                      group_name: str = "default") -> None:
    """Register a jax Mesh so topology-scheduled allreduces fold the
    intra-slice stage into one compiled in-graph psum
    (``util/collective/ici.py``) instead of host links.

    Contract: call on EVERY rank of the group (all ranks must reach
    the same schedule verdict); exactly one local device per process
    on ``axis``; SUM ops only (others keep the host schedule).  Pass
    ``mesh=None`` to deregister."""
    g = _get(group_name)
    if mesh is None:
        g._ici_reduce = None
        return
    g._ici_reduce = _mesh_psum_reducer(mesh, axis)


def _mesh_psum_reducer(mesh, axis: str):
    """Build the slice-sum callable the hierarchical schedule invokes:
    host fp32 vector in, psum-over-``axis`` vector out (every rank of
    the slice gets the sum, so host stages 1-2 are skipped)."""
    import jax

    from ray_tpu.util.collective import ici

    def _reduce(flat: np.ndarray) -> np.ndarray:
        dev = jax.local_devices()[0]
        x = jax.device_put(flat, dev)
        n = mesh.shape[axis]
        stacked = jax.make_array_from_single_device_arrays(
            (n,) + x.shape,
            jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(axis)),
            [x.reshape((1,) + x.shape)])
        return np.asarray(ici.psum(stacked, mesh, axis))

    return _reduce


def reduce(tensor: Any, dst_rank: int = 0, group_name: str = "default",
           op: str = ReduceOp.SUM) -> np.ndarray:
    return _get(group_name).reduce(tensor, dst_rank, op)


def broadcast(tensor: Any, src_rank: int = 0,
              group_name: str = "default") -> np.ndarray:
    return _get(group_name).broadcast(tensor, src_rank)


def allgather(tensor: Any, group_name: str = "default") -> List[np.ndarray]:
    return _get(group_name).allgather(tensor)


def reducescatter(tensor: Any, group_name: str = "default",
                  op: str = ReduceOp.SUM) -> np.ndarray:
    return _get(group_name).reducescatter(tensor, op)


def send(tensor: Any, dst_rank: int, group_name: str = "default") -> None:
    _get(group_name).send(tensor, dst_rank)


def recv(src_rank: int, group_name: str = "default") -> np.ndarray:
    return _get(group_name).recv(src_rank)


def barrier(group_name: str = "default") -> None:
    _get(group_name).barrier()
