"""A pool of actors processing a stream of tasks.

Analog of /root/reference/python/ray/util/actor_pool.py (ActorPool).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    """Round-robins work over a fixed set of actor handles.

    >>> pool = ActorPool([Worker.remote() for _ in range(4)])
    >>> list(pool.map(lambda a, v: a.double.remote(v), range(8)))
    """

    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._inflight_owner = {}
        self._submit_order_refs = {}
        self._submit_counter = 0
        self._deliver_counter = 0
        self._backlog: List[tuple] = []

    # ------------------------------------------------------------- mapping
    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterable[Any]:
        """Ordered map; yields results in submission order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]) -> Iterable[Any]:
        """Unordered map; yields results as they complete (faster when task
        durations vary)."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # ---------------------------------------------------------- scheduling
    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        if not self._idle and not self._inflight_owner \
                and not self._backlog:
            raise ValueError("cannot submit to an ActorPool with no actors")
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._inflight_owner[future] = (self._submit_counter, actor)
            self._submit_order_refs[self._submit_counter] = future
            self._submit_counter += 1
        else:
            self._backlog.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._inflight_owner) or bool(self._backlog)

    def _return_actor(self, actor) -> None:
        self._idle.append(actor)
        if self._backlog:
            self.submit(*self._backlog.pop(0))

    def get_next(self, timeout: float = None) -> Any:
        """Next result in submission order (skipping results already taken
        by :meth:`get_next_unordered`)."""
        if not self.has_next():
            raise StopIteration("no pending results")
        # indices assigned at submit time but absent from the map were
        # consumed by get_next_unordered: skip them
        while self._deliver_counter < self._submit_counter and \
                self._deliver_counter not in self._submit_order_refs:
            self._deliver_counter += 1
        future = self._submit_order_refs.get(self._deliver_counter)
        if future is None:
            # every indexed task was consumed; anything left is parked,
            # which with a non-empty pool implies in-flight futures exist —
            # so this means has_next() lied (defensive)
            raise StopIteration("no pending results")
        value = ray_tpu.get(future, timeout=timeout)
        del self._submit_order_refs[self._deliver_counter]
        self._deliver_counter += 1
        _, actor = self._inflight_owner.pop(future)
        self._return_actor(actor)
        return value

    def get_next_unordered(self, timeout: float = None) -> Any:
        """Any completed result (completion order)."""
        if not self._inflight_owner:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._inflight_owner),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result within timeout")
        future = ready[0]
        i, actor = self._inflight_owner.pop(future)
        del self._submit_order_refs[i]
        self._return_actor(actor)
        return ray_tpu.get(future)

    # --------------------------------------------------------------- admin
    def push(self, actor) -> None:
        """Add an idle actor to the pool."""
        self._return_actor(actor)

    def pop_idle(self):
        """Remove and return an idle actor, or None."""
        return self._idle.pop() if self._idle else None

    def has_free(self) -> bool:
        return bool(self._idle)
