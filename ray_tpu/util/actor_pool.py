"""A pool of actors processing a stream of tasks.

Analog of /root/reference/python/ray/util/actor_pool.py (ActorPool).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    """Round-robins work over a fixed set of actor handles.

    >>> pool = ActorPool([Worker.remote() for _ in range(4)])
    >>> list(pool.map(lambda a, v: a.double.remote(v), range(8)))
    """

    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List[tuple] = []

    # ------------------------------------------------------------- mapping
    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterable[Any]:
        """Ordered map; yields results in submission order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]) -> Iterable[Any]:
        """Unordered map; yields results as they complete (faster when task
        durations vary)."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # ---------------------------------------------------------- scheduling
    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        if not self._idle and not self._future_to_actor \
                and not self._pending_submits:
            raise ValueError("cannot submit to an ActorPool with no actors")
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending_submits)

    def _return_actor(self, actor) -> None:
        self._idle.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def get_next(self, timeout: float = None) -> Any:
        """Next result in submission order (skipping results already taken
        by :meth:`get_next_unordered`)."""
        if not self.has_next():
            raise StopIteration("no pending results")
        # indices assigned at submit time but absent from the map were
        # consumed by get_next_unordered: skip them
        while self._next_return_index < self._next_task_index and \
                self._next_return_index not in self._index_to_future:
            self._next_return_index += 1
        future = self._index_to_future.get(self._next_return_index)
        if future is None:
            # every indexed task was consumed; anything left is parked,
            # which with a non-empty pool implies in-flight futures exist —
            # so this means has_next() lied (defensive)
            raise StopIteration("no pending results")
        value = ray_tpu.get(future, timeout=timeout)
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        _, actor = self._future_to_actor.pop(future)
        self._return_actor(actor)
        return value

    def get_next_unordered(self, timeout: float = None) -> Any:
        """Any completed result (completion order)."""
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result within timeout")
        future = ready[0]
        i, actor = self._future_to_actor.pop(future)
        del self._index_to_future[i]
        self._return_actor(actor)
        return ray_tpu.get(future)

    # --------------------------------------------------------------- admin
    def push(self, actor) -> None:
        """Add an idle actor to the pool."""
        self._return_actor(actor)

    def pop_idle(self):
        """Remove and return an idle actor, or None."""
        return self._idle.pop() if self._idle else None

    def has_free(self) -> bool:
        return bool(self._idle)
