"""joblib backend running sklearn/joblib workloads on the cluster.

Analog of /root/reference/python/ray/util/joblib/ (register_ray +
ray_backend.RayBackend): `register_ray(); with joblib.parallel_backend
("ray_tpu"): ...` fans GridSearchCV etc. out as cluster tasks.
"""

from __future__ import annotations

__all__ = ["register_ray"]


def register_ray() -> None:
    """Register the "ray_tpu" joblib parallel backend."""
    try:
        from joblib.parallel import register_parallel_backend
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "joblib is required for the ray_tpu joblib backend") from e
    from ray_tpu.util.joblib.backend import RayTpuBackend
    register_parallel_backend("ray_tpu", RayTpuBackend)
