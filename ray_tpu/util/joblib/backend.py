"""joblib ParallelBackend over ray_tpu tasks.

Cite: /root/reference/python/ray/util/joblib/ray_backend.py (RayBackend
subclasses MultiprocessingBackend and plugs its pool in). Same trick here:
we substitute our cluster Pool for the local process pool.
"""

from __future__ import annotations

from joblib._parallel_backends import MultiprocessingBackend
from joblib.pool import PicklingPool

import ray_tpu
from ray_tpu.util.multiprocessing import Pool


class RayTpuBackend(MultiprocessingBackend):
    """`joblib.parallel_backend("ray_tpu")` — tasks instead of processes."""

    supports_timeout = True

    def effective_n_jobs(self, n_jobs: int) -> int:
        eff = super().effective_n_jobs(n_jobs)
        if n_jobs == -1:
            eff = max(1, int(ray_tpu.cluster_resources().get("CPU", 1))) \
                if ray_tpu.is_initialized() else eff
        return eff

    def configure(self, n_jobs: int = 1, parallel=None, prefer=None,
                  require=None, **memmapping_pool_args):
        n_jobs = self.effective_n_jobs(n_jobs)
        # joblib's memmapping args target local /dev/shm pools; our pool
        # ships args through the object store instead, so they are dropped.
        self._pool = _JoblibPool(n_jobs)
        self.parallel = parallel
        return n_jobs

    def terminate(self) -> None:
        if self._pool is not None:
            self._pool.terminate()
            self._pool = None


class _JoblibPool(Pool):
    """Adapts our Pool to the subset of the PicklingPool API joblib uses."""

    def __init__(self, processes: int):
        super().__init__(processes=processes)

    def apply_async(self, func, args=(), kwds=None, callback=None,
                    error_callback=None):
        # joblib passes a zero-arg BatchedCalls callable
        return super().apply_async(func, args, kwds, callback=callback,
                                   error_callback=error_callback)

    # joblib probes this attr on cleanup
    _temp_folder = None


# referenced so the import is exercised (joblib internals move around)
_ = PicklingPool
