"""Scheduling strategies for tasks and actors.

Analog of /root/reference/python/ray/util/scheduling_strategies.py
(PlacementGroupSchedulingStrategy :15, NodeAffinitySchedulingStrategy :41).

Strategies are plain declarative objects; the core worker encodes them into
the lease protocol (a placement-group bundle pins the lease to the bundle's
reserved pool on its node; node affinity pins the lease to one raylet).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional, Union

if TYPE_CHECKING:
    from ray_tpu.util.placement_group import PlacementGroup


@dataclass
class PlacementGroupSchedulingStrategy:
    """Schedule onto a reserved placement-group bundle.

    ``placement_group_bundle_index == -1`` means "any bundle that fits".
    """

    placement_group: "PlacementGroup"
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False

    def _encode(self) -> dict:
        idx = int(self.placement_group_bundle_index)
        n = self.placement_group.bundle_count
        if idx < -1 or idx >= n:
            raise ValueError(
                f"placement_group_bundle_index {idx} out of range for a "
                f"{n}-bundle placement group")
        return {
            "type": "placement_group",
            "pg_id": self.placement_group.id.hex(),
            "bundle_index": idx,
        }


@dataclass
class NodeAffinitySchedulingStrategy:
    """Pin to a specific node. ``soft=True`` falls back to the default
    policy when the node can't take it."""

    node_id: str
    soft: bool = False

    def _encode(self) -> dict:
        return {"type": "node_affinity", "node_id": self.node_id,
                "soft": bool(self.soft)}


SchedulingStrategyT = Union[
    None, str, PlacementGroupSchedulingStrategy, NodeAffinitySchedulingStrategy]


def encode_strategy(strategy: SchedulingStrategyT) -> Optional[dict]:
    """Normalize a strategy object to the wire dict the core worker uses."""
    if strategy is None or strategy == "DEFAULT":
        return None
    if isinstance(strategy, str):
        if strategy == "SPREAD":
            return {"type": "spread"}
        raise ValueError(f"unknown scheduling strategy {strategy!r}")
    return strategy._encode()
