"""Client server: hosts remote drivers over the control-plane RPC layer.

Analog of the reference's Ray Client server (/root/reference/python/ray/util/
client/server/, proxier.py; wire protocol ray_client.proto:324
``RayletDriver``): a thin process inside the cluster that executes
put/get/wait/task/actor calls on behalf of drivers connecting from outside
(laptops, notebooks).  One shared embedded driver serves every client
connection; per-SESSION registries pin ObjectRefs/actor handles.  A clean
``bye`` releases everything immediately; an abrupt connection loss keeps
the session alive for ``reconnect_grace_s`` so the client can reconnect
and keep its refs (reference client reconnect, test_client_reconnect.py),
and a per-session request-id reply cache makes retried RPCs exactly-once
across the reconnect.

Run standalone:  ``python -m ray_tpu.util.client.server --port 10001``
(connects to the latest local session, or pass ``--address host:port``).
"""

from __future__ import annotations

import cloudpickle
import pickle
import threading
import uuid
from collections import deque
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private import rpc


class _Ref:
    """Wire tag for a client-held object ref inside pickled args."""

    def __init__(self, ref_id: str):
        self.ref_id = ref_id

    def __reduce__(self):
        return (_Ref, (self.ref_id,))


class _ActorRef:
    """Wire tag for a client-held actor handle inside pickled args."""

    def __init__(self, actor_id: str):
        self.actor_id = actor_id

    def __reduce__(self):
        return (_ActorRef, (self.actor_id,))


def _map_structure(value, fn):
    """Resolve wire tags recursively through plain containers (tags buried
    inside arbitrary user objects are not found — same as the reference)."""
    if isinstance(value, (_Ref, _ActorRef)):
        return fn(value)
    if isinstance(value, (list, tuple)):
        return type(value)(_map_structure(v, fn) for v in value)
    if isinstance(value, dict):
        return {k: _map_structure(v, fn) for k, v in value.items()}
    return value


class ClientServer:
    """Serves client drivers; embeds (or joins) a cluster as their proxy."""

    def __init__(self, address: Optional[str] = None, host: str = "0.0.0.0",
                 port: int = 10001, reconnect_grace_s: float = 30.0,
                 **init_kwargs):
        import ray_tpu
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address, **init_kwargs)
        self._lock = threading.Lock()
        self.reconnect_grace_s = reconnect_grace_s
        # session_id -> {refs, actors, replies, reply_order, conn, timer}
        self._sessions: Dict[str, Dict[str, Any]] = {}
        self._conn_session: Dict[rpc.Connection, str] = {}
        self._server = rpc.Server(self._handle, host=host, port=port,
                                  on_disconnect=self._disconnected)
        self.address: Tuple[str, int] = self._server.address

    # ------------------------------------------------------------- sessions
    def _session(self, conn) -> Dict[str, Any]:
        with self._lock:
            sid = self._conn_session.get(conn)
            if sid is None:
                # pre-hello caller (or a legacy client): anonymous
                # session fate-shared with this one connection
                sid = f"anon-{id(conn):x}"
                self._conn_session[conn] = sid
            return self._ensure_session(sid, conn)

    def _ensure_session(self, sid: str, conn) -> Dict[str, Any]:
        # _lock held
        sess = self._sessions.get(sid)
        if sess is None:
            sess = {"refs": {}, "actors": {}, "replies": {},
                    "reply_order": deque(),
                    "conn": conn, "timer": None}
            self._sessions[sid] = sess
        return sess

    def _conn_refs(self, conn) -> Dict[str, Any]:
        return self._session(conn)["refs"]

    def _register(self, conn, ref) -> str:
        rid = uuid.uuid4().hex
        self._conn_refs(conn)[rid] = ref
        return rid

    def _resolve(self, conn, value):
        refs = self._conn_refs(conn)

        def one(tag):
            if isinstance(tag, _ActorRef):
                return self._actor(conn, tag.actor_id)
            try:
                return refs[tag.ref_id]
            except KeyError:
                raise rpc.RpcError(f"unknown ref {tag.ref_id[:8]}")
        return _map_structure(value, one)

    def _drop_session(self, sid: str) -> None:
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is None or sess["conn"] is not None:
                return  # reconnected during the grace window
            del self._sessions[sid]
            self._forget_conns(sid)

    def _forget_conns(self, sid: str) -> None:
        # _lock held: drop dead conn->sid bindings of this session
        for c in [c for c, s in self._conn_session.items() if s == sid]:
            del self._conn_session[c]

    def _disconnected(self, conn) -> None:
        # NOTE: the conn->sid binding is kept — a handler still running
        # on this connection must keep resolving to the right session
        # (registering into a fresh anonymous one would strand the refs
        # its cached reply hands back). Bindings drop with the session.
        with self._lock:
            sid = self._conn_session.get(conn)
            sess = self._sessions.get(sid) if sid else None
            if sess is None or sess["conn"] is not conn:
                return
            sess["conn"] = None
            if sid.startswith("anon-"):
                # legacy connection-scoped session: no reconnect identity
                del self._sessions[sid]
                self._forget_conns(sid)
                return
            # keep refs/actors for the grace window so a reconnecting
            # client finds them again
            t = threading.Timer(self.reconnect_grace_s,
                                self._drop_session, args=(sid,))
            t.daemon = True
            sess["timer"] = t
            t.start()

    # ------------------------------------------------------------- handlers
    _REPLY_CACHE_MAX_BYTES = 256 * 1024

    @staticmethod
    def _reply_size(out: Any) -> int:
        if isinstance(out, dict):
            return sum(len(v) for v in out.values()
                       if isinstance(v, (bytes, bytearray)))
        return 0

    def _handle(self, conn, method: str, p: Any) -> Any:
        p = p or {}
        req = p.get("_req")
        if req is None:
            return getattr(self, f"_rpc_{method}")(conn, p)
        sess = self._session(conn)
        while True:
            with self._lock:
                prior = sess["replies"].get(req)
                if prior is None:
                    # mark in flight so a retry racing this execution waits
                    # instead of re-executing (exactly-once when the
                    # original completes; see absent-entry case below)
                    inflight = threading.Event()
                    sess["replies"][req] = inflight
                    break
            if not isinstance(prior, threading.Event):
                return prior
            prior.wait(timeout=120)
            with self._lock:
                done = sess["replies"].get(req)
            if done is None:
                # entry vanished: the original raised (its error went to a
                # connection that is gone) or its reply was too big to pin
                # (only the idempotent get) — re-execute rather than hand
                # the client a bogus None reply
                continue
            if not isinstance(done, threading.Event):
                return done
            raise rpc.RpcError("retried request still executing")
        try:
            out = getattr(self, f"_rpc_{method}")(conn, p)
        except BaseException:
            with self._lock:
                sess["replies"].pop(req, None)
            inflight.set()
            raise
        with self._lock:
            # huge replies (multi-MB gets) are not worth pinning; the
            # only RPC with big replies is the idempotent get
            if self._reply_size(out) <= self._REPLY_CACHE_MAX_BYTES:
                sess["replies"][req] = out
                sess["reply_order"].append(req)
                while len(sess["reply_order"]) > 512:
                    sess["replies"].pop(sess["reply_order"].popleft(),
                                        None)
            else:
                sess["replies"].pop(req, None)
        inflight.set()
        return out

    def _rpc_hello(self, conn, p):
        """Bind this connection to a client session (new or resumed)."""
        sid = p["session_id"]
        with self._lock:
            # a reconnecting session's previous conns are dead: drop their
            # bindings now (not at session end) or each reconnect leaks one
            for c in [c for c, s in self._conn_session.items()
                      if s == sid and c is not conn and c.closed]:
                del self._conn_session[c]
            self._conn_session[conn] = sid
            sess = self._ensure_session(sid, conn)
            sess["conn"] = conn
            if sess["timer"] is not None:
                sess["timer"].cancel()
                sess["timer"] = None
        return {"ok": True}

    def _rpc_bye(self, conn, p):
        """Clean disconnect: release the session's refs immediately."""
        with self._lock:
            sid = self._conn_session.get(conn)
            if sid:
                self._sessions.pop(sid, None)
                self._forget_conns(sid)
        return {"ok": True}

    def _rpc_put(self, conn, p):
        import ray_tpu
        ref = ray_tpu.put(pickle.loads(p["data"]))
        return {"ref_id": self._register(conn, ref)}

    def _rpc_get(self, conn, p):
        import ray_tpu
        refs = [self._resolve(conn, _Ref(r)) for r in p["ref_ids"]]
        values = ray_tpu.get(refs, timeout=p.get("timeout"))
        values = [self._wrap_value(conn, v) for v in values]
        return {"data": cloudpickle.dumps(values)}

    def _wrap_value(self, conn, value):
        """Dynamic-return generators carry server-side ObjectRefs the client
        cannot resolve; register each and ship a marker of client ref ids."""
        from ray_tpu.runtime.core_worker import ObjectRefGenerator
        if isinstance(value, ObjectRefGenerator):
            return {"__client_ref_generator__":
                    [self._register(conn, r) for r in value]}
        return value

    def _rpc_wait(self, conn, p):
        import ray_tpu
        id_of = {id(v): rid for rid, v in self._conn_refs(conn).items()}
        refs = [self._resolve(conn, _Ref(r)) for r in p["ref_ids"]]
        ready, pending = ray_tpu.wait(refs,
                                      num_returns=p.get("num_returns", 1),
                                      timeout=p.get("timeout"))
        return {"ready": [id_of[id(r)] for r in ready],
                "pending": [id_of[id(r)] for r in pending]}

    def _rpc_task(self, conn, p):
        import ray_tpu
        fn = pickle.loads(p["func"])
        args = self._resolve(conn, pickle.loads(p["args"]))
        kwargs = self._resolve(conn, pickle.loads(p["kwargs"]))
        remote_fn = ray_tpu.remote(fn)
        if p.get("options"):
            remote_fn = remote_fn.options(**p["options"])
        out = remote_fn.remote(*args, **kwargs)
        refs = out if isinstance(out, list) else [out]
        return {"ref_ids": [self._register(conn, r) for r in refs]}

    def _rpc_create_actor(self, conn, p):
        import ray_tpu
        cls = pickle.loads(p["cls"])
        args = self._resolve(conn, pickle.loads(p["args"]))
        kwargs = self._resolve(conn, pickle.loads(p["kwargs"]))
        actor_cls = ray_tpu.remote(cls)
        if p.get("options"):
            actor_cls = actor_cls.options(**p["options"])
        handle = actor_cls.remote(*args, **kwargs)
        aid = uuid.uuid4().hex
        self._session(conn)["actors"][aid] = handle
        return {"actor_id": aid}

    def _actor(self, conn, aid):
        handle = self._session(conn)["actors"].get(aid)
        if handle is None:
            raise rpc.RpcError(f"unknown actor {aid[:8]}")
        return handle

    def _rpc_actor_call(self, conn, p):
        handle = self._actor(conn, p["actor_id"])
        args = self._resolve(conn, pickle.loads(p["args"]))
        kwargs = self._resolve(conn, pickle.loads(p["kwargs"]))
        ref = getattr(handle, p["method"]).remote(*args, **kwargs)
        return {"ref_id": self._register(conn, ref)}

    def _rpc_kill_actor(self, conn, p):
        import ray_tpu
        ray_tpu.kill(self._actor(conn, p["actor_id"]))
        self._session(conn)["actors"].pop(p["actor_id"], None)
        return {}

    def _rpc_nodes(self, conn, p):
        import ray_tpu
        return {"nodes": ray_tpu.nodes()}

    def _rpc_cluster_info(self, conn, p):
        import ray_tpu
        return {"nodes": len(ray_tpu.nodes()),
                "resources": ray_tpu.cluster_resources()}

    def stop(self) -> None:
        self._server.stop()


def main() -> None:
    import argparse
    import time
    parser = argparse.ArgumentParser(description="ray_tpu client server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=10001)
    parser.add_argument("--address", default="auto",
                        help="cluster GCS address (default: latest session)")
    args = parser.parse_args()
    server = ClientServer(address=args.address, host=args.host,
                          port=args.port)
    print(f"client server listening on {server.address[0]}:{server.address[1]}",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
