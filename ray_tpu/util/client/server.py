"""Client server: hosts remote drivers over the control-plane RPC layer.

Analog of the reference's Ray Client server (/root/reference/python/ray/util/
client/server/, proxier.py; wire protocol ray_client.proto:324
``RayletDriver``): a thin process inside the cluster that executes
put/get/wait/task/actor calls on behalf of drivers connecting from outside
(laptops, notebooks).  One shared embedded driver serves every client
connection; per-connection registries pin ObjectRefs/actor handles so a
client disconnect releases everything it created.

Run standalone:  ``python -m ray_tpu.util.client.server --port 10001``
(connects to the latest local session, or pass ``--address host:port``).
"""

from __future__ import annotations

import cloudpickle
import pickle
import threading
import uuid
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private import rpc


class _Ref:
    """Wire tag for a client-held object ref inside pickled args."""

    def __init__(self, ref_id: str):
        self.ref_id = ref_id

    def __reduce__(self):
        return (_Ref, (self.ref_id,))


class _ActorRef:
    """Wire tag for a client-held actor handle inside pickled args."""

    def __init__(self, actor_id: str):
        self.actor_id = actor_id

    def __reduce__(self):
        return (_ActorRef, (self.actor_id,))


def _map_structure(value, fn):
    """Resolve wire tags recursively through plain containers (tags buried
    inside arbitrary user objects are not found — same as the reference)."""
    if isinstance(value, (_Ref, _ActorRef)):
        return fn(value)
    if isinstance(value, (list, tuple)):
        return type(value)(_map_structure(v, fn) for v in value)
    if isinstance(value, dict):
        return {k: _map_structure(v, fn) for k, v in value.items()}
    return value


class ClientServer:
    """Serves client drivers; embeds (or joins) a cluster as their proxy."""

    def __init__(self, address: Optional[str] = None, host: str = "0.0.0.0",
                 port: int = 10001, **init_kwargs):
        import ray_tpu
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address, **init_kwargs)
        self._lock = threading.Lock()
        # per-connection state: refs and actor handles created by the client
        self._refs: Dict[rpc.Connection, Dict[str, Any]] = {}
        self._actors: Dict[rpc.Connection, Dict[str, Any]] = {}
        self._server = rpc.Server(self._handle, host=host, port=port,
                                  on_disconnect=self._disconnected)
        self.address: Tuple[str, int] = self._server.address

    # ------------------------------------------------------------- plumbing
    def _conn_refs(self, conn) -> Dict[str, Any]:
        with self._lock:
            return self._refs.setdefault(conn, {})

    def _register(self, conn, ref) -> str:
        rid = uuid.uuid4().hex
        self._conn_refs(conn)[rid] = ref
        return rid

    def _resolve(self, conn, value):
        refs = self._conn_refs(conn)

        def one(tag):
            if isinstance(tag, _ActorRef):
                return self._actor(conn, tag.actor_id)
            try:
                return refs[tag.ref_id]
            except KeyError:
                raise rpc.RpcError(f"unknown ref {tag.ref_id[:8]}")
        return _map_structure(value, one)

    def _disconnected(self, conn) -> None:
        with self._lock:
            self._refs.pop(conn, None)
            self._actors.pop(conn, None)

    # ------------------------------------------------------------- handlers
    def _handle(self, conn, method: str, p: Any) -> Any:
        return getattr(self, f"_rpc_{method}")(conn, p or {})

    def _rpc_put(self, conn, p):
        import ray_tpu
        ref = ray_tpu.put(pickle.loads(p["data"]))
        return {"ref_id": self._register(conn, ref)}

    def _rpc_get(self, conn, p):
        import ray_tpu
        refs = [self._resolve(conn, _Ref(r)) for r in p["ref_ids"]]
        values = ray_tpu.get(refs, timeout=p.get("timeout"))
        values = [self._wrap_value(conn, v) for v in values]
        return {"data": cloudpickle.dumps(values)}

    def _wrap_value(self, conn, value):
        """Dynamic-return generators carry server-side ObjectRefs the client
        cannot resolve; register each and ship a marker of client ref ids."""
        from ray_tpu.runtime.core_worker import ObjectRefGenerator
        if isinstance(value, ObjectRefGenerator):
            return {"__client_ref_generator__":
                    [self._register(conn, r) for r in value]}
        return value

    def _rpc_wait(self, conn, p):
        import ray_tpu
        id_of = {id(v): rid for rid, v in self._conn_refs(conn).items()}
        refs = [self._resolve(conn, _Ref(r)) for r in p["ref_ids"]]
        ready, pending = ray_tpu.wait(refs,
                                      num_returns=p.get("num_returns", 1),
                                      timeout=p.get("timeout"))
        return {"ready": [id_of[id(r)] for r in ready],
                "pending": [id_of[id(r)] for r in pending]}

    def _rpc_task(self, conn, p):
        import ray_tpu
        fn = pickle.loads(p["func"])
        args = self._resolve(conn, pickle.loads(p["args"]))
        kwargs = self._resolve(conn, pickle.loads(p["kwargs"]))
        remote_fn = ray_tpu.remote(fn)
        if p.get("options"):
            remote_fn = remote_fn.options(**p["options"])
        out = remote_fn.remote(*args, **kwargs)
        refs = out if isinstance(out, list) else [out]
        return {"ref_ids": [self._register(conn, r) for r in refs]}

    def _rpc_create_actor(self, conn, p):
        import ray_tpu
        cls = pickle.loads(p["cls"])
        args = self._resolve(conn, pickle.loads(p["args"]))
        kwargs = self._resolve(conn, pickle.loads(p["kwargs"]))
        actor_cls = ray_tpu.remote(cls)
        if p.get("options"):
            actor_cls = actor_cls.options(**p["options"])
        handle = actor_cls.remote(*args, **kwargs)
        aid = uuid.uuid4().hex
        with self._lock:
            self._actors.setdefault(conn, {})[aid] = handle
        return {"actor_id": aid}

    def _actor(self, conn, aid):
        with self._lock:
            handle = self._actors.get(conn, {}).get(aid)
        if handle is None:
            raise rpc.RpcError(f"unknown actor {aid[:8]}")
        return handle

    def _rpc_actor_call(self, conn, p):
        handle = self._actor(conn, p["actor_id"])
        args = self._resolve(conn, pickle.loads(p["args"]))
        kwargs = self._resolve(conn, pickle.loads(p["kwargs"]))
        ref = getattr(handle, p["method"]).remote(*args, **kwargs)
        return {"ref_id": self._register(conn, ref)}

    def _rpc_kill_actor(self, conn, p):
        import ray_tpu
        ray_tpu.kill(self._actor(conn, p["actor_id"]))
        with self._lock:
            self._actors.get(conn, {}).pop(p["actor_id"], None)
        return {}

    def _rpc_nodes(self, conn, p):
        import ray_tpu
        return {"nodes": ray_tpu.nodes()}

    def _rpc_cluster_info(self, conn, p):
        import ray_tpu
        return {"nodes": len(ray_tpu.nodes()),
                "resources": ray_tpu.cluster_resources()}

    def stop(self) -> None:
        self._server.stop()


def main() -> None:
    import argparse
    import time
    parser = argparse.ArgumentParser(description="ray_tpu client server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=10001)
    parser.add_argument("--address", default="auto",
                        help="cluster GCS address (default: latest session)")
    args = parser.parse_args()
    server = ClientServer(address=args.address, host=args.host,
                          port=args.port)
    print(f"client server listening on {server.address[0]}:{server.address[1]}",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
