"""Ray-Client-style remote driver: drive a cluster from outside it.

Analog of /root/reference/python/ray/util/client (``ray://`` protocol,
ray_client.proto:324, client worker.py): ``ray_tpu.init(address=
"client://host:port")`` routes the public API — remote functions, actors,
put/get/wait — through a thin RPC connection to a ClientServer running inside
the cluster (ray_tpu/util/client/server.py), so laptops and notebooks can
drive TPU clusters without being cluster nodes themselves.

Object refs on this side are ``ClientObjectRef`` handles (ids into the
server's per-connection registry); passing one back into a task/actor call
re-resolves it server-side, so data never round-trips through the client.
"""

from __future__ import annotations

import cloudpickle
import pickle
import threading
from typing import Any, Optional, Sequence, Tuple, Union

from ray_tpu._private import rpc
from ray_tpu.util.client.server import (ClientServer,  # noqa: F401
                                        _ActorRef, _Ref)

_lock = threading.Lock()
_ctx: Optional["ClientContext"] = None


class ClientObjectRef:
    def __init__(self, ctx: "ClientContext", ref_id: str):
        self._ctx = ctx
        self.ref_id = ref_id

    def __repr__(self):
        return f"ClientObjectRef({self.ref_id[:8]})"

    def __reduce__(self):
        # pickles into the wire tag the server resolves to the real ref
        return (_Ref, (self.ref_id,))


class ClientObjectRefGenerator:
    """Client-side view of a num_returns="dynamic" result."""

    def __init__(self, ctx: "ClientContext", ref_ids):
        self._refs = [ClientObjectRef(ctx, rid) for rid in ref_ids]

    def __iter__(self):
        return iter(self._refs)

    def __len__(self):
        return len(self._refs)

    def __getitem__(self, i):
        return self._refs[i]

    def __repr__(self):
        return f"ClientObjectRefGenerator({len(self._refs)} refs)"


class ClientActorMethod:
    def __init__(self, handle: "ClientActorHandle", name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        ctx = self._handle._ctx
        r = ctx._call("actor_call", {
            "actor_id": self._handle._actor_id, "method": self._name,
            "args": ctx._dumps(args), "kwargs": ctx._dumps(kwargs)})
        return ClientObjectRef(ctx, r["ref_id"])


class ClientActorHandle:
    def __init__(self, ctx: "ClientContext", actor_id: str):
        self._ctx = ctx
        self._actor_id = actor_id

    def __reduce__(self):
        # ships as a wire tag the server resolves to the real handle, so
        # client actor handles can be passed into tasks/actor calls
        return (_ActorRef, (self._actor_id,))

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return ClientActorMethod(self, name)


class ClientRemoteFunction:
    def __init__(self, ctx: "ClientContext", func, options: Optional[dict] = None):
        self._ctx = ctx
        self._func = func
        self._options = dict(options or {})

    def remote(self, *args, **kwargs):
        r = self._ctx._call("task", {
            "func": cloudpickle.dumps(self._func),
            "args": self._ctx._dumps(args),
            "kwargs": self._ctx._dumps(kwargs),
            "options": self._options})
        refs = [ClientObjectRef(self._ctx, rid) for rid in r["ref_ids"]]
        return refs[0] if len(refs) == 1 else refs

    def options(self, **opts) -> "ClientRemoteFunction":
        return ClientRemoteFunction(self._ctx, self._func,
                                    {**self._options, **opts})


class ClientActorClass:
    def __init__(self, ctx: "ClientContext", cls, options: Optional[dict] = None):
        self._ctx = ctx
        self._cls = cls
        self._options = dict(options or {})

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        r = self._ctx._call("create_actor", {
            "cls": cloudpickle.dumps(self._cls),
            "args": self._ctx._dumps(args),
            "kwargs": self._ctx._dumps(kwargs),
            "options": self._options})
        return ClientActorHandle(self._ctx, r["actor_id"])

    def options(self, **opts) -> "ClientActorClass":
        return ClientActorClass(self._ctx, self._cls,
                                {**self._options, **opts})


class ClientContext:
    """One connection to a ClientServer; the client-mode API surface.

    Survives connection drops: the context holds a session id, the
    server keeps the session's refs for a reconnect grace window, and
    ``_call`` transparently reconnects and retries — each RPC carries a
    request id the server dedups, so retries are exactly-once
    (reference client reconnect + reply caching, dataclient.py)."""

    def __init__(self, address: Tuple[str, int],
                 reconnect_grace_s: float = 30.0):
        import uuid as _uuid
        self.address = address
        self.session_id = _uuid.uuid4().hex
        self.reconnect_grace_s = reconnect_grace_s
        self._conn_lock = threading.Lock()
        self._conn = self._connect()

    def _connect(self) -> rpc.Connection:
        conn = rpc.connect(self.address)
        conn.call("hello", {"session_id": self.session_id}, timeout=10)
        return conn

    def _call(self, method: str, payload: dict) -> Any:
        import time as _time
        import uuid as _uuid
        payload = dict(payload, _req=_uuid.uuid4().hex)
        deadline = _time.monotonic() + self.reconnect_grace_s
        while True:
            conn = self._conn
            try:
                if conn.closed:
                    raise ConnectionError("client connection closed")
                return conn.call(method, payload)
            except (ConnectionError, OSError):
                if _time.monotonic() >= deadline:
                    raise
                try:
                    conn.close()
                except Exception:
                    pass
                with self._conn_lock:
                    stale = self._conn is conn or self._conn.closed
                if not stale:
                    continue   # another thread already reconnected
                # dial OUTSIDE the lock: other threads' calls must not
                # queue behind this thread's connect timeout
                try:
                    fresh = self._connect()
                except (ConnectionError, OSError):
                    _time.sleep(0.5)
                    continue
                with self._conn_lock:
                    if self._conn is conn or self._conn.closed:
                        self._conn = fresh
                    else:
                        try:
                            fresh.close()
                        except Exception:
                            pass

    @staticmethod
    def _dumps(value: Any) -> bytes:
        # ClientObjectRef.__reduce__ turns embedded refs into wire tags
        return cloudpickle.dumps(value)

    # ---------------------------------------------------------- public API
    def remote(self, obj, **options):
        if isinstance(obj, type):
            return ClientActorClass(self, obj, options)
        return ClientRemoteFunction(self, obj, options)

    def put(self, value: Any) -> ClientObjectRef:
        r = self._call("put", {"data": cloudpickle.dumps(value)})
        return ClientObjectRef(self, r["ref_id"])

    def get(self, refs: Union[ClientObjectRef, Sequence[ClientObjectRef]],
            timeout: Optional[float] = None) -> Any:
        single = isinstance(refs, ClientObjectRef)
        ref_list = [refs] if single else list(refs)
        r = self._call("get", {"ref_ids": [x.ref_id for x in ref_list],
                               "timeout": timeout})
        values = [self._unwrap(v) for v in pickle.loads(r["data"])]
        return values[0] if single else values

    def _unwrap(self, value):
        if isinstance(value, dict) and "__client_ref_generator__" in value:
            return ClientObjectRefGenerator(
                self, value["__client_ref_generator__"])
        return value

    def wait(self, refs: Sequence[ClientObjectRef], *, num_returns: int = 1,
             timeout: Optional[float] = None):
        by_id = {x.ref_id: x for x in refs}
        if len(by_id) != len(list(refs)):
            raise ValueError("wait() requires a list of unique object refs")
        r = self._call("wait", {"ref_ids": list(by_id),
                                "num_returns": num_returns,
                                "timeout": timeout})
        return ([by_id[i] for i in r["ready"]],
                [by_id[i] for i in r["pending"]])

    def kill(self, actor: ClientActorHandle) -> None:
        self._call("kill_actor", {"actor_id": actor._actor_id})

    def nodes(self) -> list:
        return self._call("nodes", {})["nodes"]

    def cluster_info(self) -> dict:
        return self._call("cluster_info", {})

    def disconnect(self) -> None:
        try:
            # clean goodbye: the server releases our refs immediately
            # instead of waiting out the reconnect grace window
            self._conn.call("bye", {}, timeout=5)
        except Exception:
            pass
        self._conn.close()


def connect(address: Union[str, Tuple[str, int]]) -> ClientContext:
    """Connect to a ClientServer.  Accepts "host:port", "client://host:port",
    or a (host, port) tuple; installs the context as the active client so the
    top-level ``ray_tpu.get/put/wait/remote`` delegate to it."""
    global _ctx
    if isinstance(address, str):
        address = address.removeprefix("client://").removeprefix("ray://")
        host, _, port = address.rpartition(":")
        address = (host or "127.0.0.1", int(port))
    with _lock:
        if _ctx is not None:
            raise RuntimeError("client already connected; disconnect() first")
        _ctx = ClientContext(tuple(address))
    return _ctx


def current() -> Optional[ClientContext]:
    return _ctx


def disconnect() -> None:
    global _ctx
    with _lock:
        if _ctx is not None:
            _ctx.disconnect()
            _ctx = None
