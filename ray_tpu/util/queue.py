"""Distributed FIFO queue backed by an actor.

Analog of /root/reference/python/ray/util/queue.py (Queue, Empty, Full).
"""

from __future__ import annotations

import queue as stdlib_queue
import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self._q = stdlib_queue.Queue(maxsize=maxsize)

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()

    def full(self) -> bool:
        return self._q.full()

    def put(self, item: Any, block: bool, timeout: Optional[float]) -> bool:
        try:
            self._q.put(item, block=block, timeout=timeout)
            return True
        except stdlib_queue.Full:
            return False

    def get(self, block: bool, timeout: Optional[float]):
        try:
            return True, self._q.get(block=block, timeout=timeout)
        except stdlib_queue.Empty:
            return False, None

    def put_nowait_batch(self, items: List[Any]) -> bool:
        if self._q.maxsize > 0 and \
                self._q.qsize() + len(items) > self._q.maxsize:
            return False
        for item in items:
            self._q.put_nowait(item)
        return True

    def get_nowait_batch(self, num_items: int):
        if self._q.qsize() < num_items:
            return False, []
        return True, [self._q.get_nowait() for _ in range(num_items)]

    def shutdown(self) -> None:
        pass


class Queue:
    """Cluster-wide FIFO queue; handles are picklable and usable from any
    task or actor."""

    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        self.maxsize = maxsize
        self.actor = ray_tpu.remote(**opts)(_QueueActor).remote(maxsize)

    def __reduce__(self):
        return (_rebuild_queue, (self.maxsize, self.actor))

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def size(self) -> int:
        return self.qsize()

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            if not ray_tpu.get(self.actor.put.remote(item, False, None)):
                raise Full
            return
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            chunk = 0.2 if deadline is None \
                else min(0.2, max(0.0, deadline - time.monotonic()))
            ok = ray_tpu.get(self.actor.put.remote(item, True, chunk))
            if ok:
                return
            if deadline is not None and time.monotonic() >= deadline:
                raise Full

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        if not ray_tpu.get(self.actor.put_nowait_batch.remote(list(items))):
            raise Full

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self.actor.get.remote(False, None))
            if not ok:
                raise Empty
            return item
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            chunk = 0.2 if deadline is None \
                else min(0.2, max(0.0, deadline - time.monotonic()))
            ok, item = ray_tpu.get(self.actor.get.remote(True, chunk))
            if ok:
                return item
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        ok, items = ray_tpu.get(
            self.actor.get_nowait_batch.remote(num_items))
        if not ok:
            raise Empty
        return items

    def shutdown(self) -> None:
        if self.actor is not None:
            ray_tpu.kill(self.actor)
            self.actor = None


def _rebuild_queue(maxsize, actor):
    q = Queue.__new__(Queue)
    q.maxsize = maxsize
    q.actor = actor
    return q
