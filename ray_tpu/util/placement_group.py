"""Placement groups: gang reservation of resource bundles across nodes.

Analog of /root/reference/python/ray/util/placement_group.py
(PlacementGroup :33, placement_group() :128); server side is the GCS
2-phase bundle reservation (cf. gcs_placement_group_scheduler.h).

TPU-first addition: a bundle may carry a ``tpu-slice`` resource, and the
GCS packer treats slice bundles as atomic — all bundles of one group land
on hosts of a single slice (SURVEY.md §2.6 "pod-slice-aware bundles").
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private.ids import PlacementGroupID
from ray_tpu.runtime.core_worker import get_global_worker

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    """Handle to a (possibly still pending) placement group."""

    def __init__(self, pg_id: PlacementGroupID,
                 bundles: Optional[List[Dict[str, float]]] = None):
        self.id = pg_id
        self._bundles = bundles

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        if self._bundles is None:
            info = self._table()
            self._bundles = info["bundles"] if info else []
        return self._bundles

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def _table(self) -> Optional[dict]:
        worker = get_global_worker()
        return worker.gcs.call("get_placement_group",
                               {"pg_id": self.id.hex()})

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        """Block until all bundles are reserved (or timeout). cf.
        PlacementGroup.wait (reference placement_group.py:60)."""
        deadline = time.monotonic() + timeout_seconds
        while True:
            info = self._table()
            if info and info["state"] == "CREATED":
                return True
            if info is None or info["state"] == "REMOVED":
                return False
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.05)

    def ready(self):
        """ObjectRef that resolves when the group is placed (ray parity:
        ``ray.get(pg.ready())``)."""
        from ray_tpu.remote_function import RemoteFunction

        def _ready(pg_id_hex: str):
            worker = get_global_worker()
            while True:
                info = worker.gcs.call("get_placement_group",
                                       {"pg_id": pg_id_hex})
                if info is None or info["state"] == "REMOVED":
                    raise RuntimeError("placement group removed")
                if info["state"] == "CREATED":
                    return True
                time.sleep(0.05)

        fn = RemoteFunction(_ready, num_cpus=0)
        return fn.remote(self.id.hex())

    def __reduce__(self):
        return (PlacementGroup, (self.id, self._bundles))

    def __repr__(self):
        return f"PlacementGroup({self.id.hex()[:12]})"


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "",
                    lifetime: Optional[str] = None) -> PlacementGroup:
    """Reserve ``bundles`` across the cluster; returns immediately with a
    handle (use ``.wait()`` / ``.ready()``)."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    for b in bundles:
        if not b or any(v < 0 for v in b.values()):
            raise ValueError(f"invalid bundle {b!r}")
    worker = get_global_worker()
    pg_id = PlacementGroupID.from_random()
    worker.gcs.call("create_placement_group", {
        "pg_id": pg_id.hex(),
        "bundles": [dict(b) for b in bundles],
        "strategy": strategy,
        "name": name,
        "lifetime": lifetime or "",
        "job_id": worker.job_id.hex(),
    })
    return PlacementGroup(pg_id, [dict(b) for b in bundles])


def remove_placement_group(pg: PlacementGroup) -> None:
    """Release all bundles (outstanding leases drain back to the node)."""
    get_global_worker().gcs.call("remove_placement_group",
                                 {"pg_id": pg.id.hex()})


def placement_group_table(pg: Optional[PlacementGroup] = None) -> dict:
    """Debug table of one or all placement groups (cf. reference
    placement_group_table)."""
    worker = get_global_worker()
    if pg is not None:
        info = worker.gcs.call("get_placement_group", {"pg_id": pg.id.hex()})
        return {pg.id.hex(): info} if info else {}
    return worker.gcs.call("list_placement_groups", {}) or {}


def get_placement_group(name: str) -> PlacementGroup:
    """Look up a named placement group."""
    worker = get_global_worker()
    table = worker.gcs.call("list_placement_groups", {}) or {}
    for pgid, info in table.items():
        if info.get("name") == name and info["state"] != "REMOVED":
            return PlacementGroup(PlacementGroupID.from_hex(pgid),
                                  info["bundles"])
    raise ValueError(f"no placement group named {name!r}")
