"""Pool implementation over ray_tpu tasks.

Cite: /root/reference/python/ray/util/multiprocessing/pool.py (Pool,
AsyncResult, chunking logic). Design difference: the reference runs a pool
of PoolActor processes; here chunks are plain stateless tasks — idiomatic
for a lease-reusing scheduler (workers are pooled by the raylet anyway),
and it inherits task retries for free. `processes` bounds the number of
chunks in flight, preserving multiprocessing's concurrency/memory cap.
"""

from __future__ import annotations

import multiprocessing as _mp
import threading
from typing import Any, Callable, Iterable, Iterator, List, Optional

import ray_tpu


def _run_chunk(fn, chunk, star):
    if star:
        return [fn(*item) for item in chunk]
    return [fn(item) for item in chunk]


# Per-worker-process record of pools whose initializer already ran: the
# chunk task below is a module function, so workers share this global and
# each worker runs a pool's initializer exactly once (stdlib semantics).
_initialized_pools: set = set()


def _run_chunk_with_init(pool_id, initializer, initargs, fn, chunk, star):
    if pool_id not in _initialized_pools:
        initializer(*initargs)
        _initialized_pools.add(pool_id)
    return _run_chunk(fn, chunk, star)


def _window(task, fn, chunks: Iterator[list], star: bool,
            max_inflight: int) -> Iterator[Any]:
    """Submit chunks (a lazy iterator) with at most `max_inflight`
    outstanding; yield chunk results in order."""
    results: dict = {}
    inflight: dict = {}  # ref -> index
    next_submit = 0
    next_yield = 0
    exhausted = False
    chunks = iter(chunks)
    while not exhausted or inflight or next_yield in results:
        while not exhausted and len(inflight) < max_inflight:
            try:
                chunk = next(chunks)
            except StopIteration:
                exhausted = True
                break
            inflight[task.remote(fn, chunk, star)] = next_submit
            next_submit += 1
        while next_yield in results:
            yield results.pop(next_yield)
            next_yield += 1
        if not inflight:
            if exhausted and next_yield not in results:
                break
            continue
        done, _ = ray_tpu.wait(list(inflight), num_returns=1)
        idx = inflight.pop(done[0])
        results[idx] = ray_tpu.get(done[0])


class AsyncResult:
    """Matches multiprocessing.pool.AsyncResult's get/wait/ready/successful."""

    def __init__(self, collect: Callable[[], Any],
                 callback: Optional[Callable] = None,
                 error_callback: Optional[Callable] = None,
                 pool: Optional["Pool"] = None):
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()
        self._callback = callback
        self._error_callback = error_callback
        self._pool = pool
        if pool is not None:
            pool._outstanding.add(self)
        threading.Thread(target=self._collect, args=(collect,),
                         daemon=True).start()

    def _collect(self, collect) -> None:
        try:
            self._result = collect()
            if self._callback is not None:
                self._callback(self._result)
        except BaseException as e:  # noqa: BLE001 - surfaced via get()
            self._error = e
            if self._error_callback is not None:
                self._error_callback(e)
        finally:
            self._done.set()
            if self._pool is not None:
                self._pool._outstanding.discard(self)

    def wait(self, timeout: Optional[float] = None) -> None:
        self._done.wait(timeout)

    def ready(self) -> bool:
        return self._done.is_set()

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        return self._error is None

    def get(self, timeout: Optional[float] = None) -> Any:
        if not self._done.wait(timeout):
            # drop-in callers catch multiprocessing.TimeoutError
            raise _mp.TimeoutError("result not ready within timeout")
        if self._error is not None:
            raise self._error
        return self._result


class Pool:
    """``with Pool(8) as p: p.map(f, xs)`` — cluster-wide.

    `processes` bounds in-flight chunks (defaults to cluster CPU count);
    `ray_remote_args` forwards @remote options (resources, retries, ...).
    """

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = (),
                 ray_remote_args: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            processes = max(1, int(
                ray_tpu.cluster_resources().get("CPU", 1)))
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._processes = processes
        self._closed = False
        self._outstanding: set = set()
        remote_args = dict(ray_remote_args or {})
        if initializer is not None:
            import uuid
            pool_id = uuid.uuid4().hex
            import functools
            body = functools.partial(_run_chunk_with_init, pool_id,
                                     initializer, initargs)
        else:
            body = _run_chunk
        self._task = ray_tpu.remote(**remote_args)(body) \
            if remote_args else ray_tpu.remote(body)

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        """Blocks until all outstanding async work has completed."""
        if not self._closed:
            raise ValueError("Pool is still running")
        for r in list(self._outstanding):
            r.wait()

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()

    def _check_running(self) -> None:
        if self._closed:
            raise ValueError("Pool not running")

    # ------------------------------------------------------------- chunking
    def _chunks(self, iterable: Iterable,
                chunksize: Optional[int]) -> List[list]:
        items = list(iterable)
        if chunksize is None:
            chunksize, extra = divmod(len(items), self._processes * 4)
            if extra:
                chunksize += 1
            chunksize = max(1, chunksize)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)]

    @staticmethod
    def _lazy_chunks(iterable: Iterable, chunksize: int) -> Iterator[list]:
        """Chunk without materializing (imap over generators/streams)."""
        buf: List[Any] = []
        for item in iterable:
            buf.append(item)
            if len(buf) >= chunksize:
                yield buf
                buf = []
        if buf:
            yield buf

    def _gather(self, fn, iterable, chunksize, star=False) -> List[Any]:
        chunks = self._chunks(iterable, chunksize)
        out: List[Any] = []
        for chunk_result in _window(self._task, fn, chunks, star,
                                    self._processes):
            out.extend(chunk_result)
        return out

    # ----------------------------------------------------------------- api
    def apply(self, fn: Callable, args: tuple = (), kwds: dict = None) -> Any:
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: dict = None, callback=None,
                    error_callback=None) -> AsyncResult:
        self._check_running()
        kwds = kwds or {}
        ref = self._task.remote(lambda _: fn(*args, **kwds), [None], False)
        return AsyncResult(lambda: ray_tpu.get(ref)[0],
                           callback=callback, error_callback=error_callback,
                           pool=self)

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        self._check_running()
        return self._gather(fn, iterable, chunksize)

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None, callback=None,
                  error_callback=None) -> AsyncResult:
        self._check_running()
        return AsyncResult(
            lambda: self._gather(fn, iterable, chunksize),
            callback=callback, error_callback=error_callback, pool=self)

    def starmap(self, fn: Callable, iterable: Iterable[tuple],
                chunksize: Optional[int] = None) -> List[Any]:
        self._check_running()
        return self._gather(fn, iterable, chunksize, star=True)

    def starmap_async(self, fn: Callable, iterable: Iterable[tuple],
                      chunksize: Optional[int] = None) -> AsyncResult:
        self._check_running()
        return AsyncResult(
            lambda: self._gather(fn, iterable, chunksize, star=True),
            pool=self)

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: int = 1) -> Iterator[Any]:
        self._check_running()  # eager, like stdlib — not on first next()

        def gen():
            chunks = self._lazy_chunks(iterable, chunksize)
            for chunk_result in _window(self._task, fn, chunks, False,
                                        self._processes):
                yield from chunk_result
        return gen()

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: int = 1) -> Iterator[Any]:
        self._check_running()

        def gen():
            inflight = {}
            it = self._lazy_chunks(iterable, chunksize)
            exhausted = False
            while inflight or not exhausted:
                while not exhausted and len(inflight) < self._processes:
                    try:
                        chunk = next(it)
                    except StopIteration:
                        exhausted = True
                        break
                    inflight[self._task.remote(fn, chunk, False)] = True
                if not inflight:
                    break
                done, _ = ray_tpu.wait(list(inflight), num_returns=1)
                del inflight[done[0]]
                yield from ray_tpu.get(done[0])
        return gen()
