"""Drop-in ``multiprocessing.Pool`` backed by cluster tasks.

Analog of /root/reference/python/ray/util/multiprocessing/ (Pool): same
surface (apply/apply_async/map/map_async/starmap/imap/imap_unordered),
but work is scheduled as ray_tpu tasks, so a Pool transparently spans the
whole cluster instead of one host.
"""

from ray_tpu.util.multiprocessing.pool import Pool, AsyncResult  # noqa: F401

__all__ = ["Pool", "AsyncResult"]
