"""Serializability inspector.

Analog of /root/reference/python/ray/util/check_serialize.py
(inspect_serializability): walks an object's closure/attributes to pinpoint
which inner object actually fails to pickle, instead of one opaque error.
"""

from __future__ import annotations

import inspect
from typing import Any, Optional, Set, Tuple

import cloudpickle

_printer_indent = 0


def _check(obj: Any, name: str, depth: int, failures: Set[str],
           seen: Set[int]) -> bool:
    if id(obj) in seen:
        return True
    seen.add(id(obj))
    try:
        cloudpickle.dumps(obj)
        return True
    except Exception as e:  # noqa: BLE001 - any pickling error counts
        if depth <= 0:
            failures.add(f"{name}: {type(obj).__name__} ({e})")
            return False
    found_inner = False
    # closures
    if inspect.isfunction(obj):
        closure = obj.__closure__ or ()
        names = obj.__code__.co_freevars
        for var, cell in zip(names, closure):
            try:
                inner = cell.cell_contents
            except ValueError:
                continue
            if not _check(inner, f"{name}.<closure>.{var}", depth - 1,
                          failures, seen):
                found_inner = True
        for var, val in (obj.__globals__ or {}).items():
            if var in obj.__code__.co_names and \
                    not inspect.ismodule(val) and _is_suspect(val):
                if not _check(val, f"{name}.<global>.{var}", depth - 1,
                              failures, seen):
                    found_inner = True
    # instance attributes
    elif hasattr(obj, "__dict__"):
        for attr, val in vars(obj).items():
            if not _check(val, f"{name}.{attr}", depth - 1, failures, seen):
                found_inner = True
    elif isinstance(obj, (list, tuple, set)):
        for i, val in enumerate(obj):
            if not _check(val, f"{name}[{i}]", depth - 1, failures, seen):
                found_inner = True
    elif isinstance(obj, dict):
        for k, val in obj.items():
            if not _check(val, f"{name}[{k!r}]", depth - 1, failures, seen):
                found_inner = True
    if not found_inner:
        failures.add(f"{name}: {type(obj).__name__}")
    return False


def _is_suspect(val: Any) -> bool:
    import threading
    return isinstance(val, (threading.Lock().__class__,
                            threading.RLock().__class__)) or \
        inspect.isgenerator(val) or hasattr(val, "fileno")


def inspect_serializability(obj: Any, name: Optional[str] = None,
                            depth: int = 3,
                            print_file=None) -> Tuple[bool, Set[str]]:
    """Returns (serializable, failure descriptions)."""
    name = name or getattr(obj, "__name__", type(obj).__name__)
    failures: Set[str] = set()
    ok = _check(obj, name, depth, failures, set())
    if not ok and print_file is not None:
        print(f"{name} is NOT serializable:", file=print_file)
        for f in sorted(failures):
            print(f"  - {f}", file=print_file)
    return ok, failures
