"""Remote pdb: break inside a task/actor and attach from another terminal.

Analog of /root/reference/python/ray/util/rpdb.py (set_trace + the
`ray debug` attach flow): ``ray_tpu.util.rpdb.set_trace()`` in worker code
opens a telnet-able pdb on a free port and registers
host:port in the GCS KV under ``RAY_PDB:<task_id>``; attach with
``python -m ray_tpu.scripts debug`` or plain ``nc host port``.
"""

from __future__ import annotations

import pdb
import socket
import sys
from typing import List, Tuple


class _SocketIO:
    def __init__(self, conn: socket.socket):
        self._file = conn.makefile("rw", buffering=1)

    def readline(self):
        return self._file.readline()

    def read(self, *a):
        return self._file.read(*a)

    def write(self, data):
        self._file.write(data)

    def flush(self):
        self._file.flush()


class RemotePdb(pdb.Pdb):
    def __init__(self, conn: socket.socket):
        io = _SocketIO(conn)
        super().__init__(stdin=io, stdout=io)
        self.use_rawinput = False


def set_trace(breakpoint_uuid: str = "") -> None:
    """Block the current worker on a socket pdb session."""
    from ray_tpu.runtime import core_worker as cw
    worker = cw._global_worker

    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    # bind all interfaces; advertise the address this worker is reachable
    # at cluster-wide (its RPC host), not loopback
    server.bind(("0.0.0.0", 0))
    server.listen(1)
    port = server.getsockname()[1]
    host = worker.address[0] if worker is not None else "127.0.0.1"

    key = None
    conn = None
    try:
        if worker is not None:
            tid = worker.current_task_id.hex()
            key = f"RAY_PDB:{breakpoint_uuid or tid}"
            worker.gcs.kv_put(key, f"{host}:{port}".encode())
        print(f"ray_tpu debugger waiting on {host}:{port} "
              f"(attach: nc {host} {port})", file=sys.stderr, flush=True)
        conn, _ = server.accept()
        dbg = RemotePdb(conn)
        dbg.reset()  # initializes bdb state (botframe) for interaction()
        # Blocking interaction at this frame: inspect stack/locals, then
        # `c` (or n/s) resumes the task.  Post-resume line stepping is not
        # supported — the session ends when interaction returns, so the
        # sockets can be closed deterministically (no fd leak per hit).
        dbg.interaction(sys._getframe().f_back, None)
    finally:
        if worker is not None and key:
            try:
                worker.gcs.kv_del(key)
            except Exception:
                pass
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
        server.close()


def list_breakpoints() -> List[Tuple[str, str]]:
    """Active (id, host:port) debugger sessions, from the driver."""
    from ray_tpu.runtime import core_worker as cw
    gcs = cw.get_global_worker().gcs
    out = []
    for key in gcs.kv_keys("RAY_PDB:"):
        val = gcs.kv_get(key)
        if val:
            out.append((key[len("RAY_PDB:"):], val.decode()))
    return out
