"""ray_tpu.util: utility patterns on top of the task/actor core.

Analog of /root/reference/python/ray/util/ (actor_pool.py, queue.py,
placement_group.py, scheduling_strategies.py, collective/).
"""

from ray_tpu.util.actor_group import ActorGroup  # noqa: F401
from ray_tpu.util.actor_pool import ActorPool  # noqa: F401
from ray_tpu.util.check_serialize import inspect_serializability  # noqa: F401
from ray_tpu.util.placement_group import (  # noqa: F401
    PlacementGroup, get_placement_group, placement_group,
    placement_group_table, remove_placement_group)
from ray_tpu.util.queue import Empty, Full, Queue  # noqa: F401
from ray_tpu.util.scheduling_strategies import (  # noqa: F401
    NodeAffinitySchedulingStrategy, PlacementGroupSchedulingStrategy)

__all__ = [
    "ActorPool", "ActorGroup", "inspect_serializability",
    "Queue", "Empty", "Full",
    "PlacementGroup", "placement_group", "remove_placement_group",
    "placement_group_table", "get_placement_group",
    "PlacementGroupSchedulingStrategy", "NodeAffinitySchedulingStrategy",
]
