"""Parallel iterators over actor-hosted shards.

Analog of /root/reference/python/ray/util/iter.py (from_items :20,
from_range, from_iterators, ParallelIterator, LocalIterator): a
ParallelIterator holds N shard actors, each lazily evaluating a chain of
transforms over its local stream; gather_sync/gather_async pull the shards
back to the driver.
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, List, TypeVar

import ray_tpu

T = TypeVar("T")
U = TypeVar("U")


def _apply_transforms(it: Iterator, transforms) -> Iterator:
    """The one interpreter for the transform chain (shard- and
    driver-side use the same dispatch so they can never diverge)."""
    for kind, fn in transforms:
        if kind == "for_each":
            it = map(fn, it)
        elif kind == "filter":
            it = filter(fn, it)
        elif kind == "flatten":
            it = (x for batch in it for x in batch)
        elif kind == "batch":
            it = _batched(it, fn)
        else:
            raise ValueError(f"unknown transform kind {kind!r}")
    return it


@ray_tpu.remote
class _ShardActor:
    """Owns one shard's item stream and applies the transform chain."""

    def __init__(self, items_fn, transforms):
        self._items_fn = items_fn
        self._transforms = list(transforms)
        self._it = None

    def reset(self):
        self._it = _apply_transforms(iter(self._items_fn()),
                                     self._transforms)
        return True

    def next_batch(self, n: int):
        """Returns (items, done)."""
        if self._it is None:
            self.reset()
        out = []
        for _ in range(n):
            try:
                out.append(next(self._it))
            except StopIteration:
                return out, True
        return out, False


def _reap(actors) -> None:
    """Free shard actors (and their CPU leases) as soon as a gather ends."""
    for a in actors:
        try:
            ray_tpu.kill(a)
        except Exception:
            pass


def _batched(it: Iterator, n: int) -> Iterator[list]:
    buf = []
    for x in it:
        buf.append(x)
        if len(buf) >= n:
            yield buf
            buf = []
    if buf:
        yield buf


class ParallelIterator:
    """Lazy, sharded iterator; transforms run inside the shard actors."""

    def __init__(self, items_fns: List[Callable[[], Iterable]],
                 transforms: List[tuple] = None, name: str = "iter"):
        self._items_fns = items_fns
        self._transforms = list(transforms or [])
        self.name = name

    def __repr__(self):
        return f"ParallelIterator[{self.name}, {self.num_shards()} shards]"

    def num_shards(self) -> int:
        return len(self._items_fns)

    def _with(self, kind: str, fn) -> "ParallelIterator":
        return ParallelIterator(self._items_fns,
                                self._transforms + [(kind, fn)],
                                name=f"{self.name}.{kind}()")

    def for_each(self, fn: Callable[[T], U]) -> "ParallelIterator":
        return self._with("for_each", fn)

    def filter(self, fn: Callable[[T], bool]) -> "ParallelIterator":
        return self._with("filter", fn)

    def batch(self, n: int) -> "ParallelIterator":
        return self._with("batch", n)

    def flatten(self) -> "ParallelIterator":
        return self._with("flatten", None)

    def _make_actors(self):
        actors = [_ShardActor.remote(fn, self._transforms)
                  for fn in self._items_fns]
        ray_tpu.get([a.reset.remote() for a in actors])
        return actors

    def gather_sync(self, batch: int = 64) -> Iterator:
        """Round-robin over shards, in order, until all exhaust."""
        actors = self._make_actors()
        try:
            live = {i: a for i, a in enumerate(actors)}
            while live:
                for i in list(live):
                    items, done = ray_tpu.get(
                        live[i].next_batch.remote(batch))
                    yield from items
                    if done:
                        del live[i]
        finally:
            _reap(actors)

    def gather_async(self, batch: int = 64) -> Iterator:
        """Yield from whichever shard responds first."""
        actors = self._make_actors()
        try:
            pending = {a.next_batch.remote(batch): a for a in actors}
            while pending:
                done, _ = ray_tpu.wait(list(pending), num_returns=1)
                actor = pending.pop(done[0])
                items, exhausted = ray_tpu.get(done[0])
                yield from items
                if not exhausted:
                    pending[actor.next_batch.remote(batch)] = actor
        finally:
            _reap(actors)

    def take(self, n: int) -> List:
        out = []
        gen = self.gather_sync()
        try:
            for x in gen:
                out.append(x)
                if len(out) >= n:
                    break
        finally:
            gen.close()  # frees the shard actors immediately
        return out

    def show(self, n: int = 20) -> None:
        for x in self.take(n):
            print(x)

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        if self._transforms or other._transforms:
            # materialize transform chains into the item fns so a union of
            # differently-transformed iterators stays correct
            return _materialized(self).union(_materialized(other))
        return ParallelIterator(self._items_fns + other._items_fns,
                                name=f"{self.name}+{other.name}")


def _materialized(it: ParallelIterator) -> ParallelIterator:
    fns = []
    for items_fn in it._items_fns:
        def make(fn=items_fn, transforms=tuple(it._transforms)):
            return lambda: _apply_transforms(iter(fn()), transforms)
        fns.append(make())
    return ParallelIterator(fns, name=it.name)


def from_items(items: List[T], num_shards: int = 2,
               repeat: bool = False) -> ParallelIterator:
    shards: List[List] = [[] for _ in range(num_shards)]
    for i, item in enumerate(items):
        shards[i % num_shards].append(item)

    def make(shard):
        if repeat:
            def gen():
                while True:
                    yield from shard
            return gen
        return lambda: list(shard)
    return ParallelIterator([make(s) for s in shards], name="from_items")


def from_range(n: int, num_shards: int = 2,
               repeat: bool = False) -> ParallelIterator:
    return from_items(list(range(n)), num_shards=num_shards, repeat=repeat)


def from_iterators(generators: List[Callable[[], Iterable]],
                   name: str = "from_iterators") -> ParallelIterator:
    return ParallelIterator(list(generators), name=name)


__all__ = ["ParallelIterator", "from_items", "from_range", "from_iterators"]
