"""User-defined metrics: Counter / Gauge / Histogram.

Analog of /root/reference/python/ray/util/metrics.py (Counter:155,
Histogram:220, Gauge:295). Metrics are pushed to the GCS KV under
``metrics/<name>/<worker>`` so any process (dashboard, tests) can read a
cluster-wide snapshot; a Prometheus scrape endpoint is served by the
dashboard module.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu.runtime.core_worker import get_global_worker

_FLUSH_PERIOD_S = 1.0


class _MetricBase:
    _TYPE = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Tuple[str, ...]] = None):
        if not name:
            raise ValueError("metric name must be non-empty")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}
        self._dirty = False
        self._last_flush = 0.0

    def set_default_tags(self, tags: Dict[str, str]):
        for k in tags:
            if k not in self._tag_keys:
                raise ValueError(f"unknown tag key {k!r}")
        self._default_tags = dict(tags)
        return self

    def _tagkey(self, tags: Optional[Dict[str, str]]
                ) -> Tuple[Tuple[str, str], ...]:
        merged = dict(self._default_tags)
        if tags:
            for k in tags:
                if k not in self._tag_keys:
                    raise ValueError(f"unknown tag key {k!r}")
            merged.update(tags)
        return tuple(sorted(merged.items()))

    def _record(self, value: float, tags: Optional[Dict[str, str]],
                mode: str) -> None:
        key = self._tagkey(tags)
        with self._lock:
            if mode == "add":
                self._values[key] = self._values.get(key, 0.0) + value
            else:
                self._values[key] = value
            self._dirty = True
        self._maybe_flush()

    def _maybe_flush(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if not self._dirty or \
                    (not force and now - self._last_flush < _FLUSH_PERIOD_S):
                return
            snapshot = {json.dumps(dict(k)):
                        (dict(v, buckets=dict(v["buckets"]))
                         if isinstance(v, dict) else v)
                        for k, v in self._values.items()}
            self._dirty = False
            self._last_flush = now
        try:
            worker = get_global_worker()
            worker.gcs.kv_put(
                f"metrics/{self._name}/{worker.worker_id.hex()[:12]}",
                json.dumps({
                    "type": self._TYPE,
                    "description": self._description,
                    "values": snapshot,
                    "ts": time.time(),
                }).encode())
        except Exception:
            pass  # metrics must never take down the app

    def flush(self) -> None:
        self._maybe_flush(force=True)


class Counter(_MetricBase):
    """Monotonically increasing value."""

    _TYPE = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value <= 0:
            raise ValueError("counter increments must be positive")
        self._record(value, tags, "add")


class Gauge(_MetricBase):
    """Point-in-time value."""

    _TYPE = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        self._record(value, tags, "set")


class Histogram(_MetricBase):
    """Distribution over configured boundaries.

    Each tag set stores ``{"buckets": {le: count}, "sum", "count"}`` —
    the shared histogram wire format (also used by the runtime-metrics
    layer, _private/runtime_metrics.py) that the dashboard renders as
    conformant Prometheus ``<name>_bucket{le=...}`` (cumulative, with
    ``+Inf``) plus ``<name>_count``/``<name>_sum`` series, instead of
    the old raw per-bucket counts with an ``le`` tag on the bare name."""

    _TYPE = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Optional[Tuple[str, ...]] = None):
        super().__init__(name, description, tag_keys)
        if not boundaries or any(b <= 0 for b in boundaries):
            raise ValueError("histogram needs positive boundaries")
        self._boundaries = sorted(boundaries)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        bucket = next((repr(float(b)) for b in self._boundaries
                       if value <= b), "+Inf")
        key = self._tagkey(tags)
        with self._lock:
            rec = self._values.get(key)
            if not isinstance(rec, dict):
                rec = self._values[key] = {"buckets": {}, "sum": 0.0,
                                           "count": 0}
            rec["buckets"][bucket] = rec["buckets"].get(bucket, 0) + 1
            rec["sum"] += value
            rec["count"] += 1
            self._dirty = True
        self._maybe_flush()


def query_metrics(prefix: str = "") -> Dict[str, dict]:
    """Cluster-wide metric snapshot from the GCS KV (for tests/dashboard)."""
    worker = get_global_worker()
    out: Dict[str, dict] = {}
    for key in worker.gcs.kv_keys("metrics/" + prefix):
        raw = worker.gcs.kv_get(key)
        if raw:
            out[key[len("metrics/"):]] = json.loads(raw.decode())
    return out
