"""ActorGroup: homogeneous gang of actors addressed as one unit.

Analog of /root/reference/python/ray/util/actor_group.py (ActorGroup):
create N identical actors, broadcast method calls, gather results.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import ray_tpu


class ActorGroupMethod:
    def __init__(self, group: "ActorGroup", name: str):
        self._group = group
        self._name = name

    def remote(self, *args, **kwargs) -> List[Any]:
        """Invoke on every member; returns one ObjectRef per member."""
        return [getattr(a, self._name).remote(*args, **kwargs)
                for a in self._group._actors]


class ActorGroup:
    def __init__(self, actor_cls, num_actors: int, *init_args,
                 resources_per_actor: Optional[Dict[str, float]] = None,
                 **init_kwargs):
        if num_actors < 1:
            raise ValueError("num_actors must be >= 1")
        opts = {}
        if resources_per_actor:
            res = dict(resources_per_actor)
            opts["num_cpus"] = res.pop("CPU", 1.0)
            if "TPU" in res:
                opts["num_tpus"] = res.pop("TPU")
            if res:
                opts["resources"] = res
        if not hasattr(actor_cls, "remote"):
            actor_cls = ray_tpu.remote(actor_cls)
        if opts:
            actor_cls = actor_cls.options(**opts)
        self._actors = [actor_cls.remote(*init_args, **init_kwargs)
                        for _ in range(num_actors)]

    def __len__(self) -> int:
        return len(self._actors)

    def __getattr__(self, name: str) -> ActorGroupMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorGroupMethod(self, name)

    @property
    def actors(self) -> List[Any]:
        return list(self._actors)

    def execute(self, method: str, *args, **kwargs) -> List[Any]:
        """Call + gather on all members."""
        return ray_tpu.get(
            ActorGroupMethod(self, method).remote(*args, **kwargs))

    def shutdown(self) -> None:
        for a in self._actors:
            ray_tpu.kill(a)
        self._actors = []
