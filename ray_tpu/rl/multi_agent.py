"""Multi-agent RL: MultiAgentEnv API + independent-PPO training.

Analog of /root/reference/rllib/env/multi_agent_env.py (dict-keyed
obs/reward/termination with the "__all__" convention) and the
policy-mapping machinery of rllib/policy/policy_map.py: each agent maps
to a policy id via ``policy_mapping_fn``; policies with multiple mapped
agents learn from their pooled experience (parameter sharing). Training
is independent PPO per policy — each policy's update is the same
mesh-jitted clipped-surrogate step the single-agent PPO uses.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.algorithm import AlgorithmConfig
from ray_tpu.rl.env import CartPoleEnv, Env
from ray_tpu.rl.sample_batch import SampleBatch, compute_gae

__all_done__ = "__all__"


class MultiAgentEnv:
    """reset() -> (obs_dict, infos); step(action_dict) ->
    (obs, rewards, terminateds, truncateds, infos), all keyed by agent id;
    ``terminateds["__all__"]`` ends the episode."""

    agent_ids: List[str] = []
    observation_spaces: Dict[str, Any] = {}
    action_spaces: Dict[str, Any] = {}

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        raise NotImplementedError

    def step(self, actions: Dict[str, Any]):
        raise NotImplementedError

    def close(self) -> None:
        pass


class MultiAgentCartPole(MultiAgentEnv):
    """N independent CartPoles, one per agent (the reference's standard
    multi-agent smoke env, rllib/examples/env/multi_agent.py)."""

    def __init__(self, num_agents: int = 2, max_steps: int = 200):
        self.agent_ids = [f"agent_{i}" for i in range(num_agents)]
        self._envs: Dict[str, Env] = {
            aid: CartPoleEnv(max_steps=max_steps) for aid in self.agent_ids}
        self.observation_spaces = {
            aid: e.observation_space for aid, e in self._envs.items()}
        self.action_spaces = {
            aid: e.action_space for aid, e in self._envs.items()}
        self._done: Dict[str, bool] = {}

    def reset(self, *, seed: Optional[int] = None):
        obs = {}
        for i, (aid, e) in enumerate(self._envs.items()):
            o, _ = e.reset(seed=None if seed is None else seed + i)
            obs[aid] = o
        self._done = {aid: False for aid in self.agent_ids}
        return obs, {}

    def step(self, actions: Dict[str, Any]):
        obs, rews, terms, truncs, infos = {}, {}, {}, {}, {}
        for aid, act in actions.items():
            if self._done.get(aid, True):
                continue
            o, r, term, trunc, info = self._envs[aid].step(act)
            obs[aid], rews[aid] = o, r
            terms[aid], truncs[aid], infos[aid] = term, trunc, info
            if term or trunc:
                self._done[aid] = True
        terms[__all_done__] = all(self._done.values())
        truncs[__all_done__] = False
        return obs, rews, terms, truncs, infos

    def close(self):
        for e in self._envs.values():
            e.close()


def _make_ma_env(spec) -> MultiAgentEnv:
    return spec() if callable(spec) else spec


class MultiAgentRolloutWorker:
    """Steps a MultiAgentEnv with one JaxPolicy per policy id; returns
    per-policy GAE-postprocessed SampleBatches."""

    def __init__(self, env_spec, policy_mapping: Dict[str, str], *,
                 hidden=(64, 64), gamma: float = 0.99, lam: float = 0.95,
                 episodes_per_sample: int = 2, max_steps: int = 500,
                 worker_index: int = 0, seed: Optional[int] = None):
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax
        jax.config.update("jax_platforms", "cpu")
        from ray_tpu.rl.policy import JaxPolicy

        self.env = _make_ma_env(env_spec)
        self.mapping = dict(policy_mapping)
        self.gamma, self.lam = gamma, lam
        self.episodes_per_sample = episodes_per_sample
        self.max_steps = max_steps
        self.worker_index = worker_index
        self._seed = (seed if seed is not None else 1234) + worker_index
        self._episode_count = 0
        self._completed: List[Dict[str, float]] = []
        self.policies: Dict[str, Any] = {}
        for aid, pid in self.mapping.items():
            if pid not in self.policies:
                self.policies[pid] = JaxPolicy(
                    self.env.observation_spaces[aid],
                    self.env.action_spaces[aid],
                    hidden=tuple(hidden), seed=self._seed)

    def set_weights(self, weights: Dict[str, Any]) -> None:
        for pid, w in weights.items():
            if pid in self.policies:
                self.policies[pid].set_weights(w)

    def sample(self) -> Dict[str, SampleBatch]:
        # stable agent indices (hash() is per-process randomized)
        agent_index = {aid: i for i, aid in enumerate(sorted(self.mapping))}
        parts: Dict[str, List[SampleBatch]] = {
            pid: [] for pid in self.policies}
        keys = (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.TERMINATEDS,
                SB.VF_PREDS, SB.ACTION_LOGP, SB.EPS_ID)
        for _ in range(self.episodes_per_sample):
            self._episode_count += 1
            base_eps = (self.worker_index * 1_000_000
                        + self._episode_count) * 100
            obs, _ = self.env.reset(
                seed=self._seed * 7919 + self._episode_count)
            # per-agent trajectory buffers: contiguous per agent, so GAE
            # sees real temporal structure even under parameter sharing
            traj = {aid: {k: [] for k in keys} for aid in self.mapping}
            alive = set(obs)
            ep_reward = 0.0
            steps = 0
            while steps < self.max_steps and alive:
                actions, logps, values = {}, {}, {}
                for aid in sorted(alive):
                    pid = self.mapping[aid]
                    a, lp, v = self.policies[pid].compute_actions(
                        np.asarray(obs[aid], np.float32)[None])
                    actions[aid] = int(a[0]) if np.asarray(a[0]).ndim == 0 \
                        else a[0]
                    logps[aid], values[aid] = float(lp[0]), float(v[0])
                nobs, rews, terms, truncs, _ = self.env.step(actions)
                for aid in actions:
                    t = traj[aid]
                    t[SB.OBS].append(np.asarray(obs[aid], np.float32))
                    t[SB.ACTIONS].append(actions[aid])
                    t[SB.REWARDS].append(rews.get(aid, 0.0))
                    t[SB.TERMINATEDS].append(terms.get(aid, False))
                    t[SB.VF_PREDS].append(values[aid])
                    t[SB.ACTION_LOGP].append(logps[aid])
                    t[SB.EPS_ID].append(base_eps + agent_index[aid])
                    ep_reward += rews.get(aid, 0.0)
                    # a finished agent takes no more actions: no phantom
                    # post-terminal rows
                    if terms.get(aid) or truncs.get(aid):
                        alive.discard(aid)
                for aid, ob in nobs.items():
                    obs[aid] = ob
                steps += 1
                if terms.get(__all_done__) or truncs.get(__all_done__):
                    break
            for aid, t in traj.items():
                if not t[SB.REWARDS]:
                    continue
                batch = SampleBatch({k: np.asarray(v)
                                     for k, v in t.items()})
                parts[self.mapping[aid]].append(
                    compute_gae(batch, gamma=self.gamma, lam=self.lam))
            self._completed.append({"episode_reward": ep_reward,
                                    "episode_len": steps})
        return {pid: SampleBatch.concat_samples(p)
                for pid, p in parts.items() if p}

    def get_metrics(self) -> List[Dict[str, float]]:
        out, self._completed = self._completed, []
        return out

    def ping(self) -> bool:
        return True


class MultiAgentPPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = MultiAgentPPO
        self.policy_mapping_fn: Callable[[str], str] = lambda aid: "shared"
        self.episodes_per_sample = 2
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.lr = 3e-4
        self.num_sgd_iter = 6
        self.sgd_minibatch_size = 128
        self.hidden = (64, 64)

    def multi_agent(self, *, policy_mapping_fn=None,
                    **kwargs) -> "MultiAgentPPOConfig":
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        self.extra.update(kwargs)
        return self


class MultiAgentPPO:
    """Independent PPO over the policy map (shared-parameter when several
    agents map to one policy id)."""

    def __init__(self, config: MultiAgentPPOConfig):
        import ray_tpu
        self.config = config
        if config.env_spec is None:
            raise ValueError("config.environment(env) is required")
        probe = _make_ma_env(config.env_spec)
        self.mapping = {aid: config.policy_mapping_fn(aid)
                        for aid in probe.agent_ids}
        # one representative agent per policy for space probing
        self._spaces = {}
        for aid, pid in self.mapping.items():
            self._spaces.setdefault(
                pid, (probe.observation_spaces[aid],
                      probe.action_spaces[aid]))
        probe.close()

        self._worker_cls = ray_tpu.remote(num_cpus=1)(
            MultiAgentRolloutWorker)
        self.workers = [
            self._worker_cls.remote(
                config.env_spec, self.mapping,
                hidden=tuple(config.hidden), gamma=config.gamma,
                lam=config.lam,
                episodes_per_sample=config.episodes_per_sample,
                worker_index=i, seed=config.seed)
            for i in range(max(config.num_rollout_workers, 1))]
        self.iteration = 0
        self._timesteps_total = 0
        self._episode_history: List[Dict[str, float]] = []
        self._setup_learners()
        self._sync()

    def _setup_learners(self) -> None:
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.rl import models as M
        from ray_tpu.rl.env import Box

        cfg = self.config
        self._learners: Dict[str, Dict[str, Any]] = {}
        clip, vf_c, ent_c = (cfg.clip_param, cfg.vf_loss_coeff,
                             cfg.entropy_coeff)
        # stable per-policy seeds (hash() is per-process randomized)
        pid_index = {pid: i for i, pid in enumerate(sorted(self._spaces))}
        for pid, (obs_space, act_space) in self._spaces.items():
            continuous = isinstance(act_space, Box)
            act_dim = int(np.prod(act_space.shape)) if continuous \
                else act_space.n
            obs_dim = int(np.prod(obs_space.shape))
            model = M.ActorCritic(action_dim=act_dim,
                                  hidden=tuple(cfg.hidden),
                                  continuous=continuous)
            params = model.init(
                jax.random.PRNGKey((cfg.seed or 0) + pid_index[pid]),
                jnp.zeros((1, obs_dim)))["params"]
            tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                             optax.adam(cfg.lr))
            logp_fn = M.diag_gaussian_logp if continuous \
                else M.categorical_logp
            ent_fn = M.diag_gaussian_entropy if continuous \
                else M.categorical_entropy

            def make_step(model=model, tx=tx, logp_fn=logp_fn,
                          ent_fn=ent_fn):
                def loss_fn(params, batch):
                    logits, values = model.apply({"params": params},
                                                 batch[SB.OBS])
                    logp = logp_fn(logits, batch[SB.ACTIONS])
                    ratio = jnp.exp(logp - batch[SB.ACTION_LOGP])
                    adv = batch[SB.ADVANTAGES]
                    adv = (adv - adv.mean()) / jnp.maximum(adv.std(), 1e-4)
                    surr = jnp.minimum(
                        ratio * adv,
                        jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
                    vf_loss = 0.5 * jnp.square(
                        values - batch[SB.VALUE_TARGETS]).mean()
                    entropy = ent_fn(logits).mean()
                    total = (-surr.mean() + vf_c * vf_loss
                             - ent_c * entropy)
                    return total, {"policy_loss": -surr.mean(),
                                   "vf_loss": vf_loss, "entropy": entropy}

                @jax.jit
                def sgd_step(params, opt_state, batch):
                    (loss, aux), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(params, batch)
                    updates, opt_state = tx.update(grads, opt_state, params)
                    params = optax.apply_updates(params, updates)
                    aux["total_loss"] = loss
                    return params, opt_state, aux
                return sgd_step

            self._learners[pid] = {
                "params": params, "opt_state": tx.init(params),
                "step": make_step(),
            }

    def get_weights(self) -> Dict[str, Any]:
        import jax
        return {pid: jax.tree.map(np.asarray, st["params"])
                for pid, st in self._learners.items()}

    def set_weights(self, weights: Dict[str, Any]) -> None:
        import jax.numpy as jnp
        import jax
        for pid, w in weights.items():
            if pid in self._learners:
                self._learners[pid]["params"] = jax.tree.map(jnp.asarray, w)

    def _sync(self) -> None:
        import ray_tpu
        wref = ray_tpu.put(self.get_weights())
        for w in self.workers:
            w.set_weights.remote(wref)

    def train(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        import ray_tpu
        cfg = self.config

        per_policy: Dict[str, List[SampleBatch]] = {}
        refs = [w.sample.remote() for w in self.workers]
        for ref in refs:
            batches = ray_tpu.get(ref, timeout=120.0)
            for pid, b in batches.items():
                per_policy.setdefault(pid, []).append(b)

        info: Dict[str, Any] = {}
        for pid, parts in per_policy.items():
            batch = SampleBatch.concat_samples(parts)
            self._timesteps_total += batch.count
            st = self._learners[pid]
            aux = {}
            for epoch in range(cfg.num_sgd_iter):
                for mb in batch.minibatches(
                        min(cfg.sgd_minibatch_size, batch.count),
                        seed=None if cfg.seed is None
                        else cfg.seed + self.iteration * 100 + epoch):
                    device_batch = {
                        k: jnp.asarray(v) for k, v in mb.items()
                        if k in (SB.OBS, SB.ACTIONS, SB.ACTION_LOGP,
                                 SB.ADVANTAGES, SB.VALUE_TARGETS)}
                    st["params"], st["opt_state"], aux = st["step"](
                        st["params"], st["opt_state"], device_batch)
            info[pid] = {k: float(v) for k, v in aux.items()}
        self._sync()
        self.iteration += 1

        metrics_refs = [w.get_metrics.remote() for w in self.workers]
        for ref in metrics_refs:
            try:
                self._episode_history.extend(ray_tpu.get(ref, timeout=30.0))
            except Exception:
                pass
        self._episode_history = self._episode_history[-100:]
        rewards = [e["episode_reward"] for e in self._episode_history]
        return {"info": info, "training_iteration": self.iteration,
                "timesteps_total": self._timesteps_total,
                "episode_reward_mean": float(np.mean(rewards))
                if rewards else float("nan"),
                "episodes_total": len(self._episode_history)}

    def save(self) -> Checkpoint:
        return Checkpoint.from_dict({"weights": self.get_weights(),
                                     "iteration": self.iteration})

    def restore(self, checkpoint: Checkpoint) -> None:
        d = checkpoint.to_dict()
        self.set_weights(d["weights"])
        self.iteration = d.get("iteration", 0)
        self._sync()

    def stop(self) -> None:
        import ray_tpu
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
