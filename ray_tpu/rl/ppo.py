"""PPO: synchronous on-policy training with a mesh-sharded learner.

Analog of /root/reference/rllib/algorithms/ppo/ppo.py:311 (training_step:
synchronous_parallel_sample → train over minibatch epochs) with the loss
of ppo_torch_policy.py (clipped surrogate + clipped value loss + entropy).
TPU-native: the SGD step is one jitted function whose batch is sharded
over the mesh's data axis — XLA inserts the gradient psum over ICI.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.kl_target = 0.01
        self.lr = 3e-4
        self.algo_class = PPO


def make_ppo_optimizer(cfg) -> "optax.GradientTransformation":
    return optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                       optax.adam(cfg.lr))


def make_ppo_sgd_step(model, logp_fn, ent_fn, tx, cfg):
    """The jitted clipped-surrogate learner step — built once here so
    the mesh-sharded driver and the podracer compiled-DAG learner train
    with identical math."""
    clip, vf_clip = cfg.clip_param, cfg.vf_clip_param
    vf_coeff, ent_coeff = cfg.vf_loss_coeff, cfg.entropy_coeff

    def loss_fn(params, batch):
        logits, values = model.apply({"params": params}, batch[SB.OBS])
        logp = logp_fn(logits, batch[SB.ACTIONS])
        ratio = jnp.exp(logp - batch[SB.ACTION_LOGP])
        adv = batch[SB.ADVANTAGES]
        adv = (adv - adv.mean()) / jnp.maximum(adv.std(), 1e-4)
        surr = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
        vf_targets = batch[SB.VALUE_TARGETS]
        vf_err = jnp.square(values - vf_targets)
        vf_clipped = batch[SB.VF_PREDS] + jnp.clip(
            values - batch[SB.VF_PREDS], -vf_clip, vf_clip)
        vf_err2 = jnp.square(vf_clipped - vf_targets)
        vf_loss = 0.5 * jnp.maximum(vf_err, vf_err2)
        entropy = ent_fn(logits)
        total = (-surr + vf_coeff * vf_loss - ent_coeff * entropy).mean()
        kl = (batch[SB.ACTION_LOGP] - logp).mean()
        return total, {"policy_loss": -surr.mean(),
                       "vf_loss": vf_loss.mean(),
                       "entropy": entropy.mean(), "kl": kl}

    @jax.jit
    def sgd_step(params, opt_state, batch):
        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        aux["total_loss"] = loss
        aux["grad_norm"] = optax.global_norm(grads)
        return params, opt_state, aux

    return sgd_step


class PPO(Algorithm):
    podracer_algo = "ppo"

    def setup_learner(self) -> None:
        cfg: PPOConfig = self.config
        self.model, params, self.continuous, logp_fn, ent_fn = \
            self.init_actor_critic()
        self.tx = make_ppo_optimizer(cfg)

        # learner mesh: data-parallel over every local device
        self.build_learner_mesh()
        params = jax.device_put(params, self.repl_sharding)
        self.opt_state = jax.device_put(self.tx.init(params),
                                        self.repl_sharding)
        self.params = params
        self._sgd_step = make_ppo_sgd_step(
            self.model, logp_fn, ent_fn, self.tx, cfg)

    def get_weights(self) -> Any:
        if self.podracer is not None:
            return self.podracer.get_weights()
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        if self.podracer is not None:
            self.podracer.set_weights(weights)
            return
        self.params = jax.device_put(
            jax.tree.map(jnp.asarray, weights), self.repl_sharding)

    def training_step(self) -> Dict[str, Any]:
        cfg: PPOConfig = self.config
        # 1. synchronous parallel sample (rollout_ops.py:21)
        train_batch = self.gather_on_policy_batch(cfg.train_batch_size)

        # 2. minibatch SGD epochs on the mesh (train_ops.py:26)
        mb = self.round_minibatch(cfg.sgd_minibatch_size)
        aux_last: Dict[str, Any] = {}
        n_updates = 0
        for epoch in range(cfg.num_sgd_iter):
            for minibatch in train_batch.minibatches(
                    mb, seed=None if cfg.seed is None
                    else cfg.seed + self.iteration * 100 + epoch):
                device_batch = {
                    k: jax.device_put(v, self.batch_sharding)
                    for k, v in minibatch.items() if k != SB.EPS_ID}
                self.params, self.opt_state, aux = self._sgd_step(
                    self.params, self.opt_state, device_batch)
                n_updates += 1
            aux_last = aux
        # 3. broadcast fresh weights to rollout workers
        self.workers.sync_weights(self.get_weights())
        info = {k: float(v) for k, v in aux_last.items()}
        info["num_sgd_updates"] = n_updates
        info["train_batch_size"] = train_batch.count
        return {"info": info}
