"""R2D2: recurrent replay distributed DQN.

Analog of /root/reference/rllib/algorithms/r2d2/r2d2.py (Kapturowski et
al.): LSTM Q-network trained on replayed fixed-length sequences with the
zero-start-state strategy — each sequence replays from a zero carry, the
first ``burn_in`` steps only warm the hidden state (no loss). Double-Q
targets from a periodically synced target network; per-worker epsilon
rollouts via the recurrent policy (ray_tpu/rl/policy.py R2D2Policy).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import models as M
from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import Box, make_env
from ray_tpu.rl.replay_buffer import ReplayBuffer


class R2D2Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = R2D2
        self.lr = 5e-4
        self.lstm_size = 64
        self.hidden = (64,)
        self.rollout_fragment_length = 40   # sequence length L
        self.burn_in = 8                    # carry warmup, no loss
        self.train_batch_size = 16          # sequences per update
        self.buffer_size = 2000             # stored sequences
        self.learning_starts = 64           # sequences before updates
        self.target_update_freq = 1000      # env steps between syncs
        self.n_updates_per_iter = 16
        self.double_q = True
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_timesteps = 20_000


class R2D2(Algorithm):
    @classmethod
    def extra_worker_kwargs(cls, config: AlgorithmConfig) -> Dict[str, Any]:
        return {"policy": "r2d2",
                "policy_kwargs": {"lstm_size": getattr(config, "lstm_size",
                                                       64)}}

    def setup_learner(self) -> None:
        cfg: R2D2Config = self.config
        probe = make_env(cfg.env_spec)
        if isinstance(probe.action_space, Box):
            raise ValueError("R2D2 requires a discrete action space")
        act_dim = probe.action_space.n
        obs_dim = int(np.prod(probe.observation_space.shape))
        probe.close()

        self.model = M.RecurrentQNetwork(action_dim=act_dim,
                                         hidden=tuple(cfg.hidden),
                                         lstm_size=cfg.lstm_size)
        carry0 = self.model.initial_state(1)
        params = self.model.init(jax.random.PRNGKey(cfg.seed or 0),
                                 jnp.zeros((1, 1, obs_dim)),
                                 carry0)["params"]
        self.tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                              optax.adam(cfg.lr))
        self.build_learner_mesh()
        repl = self.repl_sharding
        self.params = jax.device_put(params, repl)
        self.target_params = jax.device_put(params, repl)
        self.opt_state = jax.device_put(self.tx.init(self.params), repl)
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._steps_since_target_sync = 0

        model, tx = self.model, self.tx
        gamma, double_q = cfg.gamma, cfg.double_q
        burn_in = cfg.burn_in

        def loss_fn(params, target_params, batch):
            B, L = batch[SB.REWARDS].shape
            carry = model.initial_state(B)
            # replay the whole sequence from the zero start state
            q_seq, _ = model.apply({"params": params}, batch[SB.OBS],
                                   carry)
            q_taken = jnp.take_along_axis(
                q_seq, batch[SB.ACTIONS][..., None].astype(jnp.int32),
                axis=-1)[..., 0]
            # targets: value of next step within the same sequence replay
            tq_seq, _ = model.apply({"params": target_params},
                                    batch[SB.OBS], carry)
            # step the networks once more on NEXT_OBS's final column by
            # shifting: q(s_{t+1}) comes from position t+1 of the replay;
            # the last position bootstraps through its own next_obs pass
            q_next_online = jnp.concatenate(
                [q_seq[:, 1:], q_seq[:, -1:]], axis=1)
            q_next_target = jnp.concatenate(
                [tq_seq[:, 1:], tq_seq[:, -1:]], axis=1)
            if double_q:
                next_a = jnp.argmax(q_next_online, axis=-1)
                q_next = jnp.take_along_axis(
                    q_next_target, next_a[..., None], axis=-1)[..., 0]
            else:
                q_next = jnp.max(q_next_target, axis=-1)
            not_done = 1.0 - batch[SB.TERMINATEDS].astype(jnp.float32)
            # the final step of a sequence has no in-sequence successor:
            # exclude it from the loss (mask below) rather than bootstrap
            # from a stale column
            target = batch[SB.REWARDS] + gamma * not_done * \
                jax.lax.stop_gradient(q_next)
            mask = batch["seq_valid"].astype(jnp.float32)
            mask = mask.at[:, :burn_in].set(0.0)     # carry warmup only
            mask = mask.at[:, -1].set(0.0)           # no successor
            # truncated steps would bootstrap from the auto-reset
            # episode's first obs at t+1 — exclude them from the loss
            # (true terminations are handled by not_done above)
            mask = mask * (1.0 - batch[SB.TRUNCATEDS].astype(jnp.float32))
            huber = optax.huber_loss(q_taken, target, delta=1.0)
            denom = jnp.maximum(mask.sum(), 1.0)
            loss = (huber * mask).sum() / denom
            return loss, {"mean_q": (q_taken * mask).sum() / denom,
                          "trained_steps": denom}

        @jax.jit
        def td_step(params, target_params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["loss"] = loss
            aux["grad_norm"] = optax.global_norm(grads)
            return params, opt_state, aux

        self._td_step = td_step

    def get_weights(self) -> Any:
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = jax.device_put(jax.tree.map(jnp.asarray, weights),
                                     self.repl_sharding)
        self.target_params = self.params

    def _epsilon(self) -> float:
        cfg: R2D2Config = self.config
        frac = min(self._timesteps_total / max(cfg.epsilon_timesteps, 1),
                   1.0)
        return cfg.epsilon_initial + frac * (
            cfg.epsilon_final - cfg.epsilon_initial)

    def training_step(self) -> Dict[str, Any]:
        cfg: R2D2Config = self.config
        self.workers.foreach_worker("set_epsilon", self._epsilon())
        batches = self.workers.foreach_worker("sample_sequences")
        for b in batches:
            self.buffer.add(b)          # rows are [L, ...] sequences
            self._timesteps_total += int(np.sum(b["seq_valid"]))
            self._steps_since_target_sync += int(np.sum(b["seq_valid"]))

        info: Dict[str, Any] = {"epsilon": self._epsilon(),
                                "buffer_sequences": len(self.buffer)}
        if len(self.buffer) < cfg.learning_starts:
            return {"info": info}

        n = self.round_minibatch(cfg.train_batch_size)
        aux_last: Dict[str, Any] = {}
        for _ in range(cfg.n_updates_per_iter):
            sample = self.buffer.sample(n)
            device_batch = self.stage_batch(
                sample, (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.TERMINATEDS,
                         SB.TRUNCATEDS, "seq_valid"))
            self.params, self.opt_state, aux = self._td_step(
                self.params, self.target_params, self.opt_state,
                device_batch)
            aux_last = aux

        if self._steps_since_target_sync >= cfg.target_update_freq:
            self.target_params = self.params
            self._steps_since_target_sync = 0
            info["target_synced"] = True
        self.workers.sync_weights(self.get_weights())
        info.update({k: float(v) for k, v in aux_last.items()})
        return {"info": info}
