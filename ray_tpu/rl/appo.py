"""APPO: asynchronous PPO — IMPALA's actor-learner pipeline with the
clipped-surrogate loss computed on V-trace-corrected advantages.

Analog of /root/reference/rllib/algorithms/appo/appo.py (+
appo_torch_policy.py): off-policy fragments stream in asynchronously; the
importance ratio is taken against the behavior policy's logp and clipped
PPO-style; a slow-moving target policy network anchors the V-trace
correction (appo.py target_update_frequency). Inherits IMPALA's async
submit/consume loop; only the jitted loss differs.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.algorithm import AlgorithmConfig
from ray_tpu.rl.impala import Impala, vtrace


class APPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = APPO
        self.lr = 5e-4
        self.clip_param = 0.3
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.vtrace_rho_bar = 1.0
        self.vtrace_c_bar = 1.0
        self.batches_per_step = 8
        self.rollout_fragment_length = 50
        self.target_update_frequency = 4   # learner steps between syncs


class APPO(Impala):
    def setup_learner(self) -> None:
        cfg: APPOConfig = self.config
        self.model, self.params, _, logp_fn, ent_fn = \
            self.init_actor_critic()
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                              optax.adam(cfg.lr))
        self.opt_state = self.tx.init(self.params)
        self._inflight: Dict = {}
        self._learner_steps = 0

        model, gamma = self.model, cfg.gamma
        clip = cfg.clip_param
        vf_coeff, ent_coeff = cfg.vf_loss_coeff, cfg.entropy_coeff
        rho_bar, c_bar = cfg.vtrace_rho_bar, cfg.vtrace_c_bar
        tx = self.tx

        def loss_fn(params, target_params, batch):
            T, B = batch[SB.REWARDS].shape
            obs = batch[SB.OBS]
            flat_obs = obs.reshape((T * B,) + obs.shape[2:])
            logits, values = model.apply({"params": params}, flat_obs)
            logits = logits.reshape((T, B) + logits.shape[1:])
            values = values.reshape(T, B)
            _, boot_value = model.apply({"params": params},
                                        batch["bootstrap_obs"])
            # target policy anchors the V-trace correction (appo.py)
            t_logits, _ = model.apply({"params": target_params}, flat_obs)
            t_logits = t_logits.reshape((T, B) + t_logits.shape[1:])
            target_logp_anchor = logp_fn(t_logits, batch[SB.ACTIONS])
            discounts = gamma * (1.0 - batch[SB.TERMINATEDS]
                                 .astype(jnp.float32))
            vs, pg_adv = vtrace(
                jax.lax.stop_gradient(target_logp_anchor),
                batch[SB.ACTION_LOGP], batch[SB.REWARDS], values,
                boot_value, discounts, rho_bar, c_bar)
            # PPO clipped surrogate against the behavior policy
            logp = logp_fn(logits, batch[SB.ACTIONS])
            ratio = jnp.exp(logp - batch[SB.ACTION_LOGP])
            surr = jnp.minimum(
                ratio * pg_adv,
                jnp.clip(ratio, 1 - clip, 1 + clip) * pg_adv)
            pg_loss = -surr.mean()
            vf_loss = 0.5 * jnp.square(vs - values).mean()
            entropy = ent_fn(logits).mean()
            total = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy,
                           "mean_ratio": ratio.mean()}

        @jax.jit
        def sgd_step(params, target_params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = loss
            return params, opt_state, aux

        self._appo_step = sgd_step
        # adapter so Impala.training_step's 3-arg call keeps working
        self._sgd_step = self._appo_adapter

    def _appo_adapter(self, params, opt_state, batch):
        cfg: APPOConfig = self.config
        params, opt_state, aux = self._appo_step(
            params, self.target_params, opt_state, batch)
        self._learner_steps += 1
        if self._learner_steps % max(cfg.target_update_frequency, 1) == 0:
            self.target_params = jax.tree.map(jnp.copy, params)
        return params, opt_state, aux

    def set_weights(self, weights) -> None:
        super().set_weights(weights)
        self.target_params = jax.tree.map(jnp.copy, self.params)
