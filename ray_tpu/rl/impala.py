"""IMPALA: async actor-learner training with V-trace.

Analog of /root/reference/rllib/algorithms/impala/impala.py:528
(training_step: async rollout queue → LearnerThread
rllib/execution/learner_thread.py:17) with the V-trace correction of
vtrace_torch.py (Espeholt et al. 2018). Rollout actors free-run with
stale weights; each completed fragment triggers one learner step and a
weight push back to that actor only — no global sync barrier.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig


def vtrace(target_logp, behavior_logp, rewards, values, bootstrap_value,
           discounts, rho_bar: float = 1.0, c_bar: float = 1.0):
    """V-trace targets/advantages over a [T, B] fragment (time-major).

    discounts: gamma * (1 - done) per step. Returns (vs, pg_advantages).
    """
    rhos = jnp.exp(target_logp - behavior_logp)
    clipped_rhos = jnp.minimum(rho_bar, rhos)
    cs = jnp.minimum(c_bar, rhos)
    values_t_plus_1 = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (
        rewards + discounts * values_t_plus_1 - values)

    def scan_fn(carry, xs):
        delta, discount, c = xs
        carry = delta + discount * c * carry
        return carry, carry

    _, vs_minus_v = jax.lax.scan(
        scan_fn, jnp.zeros_like(bootstrap_value),
        (deltas, discounts, cs), reverse=True)
    vs = vs_minus_v + values
    vs_t_plus_1 = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_adv = clipped_rhos * (rewards + discounts * vs_t_plus_1 - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class ImpalaConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.vtrace_rho_bar = 1.0
        self.vtrace_c_bar = 1.0
        self.batches_per_step = 8
        self.rollout_fragment_length = 50
        self.algo_class = Impala


def make_impala_optimizer(cfg) -> "optax.GradientTransformation":
    return optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                       optax.rmsprop(cfg.lr, decay=0.99))


def make_impala_sgd_step(model, logp_fn, ent_fn, tx, cfg):
    """The jitted V-trace learner step over a [T, B] time-major fragment
    — built once here so the classic driver and the podracer compiled-
    DAG learner train with identical math."""
    gamma = cfg.gamma
    vf_coeff, ent_coeff = cfg.vf_loss_coeff, cfg.entropy_coeff
    rho_bar, c_bar = cfg.vtrace_rho_bar, cfg.vtrace_c_bar

    def loss_fn(params, batch):
        T, B = batch[SB.REWARDS].shape
        obs = batch[SB.OBS]
        flat_obs = obs.reshape((T * B,) + obs.shape[2:])
        logits, values = model.apply({"params": params}, flat_obs)
        logits = logits.reshape((T, B) + logits.shape[1:])
        values = values.reshape(T, B)
        boot_logits, boot_value = model.apply(
            {"params": params}, batch["bootstrap_obs"])
        target_logp = logp_fn(logits, batch[SB.ACTIONS])
        discounts = gamma * (1.0 - batch[SB.TERMINATEDS]
                             .astype(jnp.float32))
        vs, pg_adv = vtrace(target_logp, batch[SB.ACTION_LOGP],
                            batch[SB.REWARDS], values, boot_value,
                            discounts, rho_bar, c_bar)
        pg_loss = -(target_logp * pg_adv).mean()
        vf_loss = 0.5 * jnp.square(vs - values).mean()
        entropy = ent_fn(logits).mean()
        total = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy}

    @jax.jit
    def sgd_step(params, opt_state, batch):
        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        aux["total_loss"] = loss
        return params, opt_state, aux

    return sgd_step


class Impala(Algorithm):
    podracer_algo = "impala"

    def setup_learner(self) -> None:
        cfg: ImpalaConfig = self.config
        self.model, self.params, _, logp_fn, ent_fn = \
            self.init_actor_critic()
        self.tx = make_impala_optimizer(cfg)
        self.opt_state = self.tx.init(self.params)
        self._inflight: Dict[Any, int] = {}   # ref -> worker index
        self._sgd_step = make_impala_sgd_step(
            self.model, logp_fn, ent_fn, self.tx, cfg)

    def get_weights(self) -> Any:
        if self.podracer is not None:
            return self.podracer.get_weights()
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        if self.podracer is not None:
            self.podracer.set_weights(weights)
            return
        self.params = jax.tree.map(jnp.asarray, weights)

    def _submit(self, idx: int) -> None:
        ref = self.workers.workers[idx].sample_time_major.remote()
        self._inflight[ref] = idx

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu
        cfg: ImpalaConfig = self.config
        # keep one fragment in flight per worker
        live = set(self._inflight.values())
        for i in range(len(self.workers.workers)):
            if i not in live:
                self._submit(i)
        aux_last: Dict[str, Any] = {}
        processed = 0
        steps = 0
        while processed < cfg.batches_per_step:
            ready, _ = ray_tpu.wait(list(self._inflight.keys()),
                                    num_returns=1, timeout=60.0)
            if not ready:
                break
            ref = ready[0]
            idx = self._inflight.pop(ref)
            try:
                fragment = ray_tpu.get(ref, timeout=30.0)
            except Exception:
                # worker died mid-fragment: replace it and move on
                self.workers.restart_worker(idx, self.get_weights())
                self._submit(idx)
                continue
            batch = {k: jnp.asarray(v) for k, v in fragment.items()}
            self.params, self.opt_state, aux = self._sgd_step(
                self.params, self.opt_state, batch)
            aux_last = aux
            steps += fragment[SB.REWARDS].size
            processed += 1
            # push fresh weights only to the actor we just consumed
            try:
                self.workers.workers[idx].set_weights.remote(
                    self.get_weights())
            except Exception:
                pass
            self._submit(idx)
        self._timesteps_total += steps
        info = {k: float(v) for k, v in aux_last.items()}
        info["batches_processed"] = processed
        return {"info": info}

    def stop(self) -> None:
        getattr(self, "_inflight", {}).clear()
        super().stop()
