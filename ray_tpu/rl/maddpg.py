"""MADDPG: multi-agent DDPG with centralized critics.

Analog of /root/reference/rllib/algorithms/maddpg/maddpg.py (Lowe et
al.): each agent has a deterministic actor over its own observation and a
centralized critic Q_i(o_1..o_n, a_1..a_n) that sees every agent's
observation and action during training — decentralized execution,
centralized training. Target actors/critics with soft updates. Ships
CooperativeNav, a simple-spread-style continuous landmark-covering env.
Driver-local stepping (tiny envs, like QMIX/bandits); the jitted joint
update is the compute path.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rl.algorithm import AlgorithmConfig
from ray_tpu.rl.env import Box
from ray_tpu.rl.multi_agent import MultiAgentEnv


class CooperativeNav(MultiAgentEnv):
    """N agents on the 2D unit square must cover N landmarks; shared
    reward is -(sum of each landmark's distance to its nearest agent)
    (the MPE simple-spread objective without collisions)."""

    def __init__(self, num_agents: int = 2, max_steps: int = 25,
                 seed: int = 0):
        self.n = num_agents
        self.max_steps = max_steps
        self.agent_ids = [f"agent_{i}" for i in range(num_agents)]
        obs_dim = 2 + 2 * num_agents + 2 * num_agents
        obs_space = Box(low=-2.0, high=2.0, shape=(obs_dim,))
        act_space = Box(low=-1.0, high=1.0, shape=(2,))
        self.observation_spaces = {a: obs_space for a in self.agent_ids}
        self.action_spaces = {a: act_space for a in self.agent_ids}
        self._rng = np.random.default_rng(seed)
        self._t = 0

    def _obs_for(self, i: int) -> np.ndarray:
        rel_land = (self.landmarks - self.pos[i]).reshape(-1)
        rel_agents = (self.pos - self.pos[i]).reshape(-1)
        return np.concatenate([self.pos[i], rel_land,
                               rel_agents]).astype(np.float32)

    def _all_obs(self):
        return {a: self._obs_for(i) for i, a in enumerate(self.agent_ids)}

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.pos = self._rng.uniform(0, 1, (self.n, 2))
        self.landmarks = self._rng.uniform(0, 1, (self.n, 2))
        self._t = 0
        return self._all_obs(), {}

    def _reward(self) -> float:
        d = np.linalg.norm(self.pos[None, :, :]
                           - self.landmarks[:, None, :], axis=-1)
        return float(-d.min(axis=1).sum())

    def step(self, actions: Dict[str, np.ndarray]):
        for i, a in enumerate(self.agent_ids):
            act = np.clip(np.asarray(actions[a], np.float32), -1, 1)
            self.pos[i] = np.clip(self.pos[i] + 0.1 * act, -0.5, 1.5)
        self._t += 1
        r = self._reward()
        done = self._t >= self.max_steps
        rews = {a: r / self.n for a in self.agent_ids}
        terms = {"__all__": False, **{a: False for a in self.agent_ids}}
        truncs = {"__all__": done, **{a: done for a in self.agent_ids}}
        return self._all_obs(), rews, terms, truncs, {}


class MADDPGConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = MADDPG
        self.actor_lr = 1e-3
        self.critic_lr = 1e-3
        self.tau = 0.01
        self.exploration_noise = 0.1
        self.buffer_size = 20_000
        self.train_batch_size = 128
        self.learning_starts = 500
        self.n_updates_per_iter = 16
        self.steps_per_iter = 250
        self.hidden = (64, 64)


class MADDPG:
    def __init__(self, config: MADDPGConfig):
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.rl import models as M

        self.config = config
        self._env_ctor = config.env_spec if callable(config.env_spec) \
            else None
        env = config.env_spec() if callable(config.env_spec) \
            else config.env_spec
        if not isinstance(env, MultiAgentEnv):
            raise ValueError("MADDPG requires a MultiAgentEnv")
        self.env = env
        self.agents: List[str] = list(env.agent_ids)
        n = len(self.agents)
        a0 = self.agents[0]
        if not isinstance(env.action_spaces[a0], Box):
            raise ValueError("MADDPG requires continuous action spaces")
        self.act_dim = int(np.prod(env.action_spaces[a0].shape))
        self.obs_dim = int(np.prod(env.observation_spaces[a0].shape))
        joint_obs = n * self.obs_dim
        joint_act = n * self.act_dim

        self.actor = M.DeterministicActor(action_dim=self.act_dim,
                                          hidden=tuple(config.hidden))
        self.critic = M.ContinuousQ(hidden=tuple(config.hidden))
        rng = jax.random.PRNGKey(config.seed or 0)
        keys = jax.random.split(rng, 2 * n)
        actor_params = [self.actor.init(keys[i],
                                        jnp.zeros((1, self.obs_dim)))
                        ["params"] for i in range(n)]
        critic_params = [self.critic.init(
            keys[n + i], jnp.zeros((1, joint_obs)),
            jnp.zeros((1, joint_act)))["params"] for i in range(n)]
        stack = lambda trees: jax.tree.map(  # noqa: E731
            lambda *xs: jnp.stack(xs), *trees)
        # agent-stacked param trees: updates vmap over the agent axis
        self.state = {
            "actor": stack(actor_params),
            "critic": stack(critic_params),
            "target_actor": jax.tree.map(jnp.copy, stack(actor_params)),
            "target_critic": jax.tree.map(jnp.copy, stack(critic_params)),
        }
        self.actor_tx = optax.adam(config.actor_lr)
        self.critic_tx = optax.adam(config.critic_lr)
        self.state["actor_opt"] = self.actor_tx.init(self.state["actor"])
        self.state["critic_opt"] = self.critic_tx.init(
            self.state["critic"])

        actor, critic = self.actor, self.critic
        gamma, tau = config.gamma, config.tau
        n_agents, act_dim = n, self.act_dim

        def actor_apply(p, obs):
            return actor.apply({"params": p}, obs)

        def critic_apply(p, jo, ja):
            return critic.apply({"params": p}, jo, ja)

        def update(state, batch):
            # batch: obs [B, n, o], actions [B, n, a], rewards [B, n],
            # next_obs [B, n, o], dones [B]
            B = batch["rewards"].shape[0]
            jo = batch["obs"].reshape(B, -1)
            ja = batch["actions"].reshape(B, -1)
            njo = batch["next_obs"].reshape(B, -1)
            # target joint action from target actors (per agent vmap)
            na = jax.vmap(actor_apply, in_axes=(0, 1), out_axes=1)(
                state["target_actor"], batch["next_obs"])
            nja = na.reshape(B, -1)

            # per-agent critic update
            def one_critic_loss(cp, tcp, reward_i):
                target_q = critic_apply(tcp, njo, nja)
                not_done = 1.0 - batch["dones"]
                y = reward_i + gamma * not_done * \
                    jax.lax.stop_gradient(target_q)
                q = critic_apply(cp, jo, ja)
                return jnp.mean(jnp.square(q - y)), q.mean()

            def critic_grads(cp, tcp, reward_i):
                (loss, mean_q), g = jax.value_and_grad(
                    one_critic_loss, has_aux=True)(cp, tcp, reward_i)
                return g, loss, mean_q

            c_grads, c_losses, mean_qs = jax.vmap(
                critic_grads, in_axes=(0, 0, 1))(
                state["critic"], state["target_critic"],
                batch["rewards"])
            c_updates, critic_opt = self.critic_tx.update(
                c_grads, state["critic_opt"], state["critic"])
            critic_params = optax.apply_updates(state["critic"], c_updates)

            # per-agent actor update through its centralized critic:
            # replace agent i's action with its fresh actor output
            def one_actor_loss(ap, i, cp):
                my_a = actor_apply(ap, batch["obs"][:, i])
                all_a = jax.vmap(actor_apply, in_axes=(0, 1), out_axes=1)(
                    state["actor"], batch["obs"])
                all_a = jax.lax.dynamic_update_slice(
                    all_a, my_a[:, None, :], (0, i, 0))
                q = critic_apply(cp, jo, all_a.reshape(B, -1))
                return -q.mean()

            def actor_grads(ap, i, cp):
                loss, g = jax.value_and_grad(one_actor_loss)(ap, i, cp)
                return g, loss

            idxs = jnp.arange(n_agents)
            a_grads, a_losses = jax.vmap(
                actor_grads, in_axes=(0, 0, 0))(
                state["actor"], idxs, critic_params)
            a_updates, actor_opt = self.actor_tx.update(
                a_grads, state["actor_opt"], state["actor"])
            actor_params = optax.apply_updates(state["actor"], a_updates)

            soft = lambda t, o: jax.tree.map(  # noqa: E731
                lambda a, b: a * (1 - tau) + b * tau, t, o)
            new_state = {
                "actor": actor_params, "critic": critic_params,
                "target_actor": soft(state["target_actor"], actor_params),
                "target_critic": soft(state["target_critic"],
                                      critic_params),
                "actor_opt": actor_opt, "critic_opt": critic_opt,
            }
            return new_state, {"critic_loss": c_losses.mean(),
                               "actor_loss": a_losses.mean(),
                               "mean_q": mean_qs.mean()}

        @jax.jit
        def act_all(actor_params, obs_stack):
            return jax.vmap(actor_apply, in_axes=(0, 0))(
                actor_params, obs_stack[:, None])[:, 0]

        self._update = jax.jit(update, donate_argnums=(0,))
        self._act_all = act_all
        self._jnp = jnp
        self._jax = jax
        from ray_tpu.rl.replay_buffer import ReplayBuffer
        self._np_rng = np.random.default_rng(config.seed or 0)
        self._buffer = ReplayBuffer(config.buffer_size, seed=config.seed)
        self.iteration = 0
        self._timesteps_total = 0
        self._episodes_total = 0
        self._reward_window: List[float] = []
        self._obs, _ = self.env.reset(seed=config.seed or 0)
        self._ep_reward = 0.0

    def _actions(self, obs: Dict[str, Any],
                 explore: bool) -> Tuple[np.ndarray, np.ndarray]:
        obs_stack = np.stack([np.asarray(obs[a], np.float32)
                              for a in self.agents])
        acts = np.asarray(self._act_all(self.state["actor"],
                                        self._jnp.asarray(obs_stack)))
        if explore:
            acts = acts + self.config.exploration_noise * \
                self._np_rng.standard_normal(acts.shape)
        return np.clip(acts, -1.0, 1.0), obs_stack

    def train(self) -> Dict[str, Any]:
        from ray_tpu.rl.sample_batch import SampleBatch
        cfg = self.config
        jnp = self._jnp
        rows: Dict[str, List[np.ndarray]] = {
            k: [] for k in ("obs", "actions", "rewards", "next_obs",
                            "dones")}
        for _ in range(cfg.steps_per_iter):
            acts, obs_stack = self._actions(self._obs, explore=True)
            action_dict = {a: acts[i] for i, a in enumerate(self.agents)}
            nobs, rews, terms, truncs, _ = self.env.step(action_dict)
            nobs_stack = np.stack(
                [np.asarray(nobs.get(a, self._obs[a]), np.float32)
                 for a in self.agents])
            done = bool(terms.get("__all__")) or bool(
                truncs.get("__all__"))
            terminal = bool(terms.get("__all__"))
            rows["obs"].append(obs_stack.astype(np.float32))
            rows["actions"].append(acts.astype(np.float32))
            rows["rewards"].append(np.asarray(
                [rews.get(a, 0.0) for a in self.agents], np.float32))
            rows["next_obs"].append(nobs_stack.astype(np.float32))
            rows["dones"].append(np.float32(terminal))
            self._ep_reward += float(sum(rews.values()))
            self._timesteps_total += 1
            self._obs = nobs
            if done:
                self._reward_window.append(self._ep_reward)
                self._episodes_total += 1
                self._ep_reward = 0.0
                self._obs, _ = self.env.reset()
        self._reward_window = self._reward_window[-100:]
        self._buffer.add(SampleBatch(
            {k: np.stack(v) for k, v in rows.items()}))

        info: Dict[str, Any] = {"buffer_size": len(self._buffer)}
        aux: Dict[str, Any] = {}
        if len(self._buffer) >= cfg.learning_starts:
            for _ in range(cfg.n_updates_per_iter):
                sample = self._buffer.sample(
                    min(cfg.train_batch_size, len(self._buffer)))
                batch = {k: jnp.asarray(v) for k, v in sample.items()}
                self.state, aux = self._update(self.state, batch)
            info.update({k: float(v) for k, v in aux.items()})
        self.iteration += 1
        return {"info": info, "training_iteration": self.iteration,
                "timesteps_total": self._timesteps_total,
                "episode_reward_mean": float(
                    np.mean(self._reward_window))
                if self._reward_window else float("nan"),
                "episodes_total": self._episodes_total}

    def evaluate(self, episodes: int = 5) -> float:
        # a dedicated env instance: seeding the shared training env would
        # leave its RNG in the same state after every evaluate() call
        env = self._env_ctor() if self._env_ctor is not None else self.env
        totals = []
        for ep in range(episodes):
            obs, _ = env.reset(seed=5000 + ep)
            total = 0.0
            for _ in range(200):
                acts, _ = self._actions(obs, explore=False)
                obs, rews, terms, truncs, _ = env.step(
                    {a: acts[i] for i, a in enumerate(self.agents)})
                total += float(sum(rews.values()))
                if terms.get("__all__") or truncs.get("__all__"):
                    break
            totals.append(total)
        if env is self.env:
            # fell back to the shared env: restore training state
            self._obs, _ = self.env.reset()
            self._ep_reward = 0.0
        else:
            env.close()
        return float(np.mean(totals))

    def get_weights(self) -> Any:
        return self._jax.tree.map(np.asarray, self.state["actor"])

    def set_weights(self, weights: Any) -> None:
        self.state["actor"] = self._jax.tree.map(self._jnp.asarray,
                                                 weights)
        self.state["target_actor"] = self._jax.tree.map(
            self._jnp.copy, self.state["actor"])

    def save(self) -> Checkpoint:
        from ray_tpu.rl.algorithm import full_training_state
        return Checkpoint.from_dict({
            "state": full_training_state(self),
            "iteration": self.iteration,
            "timesteps_total": self._timesteps_total})

    def restore(self, checkpoint: Checkpoint) -> None:
        from ray_tpu.rl.algorithm import apply_full_training_state
        d = checkpoint.to_dict()
        if d.get("state") is not None:
            # full training state: all agents' actors/critics/targets/opts
            apply_full_training_state(self, d["state"])
        else:  # legacy actor-only checkpoint
            self.set_weights(d["weights"])
        self.iteration = d.get("iteration", 0)
        self._timesteps_total = d.get("timesteps_total", 0)

    def stop(self) -> None:
        self.env.close()
