"""MBMPO: model-based meta-policy optimization.

Analog of /root/reference/rllib/algorithms/mbmpo/mbmpo.py (Clavera et al.
2018): learn an ensemble of dynamics models from real transitions, treat
each ensemble member as a "task", and meta-learn policy parameters with a
MAML-style inner/outer loop over *imagined* rollouts so one inner gradient
step adapts the policy to any member (and therefore robustly to the real
dynamics, which the ensemble brackets).

TPU-native shape (same design as rl/maml.py): the inner adaptation is
differentiated through directly (grad-of-grad) and the ensemble dimension
is vmapped, so one jitted meta-step computes every member's imagined
rollouts, inner updates, and the second-order meta-gradient as a single
XLA program. Model training is likewise one jitted step vmapped over the
ensemble with bootstrap-resampled minibatches.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rl.algorithm import AlgorithmConfig
from ray_tpu.rl.env import Box, make_env


class MBMPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = MBMPO
        self.ensemble_size = 5
        self.model_hidden = (128, 128)
        self.model_lr = 1e-3
        self.model_train_steps = 200    # sgd steps per iteration
        self.model_batch_size = 256
        self.inner_lr = 0.05
        self.meta_lr = 3e-4
        self.horizon = 20               # imagined rollout length
        self.n_imagined = 16            # rollouts per ensemble member
        self.meta_updates_per_iter = 10
        self.real_steps_per_iter = 1000
        self.buffer_size = 50_000
        self.hidden = (64, 64)          # policy net
        self.exploration_noise = 0.2

    def environment(self, env=None, **kwargs):
        return super().environment(env or "Pendulum-v1", **kwargs)


class MBMPO:
    def __init__(self, config: MBMPOConfig):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp
        import optax

        self.config = config
        self.iteration = 0
        self._timesteps_total = 0
        cfg = config

        self.env = make_env(cfg.env_spec)
        if not isinstance(self.env.action_space, Box):
            raise ValueError("MBMPO requires a continuous action space")
        obs_dim = int(np.prod(self.env.observation_space.shape))
        act_dim = int(np.prod(self.env.action_space.shape))
        self.obs_dim, self.act_dim = obs_dim, act_dim
        low = np.asarray(self.env.action_space.low, np.float32).reshape(-1)
        high = np.asarray(self.env.action_space.high, np.float32).reshape(-1)
        self._scale = (high - low) / 2.0
        self._shift = (high + low) / 2.0

        class Policy(nn.Module):
            @nn.compact
            def __call__(self, s):
                x = s
                for h in cfg.hidden:
                    x = nn.tanh(nn.Dense(h)(x))
                mean = nn.Dense(act_dim)(x)
                log_std = self.param("log_std", nn.initializers.constant(-0.5),
                                     (act_dim,))
                return mean, log_std

        class Dynamics(nn.Module):
            """delta_state + reward head; trained on real transitions."""

            @nn.compact
            def __call__(self, s, a):
                x = jnp.concatenate([s, a], -1)
                for h in cfg.model_hidden:
                    x = nn.swish(nn.Dense(h)(x))
                delta = nn.Dense(obs_dim)(x)
                reward = nn.Dense(1)(x)[..., 0]
                return delta, reward

        self.policy = Policy()
        self.dynamics = Dynamics()
        rng = jax.random.PRNGKey(cfg.seed or 0)
        r_pi, r_dyn = jax.random.split(rng)
        pi_params = self.policy.init(r_pi, jnp.zeros((1, obs_dim)))["params"]
        # independently initialized ensemble members, stacked on axis 0
        dyn_params = jax.vmap(
            lambda k: self.dynamics.init(k, jnp.zeros((1, obs_dim)),
                                         jnp.zeros((1, act_dim)))["params"]
        )(jax.random.split(r_dyn, cfg.ensemble_size))

        self.pi_tx = optax.adam(cfg.meta_lr)
        self.dyn_tx = optax.adam(cfg.model_lr)
        self.state = {
            "pi": pi_params,
            "pi_opt": self.pi_tx.init(pi_params),
            "dyn": dyn_params,
            "dyn_opt": jax.vmap(self.dyn_tx.init)(dyn_params),
        }

        # ---------------------------------------------------- model training
        def model_loss(dp, s, a, s2, r):
            delta_hat, r_hat = self.dynamics.apply({"params": dp}, s, a)
            return (jnp.square(delta_hat - (s2 - s)).sum(-1).mean()
                    + jnp.square(r_hat - r).mean())

        def model_step(dyn, dyn_opt, batch):
            # batch arrays are [ensemble, B, ...] (bootstrap-resampled)
            def one(dp, do, s, a, s2, r):
                loss, grads = jax.value_and_grad(model_loss)(dp, s, a, s2, r)
                updates, do = self.dyn_tx.update(grads, do, dp)
                return optax.apply_updates(dp, updates), do, loss

            dyn, dyn_opt, losses = jax.vmap(one)(
                dyn, dyn_opt, batch["s"], batch["a"], batch["s2"],
                batch["r"])
            return dyn, dyn_opt, losses.mean()

        self._model_step = jax.jit(model_step, donate_argnums=(0, 1))

        # ------------------------------------------------ imagination + MAML
        def logp(pp, s, a):
            mean, log_std = self.policy.apply({"params": pp}, s)
            var = jnp.exp(2 * log_std)
            return (-0.5 * (jnp.square(a - mean) / var
                            + 2 * log_std + jnp.log(2 * jnp.pi))).sum(-1)

        def imagine(pp, dp, s0, key):
            """Roll the policy through one learned model; returns the
            REINFORCE surrogate (differentiable wrt pp) and mean return."""
            def step(carry, k):
                s = carry
                mean, log_std = self.policy.apply({"params": pp}, s)
                u = mean + jnp.exp(log_std) * jax.random.normal(
                    k, mean.shape)  # pre-squash sample
                a = jnp.tanh(u)
                delta, r = self.dynamics.apply(
                    {"params": dp}, s, a * self._scale + self._shift)
                return s + delta, (s, u, r)

            keys = jax.random.split(key, cfg.horizon)
            _, (ss, uu, rr) = jax.lax.scan(step, s0, keys)
            # reward-to-go weighted log-probs (REINFORCE with baseline).
            # The score is the Gaussian log-density at the PRE-squash
            # sample u: the tanh change-of-variables Jacobian is constant
            # wrt params once u is fixed, so it drops out of the gradient
            # (evaluating at tanh(u) instead would bias the score).
            rtg = jnp.cumsum(rr[::-1], 0)[::-1]              # [T, B]
            rtg = rtg - rtg.mean(axis=1, keepdims=True)
            lp = jax.vmap(lambda s, u: logp(pp, s, u))(
                ss, jax.lax.stop_gradient(uu))
            surrogate = (lp * jax.lax.stop_gradient(rtg)).sum(0).mean()
            return surrogate, rr.sum(0).mean()

        def member_meta_loss(pp, dp, s0, k_in, k_out):
            # inner: one policy-gradient step inside this member's model
            g = jax.grad(lambda p: -imagine(p, dp, s0, k_in)[0])(pp)
            adapted = jax.tree.map(lambda p, gg: p - cfg.inner_lr * gg,
                                   pp, g)
            # outer: post-adaptation performance in the same model; the
            # meta-gradient flows through the inner step (second order)
            surrogate, ret = imagine(adapted, dp, s0, k_out)
            return -surrogate, ret

        def meta_step(pi, pi_opt, dyn, s0, key):
            # s0: [ensemble, B, obs] real states; vmap members into one
            # XLA program (the MAML-over-models core of MBMPO)
            ks = jax.random.split(key, cfg.ensemble_size * 2)
            k_in, k_out = ks[:cfg.ensemble_size], ks[cfg.ensemble_size:]

            def loss(p):
                losses, rets = jax.vmap(
                    lambda dp, s, ki, ko: member_meta_loss(p, dp, s, ki, ko)
                )(dyn, s0, k_in, k_out)
                return losses.mean(), rets.mean()

            (l, ret), grads = jax.value_and_grad(loss, has_aux=True)(pi)
            updates, pi_opt = self.pi_tx.update(grads, pi_opt, pi)
            return optax.apply_updates(pi, updates), pi_opt, l, ret

        self._meta_step = jax.jit(meta_step, donate_argnums=(0, 1))
        self._jax, self._jnp = jax, jnp
        self._rng = jax.random.PRNGKey((cfg.seed or 0) + 77)
        self._np_rng = np.random.default_rng(cfg.seed or 0)

        self._buf_s: list = []
        self._buf_a: list = []
        self._buf_s2: list = []
        self._buf_r: list = []
        self._reward_window: list = []

    # ------------------------------------------------------------- rollouts
    def _act_real(self, pi_params, obs: np.ndarray) -> np.ndarray:
        jnp = self._jnp
        mean, log_std = self.policy.apply(
            {"params": pi_params}, jnp.asarray(obs, jnp.float32)[None])
        a = np.tanh(np.asarray(mean)[0]
                    + np.exp(np.asarray(log_std))
                    * self._np_rng.standard_normal(self.act_dim)
                    * self.config.exploration_noise / 0.2 * 1.0)
        return a.astype(np.float32)

    def _collect_real(self, n_steps: int) -> None:
        cfg = self.config
        obs, _ = self.env.reset()
        ep_rew = 0.0
        for _ in range(n_steps):
            a = self._act_real(self.state["pi"], np.asarray(obs, np.float32))
            env_a = a * self._scale + self._shift
            obs2, r, term, trunc, _ = self.env.step(env_a)
            self._buf_s.append(np.asarray(obs, np.float32).reshape(-1))
            self._buf_a.append(env_a.reshape(-1).astype(np.float32))
            self._buf_s2.append(np.asarray(obs2, np.float32).reshape(-1))
            self._buf_r.append(float(r))
            ep_rew += float(r)
            self._timesteps_total += 1
            obs = obs2
            if term or trunc:
                self._reward_window.append(ep_rew)
                ep_rew = 0.0
                obs, _ = self.env.reset()
        cap = cfg.buffer_size
        for buf in (self._buf_s, self._buf_a, self._buf_s2, self._buf_r):
            del buf[:-cap]
        self._reward_window = self._reward_window[-50:]

    # ---------------------------------------------------------------- train
    def train(self) -> Dict[str, Any]:
        cfg = self.config
        jnp = self._jnp
        self._collect_real(cfg.real_steps_per_iter)
        s = np.stack(self._buf_s)
        a = np.stack(self._buf_a)
        s2 = np.stack(self._buf_s2)
        r = np.asarray(self._buf_r, np.float32)
        n = len(s)

        model_loss = float("nan")
        for _ in range(cfg.model_train_steps):
            idx = self._np_rng.integers(
                0, n, (cfg.ensemble_size, min(cfg.model_batch_size, n)))
            batch = {"s": jnp.asarray(s[idx]), "a": jnp.asarray(a[idx]),
                     "s2": jnp.asarray(s2[idx]), "r": jnp.asarray(r[idx])}
            self.state["dyn"], self.state["dyn_opt"], loss = \
                self._model_step(self.state["dyn"], self.state["dyn_opt"],
                                 batch)
            model_loss = float(loss)

        meta_loss = imagined_return = float("nan")
        for _ in range(cfg.meta_updates_per_iter):
            idx = self._np_rng.integers(
                0, n, (cfg.ensemble_size, cfg.n_imagined))
            s0 = jnp.asarray(s[idx])
            self._rng, key = self._jax.random.split(self._rng)
            self.state["pi"], self.state["pi_opt"], ml, ret = \
                self._meta_step(self.state["pi"], self.state["pi_opt"],
                                self.state["dyn"], s0, key)
            meta_loss, imagined_return = float(ml), float(ret)

        self.iteration += 1
        rews = self._reward_window
        return {
            "training_iteration": self.iteration,
            "timesteps_total": self._timesteps_total,
            "episode_reward_mean": float(np.mean(rews)) if rews
            else float("nan"),
            "info": {"model_loss": model_loss, "meta_loss": meta_loss,
                     "imagined_return": imagined_return,
                     "buffer_size": n},
        }

    # ----------------------------------------------------------- checkpoint
    def get_weights(self) -> Any:
        return self._jax.tree.map(np.asarray, self.state["pi"])

    def set_weights(self, weights: Any) -> None:
        self.state["pi"] = self._jax.tree.map(self._jnp.asarray, weights)

    def save(self) -> Checkpoint:
        from ray_tpu.rl.algorithm import full_training_state
        return Checkpoint.from_dict({
            "state": full_training_state(self),
            "iteration": self.iteration})

    def restore(self, checkpoint: Checkpoint) -> None:
        from ray_tpu.rl.algorithm import apply_full_training_state
        d = checkpoint.to_dict()
        if d.get("state") is not None:
            apply_full_training_state(self, d["state"])
        else:
            self.set_weights(d["weights"])
        self.iteration = d.get("iteration", 0)

    def stop(self) -> None:
        self.env.close()
