"""CRR: Critic-Regularized Regression for offline continuous control.

Analog of /root/reference/rllib/algorithms/crr/ (crr_torch_policy.py):
twin-critic TD learning plus an actor trained by advantage-weighted
behavior cloning — weight = exp(A(s,a)/beta) (clipped) or the binary
1[A>0] indicator, with A(s,a) = Q(s,a) - mean_k Q(s, pi_k(s)). Offline:
trains from a JsonReader dataset, one jitted update per minibatch.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.cql import CQLConfig
from ray_tpu.rl.env import Box, make_env
from ray_tpu.rl.offline import JsonReader
from ray_tpu.rl.sample_batch import SampleBatch


class CRRConfig(CQLConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = CRR
        self.beta = 1.0                 # advantage temperature
        self.weight_clip = 20.0
        self.advantage_type = "exp"     # "exp" | "binary"
        self.n_action_samples = 4       # for the advantage baseline
        self.tau = 0.005


class CRR:
    def __init__(self, config: CRRConfig):
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.rl import models as M

        self.config = config
        if config.input_path is None:
            raise ValueError("config.offline_data(input_path=...) required")
        self.dataset = JsonReader(config.input_path).read_all()
        if SB.NEXT_OBS not in self.dataset:
            raise ValueError("CRR needs next_obs in the offline dataset "
                             "(collect with collect_dataset)")
        self.iteration = 0
        self._timesteps_total = 0

        probe = make_env(config.env_spec)
        if not isinstance(probe.action_space, Box):
            raise ValueError("CRR requires a continuous action space")
        act_dim = int(np.prod(probe.action_space.shape))
        obs_dim = int(np.prod(probe.observation_space.shape))
        low = np.asarray(probe.action_space.low, np.float32).reshape(-1)
        high = np.asarray(probe.action_space.high, np.float32).reshape(-1)
        probe.close()

        self.actor = M.SquashedGaussianActor(action_dim=act_dim,
                                             hidden=tuple(config.hidden))
        self.critic = M.TwinQ(hidden=tuple(config.hidden))
        rng = jax.random.PRNGKey(config.seed or 0)
        r1, r2 = jax.random.split(rng)
        actor_params = self.actor.init(r1, jnp.zeros((1, obs_dim)))["params"]
        critic_params = self.critic.init(
            r2, jnp.zeros((1, obs_dim)), jnp.zeros((1, act_dim)))["params"]
        self.actor_tx = optax.adam(config.lr)
        self.critic_tx = optax.adam(config.lr)
        self.state = {
            "actor": actor_params,
            "critic": critic_params,
            "target_critic": jax.tree.map(jnp.copy, critic_params),
            "actor_opt": self.actor_tx.init(actor_params),
            "critic_opt": self.critic_tx.init(critic_params),
        }

        actor, critic = self.actor, self.critic
        actor_tx, critic_tx = self.actor_tx, self.critic_tx
        gamma, tau, beta = config.gamma, config.tau, config.beta
        w_clip = config.weight_clip
        n_samp = config.n_action_samples
        binary = config.advantage_type == "binary"
        scale, shift = (high - low) / 2.0, (high + low) / 2.0

        def rescale(a_tanh):
            return a_tanh * scale + shift

        # logp of the (tanh-space-mapped) dataset action under the actor
        def data_logp(params, obs, act_env):
            mean, log_std = actor.apply({"params": params}, obs)
            a_tanh = jnp.clip((act_env - shift) / jnp.maximum(scale, 1e-8),
                              -1.0 + 1e-6, 1.0 - 1e-6)
            pre = jnp.arctanh(a_tanh)
            std = jnp.exp(log_std)
            logp = (-0.5 * jnp.square((pre - mean) / std) - log_std
                    - 0.5 * jnp.log(2.0 * jnp.pi)).sum(-1)
            logp -= (2.0 * (jnp.log(2.0) - pre
                            - jax.nn.softplus(-2.0 * pre))).sum(-1)
            return logp

        def update(state, batch, rng):
            r_next, r_base = jax.random.split(rng)

            # -- critic: TD target from the current policy ----------------
            mean_n, log_std_n = actor.apply({"params": state["actor"]},
                                            batch[SB.NEXT_OBS])
            a_next, _ = M.squashed_sample_logp(r_next, mean_n, log_std_n)
            q1_t, q2_t = critic.apply({"params": state["target_critic"]},
                                      batch[SB.NEXT_OBS], rescale(a_next))
            q_next = jnp.minimum(q1_t, q2_t)
            not_done = 1.0 - batch[SB.TERMINATEDS].astype(jnp.float32)
            target = jax.lax.stop_gradient(
                batch[SB.REWARDS] + gamma * not_done * q_next)

            def critic_loss(p):
                q1, q2 = critic.apply({"params": p}, batch[SB.OBS],
                                      batch[SB.ACTIONS])
                return (jnp.square(q1 - target)
                        + jnp.square(q2 - target)).mean() * 0.5, q1

            (c_loss, q_data), c_grads = jax.value_and_grad(
                critic_loss, has_aux=True)(state["critic"])
            c_updates, critic_opt = critic_tx.update(
                c_grads, state["critic_opt"], state["critic"])
            critic_params = optax.apply_updates(state["critic"], c_updates)

            # -- advantage: Q(s, a_data) - E_k Q(s, pi_k(s)) --------------
            mean_c, log_std_c = actor.apply({"params": state["actor"]},
                                            batch[SB.OBS])
            keys = jax.random.split(r_base, n_samp)
            q_base = jnp.mean(jnp.stack([
                critic.apply({"params": critic_params}, batch[SB.OBS],
                             rescale(M.squashed_sample_logp(
                                 k, mean_c, log_std_c)[0]))[0]
                for k in keys]), axis=0)
            adv = jax.lax.stop_gradient(q_data - q_base)
            if binary:
                weights = (adv > 0).astype(jnp.float32)
            else:
                weights = jnp.minimum(jnp.exp(adv / beta), w_clip)

            # -- actor: advantage-weighted regression ---------------------
            def actor_loss(p):
                logp = data_logp(p, batch[SB.OBS], batch[SB.ACTIONS])
                return -(weights * logp).mean()

            a_loss, a_grads = jax.value_and_grad(actor_loss)(state["actor"])
            a_updates, actor_opt = actor_tx.update(
                a_grads, state["actor_opt"], state["actor"])
            actor_params = optax.apply_updates(state["actor"], a_updates)

            target_critic = jax.tree.map(
                lambda t, o: t * (1.0 - tau) + o * tau,
                state["target_critic"], critic_params)
            new_state = {
                "actor": actor_params, "critic": critic_params,
                "target_critic": target_critic,
                "actor_opt": actor_opt, "critic_opt": critic_opt,
            }
            return new_state, {"critic_loss": c_loss, "actor_loss": a_loss,
                               "mean_advantage": adv.mean(),
                               "mean_weight": weights.mean(),
                               "mean_q": q_data.mean()}

        self._update = jax.jit(update, donate_argnums=(0,))
        self._rng = jax.random.PRNGKey((config.seed or 0) + 41)
        self._jax, self._jnp = jax, jnp

    def get_weights(self) -> Any:
        return self._jax.tree.map(np.asarray, self.state["actor"])

    def set_weights(self, weights: Any) -> None:
        self.state["actor"] = self._jax.tree.map(self._jnp.asarray, weights)

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        jnp = self._jnp
        rng = np.random.default_rng((cfg.seed or 0) + self.iteration * 1000)
        n = self.dataset.count
        keep = (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.NEXT_OBS, SB.TERMINATEDS)
        metrics: Dict[str, Any] = {}
        for _ in range(cfg.num_sgd_iter):
            idx = rng.choice(n, size=min(cfg.train_batch_size, n),
                             replace=False)
            mb = SampleBatch({k: np.asarray(self.dataset[k])[idx]
                              for k in keep if k in self.dataset})
            device_batch = {k: jnp.asarray(v) for k, v in mb.items()}
            self._rng, key = self._jax.random.split(self._rng)
            self.state, metrics = self._update(self.state, device_batch, key)
            self._timesteps_total += mb.count
        self.iteration += 1
        info = {k: float(v) for k, v in metrics.items()}
        return {"info": info, "training_iteration": self.iteration,
                "timesteps_total": self._timesteps_total}

    def save(self) -> Checkpoint:
        from ray_tpu.rl.algorithm import full_training_state
        return Checkpoint.from_dict({
            "state": full_training_state(self),
            "iteration": self.iteration})

    def restore(self, checkpoint: Checkpoint) -> None:
        from ray_tpu.rl.algorithm import apply_full_training_state
        d = checkpoint.to_dict()
        if d.get("state") is not None:
            # full training state: actor + critics + targets + optimizers
            apply_full_training_state(self, d["state"])
        else:  # legacy actor-only checkpoint
            self.set_weights(d["weights"])
        self.iteration = d.get("iteration", 0)

    def stop(self) -> None:
        pass
