"""RolloutWorker: env stepping on CPU hosts.

Analog of /root/reference/rllib/evaluation/rollout_worker.py:157
(sample() :869): vectorized envs stepped with the current policy, GAE
postprocessing per episode fragment, metrics tracked per completed
episode. Runs as a CPU actor; the TPU never blocks on env code.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.env import VectorEnv
from ray_tpu.rl.policy import JaxPolicy
from ray_tpu.rl.sample_batch import SampleBatch, compute_gae


class RolloutWorker:
    def __init__(self, env_spec, *, num_envs: int = 1,
                 rollout_fragment_length: int = 200,
                 gamma: float = 0.99, lam: float = 0.95,
                 hidden=(256, 256), policy: str = "ac",
                 policy_kwargs: Optional[Dict[str, Any]] = None,
                 worker_index: int = 0, seed: Optional[int] = None):
        # rollout actors must never grab the TPU
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax
        jax.config.update("jax_platforms", "cpu")
        self.worker_index = worker_index
        self._env_spec = env_spec
        seed = (seed if seed is not None else 1234) + worker_index * 1000
        self.vec = VectorEnv(env_spec, num_envs, seed=seed)
        if policy == "q":
            from ray_tpu.rl.policy import QPolicy
            self.policy = QPolicy(self.vec.observation_space,
                                  self.vec.action_space, hidden=hidden,
                                  seed=seed, **(policy_kwargs or {}))
        elif policy == "r2d2":
            from ray_tpu.rl.policy import R2D2Policy
            self.policy = R2D2Policy(self.vec.observation_space,
                                     self.vec.action_space, hidden=hidden,
                                     seed=seed, num_envs=num_envs,
                                     **(policy_kwargs or {}))
        elif policy == "ddpg":
            from ray_tpu.rl.policy import DDPGPolicy
            self.policy = DDPGPolicy(self.vec.observation_space,
                                     self.vec.action_space, hidden=hidden,
                                     seed=seed, **(policy_kwargs or {}))
        elif policy == "sac":
            from ray_tpu.rl.policy import SACPolicy
            self.policy = SACPolicy(self.vec.observation_space,
                                    self.vec.action_space, hidden=hidden,
                                    seed=seed, **(policy_kwargs or {}))
        else:
            self.policy = JaxPolicy(self.vec.observation_space,
                                    self.vec.action_space, hidden=hidden,
                                    seed=seed, **(policy_kwargs or {}))
        self.fragment = rollout_fragment_length
        self.gamma, self.lam = gamma, lam
        self._obs = self.vec.reset()
        self._eps_id = np.arange(num_envs) + worker_index * 1_000_000
        self._next_eps = num_envs + worker_index * 1_000_000
        self._ep_rewards = np.zeros(num_envs)
        self._ep_lens = np.zeros(num_envs, np.int64)
        self._completed: List[Dict[str, float]] = []

    # -- weights sync ------------------------------------------------------
    def set_weights(self, weights) -> None:
        self.policy.set_weights(weights)

    def get_weights(self):
        return self.policy.get_weights()

    # -- sampling ----------------------------------------------------------
    def sample(self) -> SampleBatch:
        n_envs = self.vec.num_envs
        T = self.fragment
        cols: Dict[str, List[np.ndarray]] = {
            SB.OBS: [], SB.ACTIONS: [], SB.REWARDS: [], SB.TERMINATEDS: [],
            SB.TRUNCATEDS: [], SB.VF_PREDS: [], SB.ACTION_LOGP: [],
            SB.EPS_ID: []}
        for _ in range(T):
            actions, logp, values = self.policy.compute_actions(self._obs)
            next_obs, rewards, terms, truncs, infos = self.vec.step(actions)
            cols[SB.OBS].append(self._obs)
            cols[SB.ACTIONS].append(actions)
            cols[SB.REWARDS].append(rewards)
            cols[SB.TERMINATEDS].append(terms)
            cols[SB.TRUNCATEDS].append(truncs)
            cols[SB.VF_PREDS].append(values)
            cols[SB.ACTION_LOGP].append(logp)
            cols[SB.EPS_ID].append(self._eps_id.copy())
            self._ep_rewards += rewards
            self._ep_lens += 1
            for i in range(n_envs):
                if terms[i] or truncs[i]:
                    self._completed.append(
                        {"episode_reward": float(self._ep_rewards[i]),
                         "episode_len": int(self._ep_lens[i])})
                    self._ep_rewards[i] = 0.0
                    self._ep_lens[i] = 0
                    self._eps_id[i] = self._next_eps
                    self._next_eps += 1
            self._obs = next_obs

        # bootstrap values for fragments cut mid-episode (or truncated)
        _, _, last_values = self.policy.compute_actions(self._obs)
        # stack to [T, N] then split per env for GAE over time order
        stacked = {k: np.stack(v) for k, v in cols.items()}
        per_env = []
        for i in range(n_envs):
            env_batch = SampleBatch(
                {k: stacked[k][:, i] for k in stacked.keys()})
            pieces = env_batch.split_by_episode()
            for j, piece in enumerate(pieces):
                last = j == len(pieces) - 1
                terminated = bool(piece[SB.TERMINATEDS][-1])
                boot = 0.0 if terminated else (
                    float(last_values[i]) if last else 0.0)
                # non-last pieces always end terminated or truncated; a
                # truncated middle piece bootstraps from its own final vf
                if not last and not terminated:
                    boot = float(piece[SB.VF_PREDS][-1])
                per_env.append(compute_gae(piece, gamma=self.gamma,
                                           lam=self.lam, last_value=boot))
        return SampleBatch.concat_samples(per_env)

    def sample_time_major(self) -> Dict[str, np.ndarray]:
        """[T, N]-shaped fragment without GAE — IMPALA's V-trace does its
        own off-policy correction on the learner (cf. rllib vtrace)."""
        n_envs = self.vec.num_envs
        cols: Dict[str, List[np.ndarray]] = {
            SB.OBS: [], SB.ACTIONS: [], SB.REWARDS: [], SB.TERMINATEDS: [],
            SB.ACTION_LOGP: []}
        for _ in range(self.fragment):
            actions, logp, _ = self.policy.compute_actions(self._obs)
            next_obs, rewards, terms, truncs, _ = self.vec.step(actions)
            cols[SB.OBS].append(self._obs)
            cols[SB.ACTIONS].append(actions)
            cols[SB.REWARDS].append(rewards)
            cols[SB.TERMINATEDS].append(np.logical_or(terms, truncs))
            cols[SB.ACTION_LOGP].append(logp)
            self._ep_rewards += rewards
            self._ep_lens += 1
            for i in range(n_envs):
                if terms[i] or truncs[i]:
                    self._completed.append(
                        {"episode_reward": float(self._ep_rewards[i]),
                         "episode_len": int(self._ep_lens[i])})
                    self._ep_rewards[i] = 0.0
                    self._ep_lens[i] = 0
            self._obs = next_obs
        out = {k: np.stack(v) for k, v in cols.items()}
        out["bootstrap_obs"] = self._obs.copy()
        return out

    def set_epsilon(self, epsilon: float) -> None:
        """Exploration schedule hook (QPolicy only; no-op otherwise)."""
        if hasattr(self.policy, "set_epsilon"):
            self.policy.set_epsilon(epsilon)

    def sample_transitions(self) -> SampleBatch:
        """(obs, action, reward, next_obs, terminated) rows for replay-based
        algorithms — no GAE, truncations bootstrap (terminated=False)."""
        cols: Dict[str, List[np.ndarray]] = {
            SB.OBS: [], SB.ACTIONS: [], SB.REWARDS: [], SB.NEXT_OBS: [],
            SB.TERMINATEDS: []}
        for _ in range(self.fragment):
            actions, _, _ = self.policy.compute_actions(self._obs)
            next_obs, rewards, terms, truncs, infos = self.vec.step(actions)
            # auto-reset replaced ended envs' obs with the NEXT episode's
            # start — TD targets must bootstrap from the real final obs
            # (truncated rows especially: terminated=False there)
            row_next = next_obs.copy()
            for i, info in enumerate(infos):
                if "terminal_observation" in info:
                    row_next[i] = info["terminal_observation"]
            cols[SB.OBS].append(self._obs)
            cols[SB.ACTIONS].append(actions)
            cols[SB.REWARDS].append(rewards)
            cols[SB.NEXT_OBS].append(row_next)
            cols[SB.TERMINATEDS].append(terms)
            self._ep_rewards += rewards
            self._ep_lens += 1
            for i in range(self.vec.num_envs):
                if terms[i] or truncs[i]:
                    self._completed.append(
                        {"episode_reward": float(self._ep_rewards[i]),
                         "episode_len": int(self._ep_lens[i])})
                    self._ep_rewards[i] = 0.0
                    self._ep_lens[i] = 0
            self._obs = next_obs
        # flatten [T, N, ...] -> [T*N, ...]
        out = {k: np.concatenate(v) if np.asarray(v[0]).ndim > 1
               else np.stack(v).reshape(-1) for k, v in cols.items()}
        return SampleBatch(out)

    def sample_sequences(self) -> SampleBatch:
        """Fixed-length recurrent sequences for R2D2: one sequence of
        ``rollout_fragment_length`` timesteps per env, the LSTM carry
        zeroed at sequence start; steps after the first episode end are
        masked invalid (the next episode needs a fresh zero carry, which
        the learner can only supply at sequence starts). Rows are
        [num_envs, L, ...]."""
        if not hasattr(self.policy, "reset_carry"):
            raise ValueError("sample_sequences needs the r2d2 policy")
        n_envs = self.vec.num_envs
        L = self.fragment
        # fresh zero state at every sequence start so the learner can
        # replay from zeros (the R2D2 zero-start-state strategy)
        self.policy.reset_carry(np.ones(n_envs))
        cols = {k: [] for k in (SB.OBS, SB.ACTIONS, SB.REWARDS,
                                SB.TERMINATEDS, SB.TRUNCATEDS)}
        valid_rows = []
        alive = np.ones(n_envs, np.float32)
        for _ in range(L):
            actions, _, _ = self.policy.compute_actions(self._obs)
            next_obs, rewards, terms, truncs, infos = self.vec.step(actions)
            cols[SB.OBS].append(self._obs)
            cols[SB.ACTIONS].append(actions)
            cols[SB.REWARDS].append(rewards)
            cols[SB.TERMINATEDS].append(terms)
            cols[SB.TRUNCATEDS].append(truncs)
            valid_rows.append(alive.copy())
            # episode metrics track every step — including steps of the
            # auto-reset episode that the sequence no longer trains on
            self._ep_rewards += rewards
            self._ep_lens += 1
            done = np.asarray(terms) | np.asarray(truncs)
            for i in range(n_envs):
                if done[i]:
                    self._completed.append(
                        {"episode_reward": float(self._ep_rewards[i]),
                         "episode_len": int(self._ep_lens[i])})
                    self._ep_rewards[i] = 0.0
                    self._ep_lens[i] = 0
            alive = alive * (1.0 - done.astype(np.float32))
            self._obs = next_obs
        # [T, N, ...] -> [N, T, ...]
        out = {k: np.swapaxes(np.stack(v), 0, 1) for k, v in cols.items()}
        out["seq_valid"] = np.swapaxes(np.stack(valid_rows), 0, 1)
        return SampleBatch(out)

    def evaluate_rollout(self, weights, *, n_episodes: int = 1,
                         explore: bool = False,
                         max_steps: int = 1000) -> Dict[str, Any]:
        """Episode returns + env-step count under ``weights`` (ES/ARS
        fitness evaluation — cf. reference rllib/algorithms/es/es.py
        Worker.do_rollouts)."""
        from ray_tpu.rl.env import make_env
        self.policy.set_weights(weights)
        env = make_env(self._env_spec)
        returns = []
        total_steps = 0
        for ep in range(n_episodes):
            obs, _ = env.reset(seed=self.worker_index * 7919 + ep)
            total, done, steps = 0.0, False, 0
            while not done and steps < max_steps:
                a, _, _ = self.policy.compute_actions(
                    np.asarray(obs, np.float32)[None], explore=explore)
                obs, r, term, trunc, _ = env.step(a[0])
                total += r
                done = term or trunc
                steps += 1
            returns.append(float(total))
            total_steps += steps
        env.close()
        return {"returns": returns, "steps": total_steps}

    def get_metrics(self) -> List[Dict[str, float]]:
        out, self._completed = self._completed, []
        return out

    def ping(self) -> bool:
        return True
