"""ray_tpu.rl: reinforcement learning — CPU rollout actors + TPU learner.

Analog of /root/reference/rllib (SURVEY.md §2.4): AlgorithmConfig builder,
Algorithm driver (Tune-compatible), WorkerSet of fault-tolerant rollout
actors, on-policy (PG, A2C/A3C, PPO, IMPALA, APPO), off-policy (SimpleQ,
DQN, DDPG, TD3, SAC), offline (BC, MARWIL, CQL + IS/WIS estimators),
black-box (ES, ARS), replay buffers, in-repo gymnasium-compatible envs,
and the name registry used by the CLI/Tune.
"""

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig  # noqa: F401
from ray_tpu.rl.env import (Box, CartPoleEnv, Discrete, Env,  # noqa: F401
                            PendulumEnv, VectorEnv, make_env, register_env)
from ray_tpu.rl.a2c import A2C, A2CConfig, A3C, A3CConfig  # noqa: F401
from ray_tpu.rl.alpha_zero import (MCTS, AlphaZero,  # noqa: F401
                                   AlphaZeroConfig, TicTacToe)
from ray_tpu.rl.apex_dqn import ApexDQN, ApexDQNConfig  # noqa: F401
from ray_tpu.rl.appo import APPO, APPOConfig  # noqa: F401
from ray_tpu.rl.bandit import (BanditConfig, BanditLinTS,  # noqa: F401
                               BanditLinTSConfig, BanditLinUCB,
                               LinearDiscreteEnv)
from ray_tpu.rl.cql import CQL, CQLConfig  # noqa: F401
from ray_tpu.rl.crr import CRR, CRRConfig  # noqa: F401
from ray_tpu.rl.dreamer import Dreamer, DreamerConfig  # noqa: F401
from ray_tpu.rl.dt import DT, DTConfig  # noqa: F401
from ray_tpu.rl.ddpg import DDPG, DDPGConfig, TD3, TD3Config  # noqa: F401
from ray_tpu.rl.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rl.es import ARS, ARSConfig, ES, ESConfig  # noqa: F401
from ray_tpu.rl.impala import Impala, ImpalaConfig, vtrace  # noqa: F401
from ray_tpu.rl.offline import (BC, BCConfig, MARWIL,  # noqa: F401
                                MARWILConfig, JsonReader, JsonWriter,
                                collect_dataset,
                                importance_sampling_estimate)
from ray_tpu.rl.maddpg import (MADDPG, CooperativeNav,  # noqa: F401
                               MADDPGConfig)
from ray_tpu.rl.maml import MAML, MAMLConfig, SinusoidTasks  # noqa: F401
from ray_tpu.rl.alpha_star import AlphaStar, AlphaStarConfig  # noqa: F401
from ray_tpu.rl.mbmpo import MBMPO, MBMPOConfig  # noqa: F401
from ray_tpu.rl.multi_agent import (MultiAgentCartPole,  # noqa: F401
                                    MultiAgentEnv, MultiAgentPPO,
                                    MultiAgentPPOConfig,
                                    MultiAgentRolloutWorker)
from ray_tpu.rl.pg import PG, PGConfig  # noqa: F401
from ray_tpu.rl.policy import (DDPGPolicy, JaxPolicy, QPolicy,  # noqa: F401
                               R2D2Policy, SACPolicy)
from ray_tpu.rl.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rl.qmix import QMix, QMixConfig, TwoStepGame  # noqa: F401
from ray_tpu.rl.r2d2 import R2D2, R2D2Config  # noqa: F401
from ray_tpu.rl.registry import get_algorithm_class  # noqa: F401
from ray_tpu.rl.replay_buffer import (PrioritizedReplayBuffer,  # noqa: F401
                                      ReplayBuffer)
from ray_tpu.rl.rollout_worker import RolloutWorker  # noqa: F401
from ray_tpu.rl.sac import SAC, SACConfig  # noqa: F401
from ray_tpu.rl.sample_batch import SampleBatch, compute_gae  # noqa: F401
from ray_tpu.rl.simple_q import SimpleQ, SimpleQConfig  # noqa: F401
from ray_tpu.rl.slateq import (InterestEvolutionEnv, SlateQ,  # noqa: F401
                               SlateQConfig)
from ray_tpu.rl.worker_set import WorkerSet  # noqa: F401

__all__ = [
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "Impala",
    "ImpalaConfig", "APPO", "APPOConfig", "DQN", "DQNConfig", "SimpleQ",
    "SimpleQConfig", "vtrace", "RolloutWorker", "WorkerSet", "JaxPolicy",
    "QPolicy", "DDPGPolicy", "SAC", "SACConfig", "DDPG", "DDPGConfig",
    "TD3", "TD3Config", "PG", "PGConfig", "A2C", "A2CConfig", "A3C",
    "A3CConfig", "BC", "BCConfig", "MARWIL", "MARWILConfig", "CQL",
    "CQLConfig", "ES", "ESConfig", "ARS", "ARSConfig", "JsonReader",
    "JsonWriter", "collect_dataset", "importance_sampling_estimate",
    "ApexDQN", "ApexDQNConfig", "CRR", "CRRConfig", "DT", "DTConfig",
    "BanditLinUCB", "BanditLinTS", "BanditConfig", "BanditLinTSConfig",
    "LinearDiscreteEnv", "MultiAgentEnv", "MultiAgentCartPole",
    "MultiAgentPPO", "MultiAgentPPOConfig", "MultiAgentRolloutWorker",
    "AlphaZero", "AlphaZeroConfig", "MCTS", "TicTacToe",
    "MADDPG", "MADDPGConfig", "CooperativeNav",
    "MAML", "MAMLConfig", "SinusoidTasks",
    "MBMPO", "MBMPOConfig",
    "AlphaStar", "AlphaStarConfig",
    "SlateQ", "SlateQConfig", "InterestEvolutionEnv",
    "Dreamer", "DreamerConfig",
    "R2D2", "R2D2Config", "R2D2Policy", "QMix", "QMixConfig",
    "TwoStepGame",
    "get_algorithm_class", "SampleBatch", "compute_gae", "ReplayBuffer",
    "PrioritizedReplayBuffer", "Env", "Box", "Discrete", "CartPoleEnv",
    "PendulumEnv", "VectorEnv", "make_env", "register_env",
]
