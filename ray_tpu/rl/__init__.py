"""ray_tpu.rl: reinforcement learning — CPU rollout actors + TPU learner.

Analog of /root/reference/rllib (SURVEY.md §2.4): AlgorithmConfig builder,
Algorithm driver (Tune-compatible), WorkerSet of fault-tolerant rollout
actors, PPO (sync, mesh-sharded SGD), IMPALA (async, V-trace), DQN (replay +
target net + double/dueling Q), SAC (max-entropy continuous control), replay
buffers, in-repo gymnasium-compatible envs.
"""

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig  # noqa: F401
from ray_tpu.rl.env import (Box, CartPoleEnv, Discrete, Env,  # noqa: F401
                            PendulumEnv, VectorEnv, make_env, register_env)
from ray_tpu.rl.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rl.impala import Impala, ImpalaConfig, vtrace  # noqa: F401
from ray_tpu.rl.policy import (JaxPolicy, QPolicy,  # noqa: F401
                               SACPolicy)
from ray_tpu.rl.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rl.replay_buffer import (PrioritizedReplayBuffer,  # noqa: F401
                                      ReplayBuffer)
from ray_tpu.rl.rollout_worker import RolloutWorker  # noqa: F401
from ray_tpu.rl.sac import SAC, SACConfig  # noqa: F401
from ray_tpu.rl.sample_batch import SampleBatch, compute_gae  # noqa: F401
from ray_tpu.rl.worker_set import WorkerSet  # noqa: F401

__all__ = [
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "Impala",
    "ImpalaConfig", "DQN", "DQNConfig", "vtrace", "RolloutWorker",
    "WorkerSet", "JaxPolicy", "QPolicy", "SAC", "SACConfig",
    "SampleBatch", "compute_gae", "ReplayBuffer", "PrioritizedReplayBuffer",
    "Env", "Box", "Discrete", "CartPoleEnv", "PendulumEnv", "VectorEnv",
    "make_env", "register_env",
]
