"""SlateQ: Q-learning for slate recommendation.

Analog of /root/reference/rllib/algorithms/slateq/slateq.py (Ie et al.):
the combinatorial slate action is decomposed — Q(s, slate) =
sum_i P(click i | s, slate) * Q(s, i) under a conditional-logit user
choice model — so a per-item Q network suffices; slates are built with
the paper's Top-K heuristic (rank by choice-weighted item value). Ships a RecSim-style interest-
evolution env (documents with topic vectors, a drifting user interest,
a no-click option). Driver-local stepping like the bandits; the jitted
decomposed TD update is the compute path.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rl.algorithm import AlgorithmConfig


class InterestEvolutionEnv:
    """RecSim-flavored testbed: each step the env offers ``n_candidates``
    docs (topic vectors); the agent shows a slate of ``slate_size``; the
    user clicks via a conditional logit over slate ∪ {no-click}, gains
    engagement reward, and their interest drifts toward clicked topics.
    """

    def __init__(self, n_topics: int = 8, n_candidates: int = 10,
                 slate_size: int = 3, episode_len: int = 20,
                 no_click_mass: float = 1.0, seed: int = 0):
        self.n_topics = n_topics
        self.n_candidates = n_candidates
        self.slate_size = slate_size
        self.episode_len = episode_len
        self.no_click_mass = no_click_mass
        self._rng = np.random.default_rng(seed)
        self._t = 0

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        u = self._rng.normal(size=self.n_topics)
        self.user = u / np.linalg.norm(u)
        self._t = 0
        self._sample_docs()
        return self.observation()

    def _sample_docs(self):
        d = self._rng.normal(size=(self.n_candidates, self.n_topics))
        self.docs = d / np.linalg.norm(d, axis=1, keepdims=True)
        # doc quality modulates engagement when clicked
        self.quality = self._rng.uniform(0.5, 1.5, self.n_candidates)

    def observation(self) -> Dict[str, np.ndarray]:
        return {"user": self.user.astype(np.float32),
                "docs": self.docs.astype(np.float32),
                "quality": self.quality.astype(np.float32)}

    def choice_probs(self, slate: np.ndarray) -> np.ndarray:
        """Conditional logit over slate items + no-click (last entry)."""
        scores = np.exp(self.docs[slate] @ self.user)
        denom = scores.sum() + self.no_click_mass
        return np.append(scores / denom, self.no_click_mass / denom)

    def step(self, slate: np.ndarray):
        probs = self.choice_probs(slate)
        pick = self._rng.choice(len(probs), p=probs)
        if pick < len(slate):
            doc = int(slate[pick])
            reward = float(self.quality[doc])
            # interest drifts toward the clicked topic
            self.user = 0.9 * self.user + 0.1 * self.docs[doc]
            self.user = self.user / np.linalg.norm(self.user)
            clicked = doc
        else:
            reward, clicked = 0.0, -1
        self._t += 1
        done = self._t >= self.episode_len
        self._sample_docs()
        return self.observation(), reward, done, clicked

    def close(self):
        pass


class SlateQConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = SlateQ
        self.lr = 1e-3
        self.buffer_size = 20_000
        self.train_batch_size = 128
        self.learning_starts = 500
        self.target_update_freq = 1000   # env steps
        self.n_updates_per_iter = 24
        self.steps_per_iter = 200
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_timesteps = 5000
        self.hidden = (64, 64)


class SlateQ:
    """Decomposed slate Q-learning over the per-item Q network."""

    def __init__(self, config: SlateQConfig):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.rl.replay_buffer import ReplayBuffer
        from ray_tpu.rl.sample_batch import SampleBatch  # noqa: F401

        self.config = config
        self._env_ctor = config.env_spec if callable(config.env_spec) \
            else (InterestEvolutionEnv if config.env_spec is None
                  else None)
        env = self._env_ctor() if self._env_ctor is not None \
            else config.env_spec
        self.env = env
        self.k = env.slate_size
        self.n_cand = env.n_candidates
        self.n_topics = env.n_topics
        self.no_click_mass = env.no_click_mass

        class ItemQ(nn.Module):
            """Q(s, item): user state + doc topic + quality -> scalar."""
            hidden_: Tuple[int, ...]

            @nn.compact
            def __call__(self, user, docs, quality):
                # user [B, T]; docs [B, D, T]; quality [B, D]
                B, D, T = docs.shape
                u = jnp.broadcast_to(user[:, None, :], (B, D, T))
                x = jnp.concatenate([u, docs, quality[..., None]], -1)
                for i, h in enumerate(self.hidden_):
                    x = nn.relu(nn.Dense(h, name=f"fc_{i}")(x))
                return nn.Dense(1, name="q")(x)[..., 0]   # [B, D]

        self.model = ItemQ(hidden_=tuple(config.hidden))
        self.params = self.model.init(
            jax.random.PRNGKey(config.seed or 0),
            jnp.zeros((1, self.n_topics)),
            jnp.zeros((1, self.n_cand, self.n_topics)),
            jnp.zeros((1, self.n_cand)))["params"]
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.tx = optax.chain(optax.clip_by_global_norm(config.grad_clip),
                              optax.adam(config.lr))
        self.opt_state = self.tx.init(self.params)
        self.buffer = ReplayBuffer(config.buffer_size, seed=config.seed)

        model, tx = self.model, self.tx
        gamma = config.gamma
        k, no_click = self.k, self.no_click_mass

        def slate_value(q_items, docs, user):
            """Slate value sum_i P(i|slate) q_i for the slate chosen by
            Ie et al.'s Top-K heuristic (rank by v_i * q_i). The exact
            conditional-logit optimum needs their threshold binary
            search (top-k of v_i*(q_i - t)); Top-K is the paper's
            recommended fast approximation and what acting uses too, so
            the TD target matches the behavior policy's slate family."""
            scores = jnp.exp(jnp.einsum("bdt,bt->bd", docs, user))
            weighted = scores * q_items
            top_w, top_idx = jax.lax.top_k(weighted, k)
            top_s = jnp.take_along_axis(scores, top_idx, axis=-1)
            return top_w.sum(-1) / (top_s.sum(-1) + no_click)

        def loss_fn(params, target_params, batch):
            q = model.apply({"params": params}, batch["user"],
                            batch["docs"], batch["quality"])   # [B, D]
            # TD target: r + gamma * V(next) with V from the target net's
            # optimal decomposed slate value
            q_next = model.apply({"params": target_params},
                                 batch["next_user"], batch["next_docs"],
                                 batch["next_quality"])
            v_next = slate_value(q_next, batch["next_docs"],
                                 batch["next_user"])
            not_done = 1.0 - batch["dones"]
            y = batch["rewards"] + gamma * not_done * \
                jax.lax.stop_gradient(v_next)
            # only the clicked item's Q trains (clicked == -1 -> no-op;
            # SlateQ's SARSA-on-clicks decomposition)
            clicked = batch["clicked"].astype(jnp.int32)
            has_click = (clicked >= 0).astype(jnp.float32)
            safe = jnp.maximum(clicked, 0)
            q_clicked = jnp.take_along_axis(q, safe[:, None],
                                            axis=-1)[:, 0]
            err = jnp.square(q_clicked - y) * has_click
            denom = jnp.maximum(has_click.sum(), 1.0)
            return err.sum() / denom, {"mean_q": q.mean()}

        @jax.jit
        def td_step(params, target_params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["loss"] = loss
            aux["grad_norm"] = optax.global_norm(grads)
            return params, opt_state, aux

        @jax.jit
        def greedy_slate(params, user, docs, quality):
            q = model.apply({"params": params}, user[None], docs[None],
                            quality[None])[0]
            scores = jnp.exp(docs @ user)
            _, idx = jax.lax.top_k(scores * q, k)
            return idx

        self._td_step = td_step
        self._greedy_slate = greedy_slate
        self._jnp = jnp
        self._jax = jax
        self._np_rng = np.random.default_rng(config.seed or 0)
        self.iteration = 0
        self._timesteps_total = 0
        self._episodes_total = 0
        self._steps_since_sync = 0
        self._reward_window: List[float] = []
        self._obs = self.env.reset(seed=config.seed or 0)
        self._ep_reward = 0.0

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(self._timesteps_total / max(cfg.epsilon_timesteps, 1),
                   1.0)
        return cfg.epsilon_initial + frac * (
            cfg.epsilon_final - cfg.epsilon_initial)

    def _slate(self, obs, explore: bool) -> np.ndarray:
        if explore and self._np_rng.random() < self._epsilon():
            return self._np_rng.choice(self.n_cand, self.k, replace=False)
        jnp = self._jnp
        return np.asarray(self._greedy_slate(
            self.params, jnp.asarray(obs["user"]),
            jnp.asarray(obs["docs"]), jnp.asarray(obs["quality"])))

    def train(self) -> Dict[str, Any]:
        from ray_tpu.rl.sample_batch import SampleBatch
        cfg = self.config
        jnp = self._jnp
        rows: Dict[str, List[Any]] = {k: [] for k in (
            "user", "docs", "quality", "rewards", "clicked", "next_user",
            "next_docs", "next_quality", "dones")}
        for _ in range(cfg.steps_per_iter):
            slate = self._slate(self._obs, explore=True)
            nobs, r, done, clicked = self.env.step(slate)
            rows["user"].append(self._obs["user"])
            rows["docs"].append(self._obs["docs"])
            rows["quality"].append(self._obs["quality"])
            rows["rewards"].append(np.float32(r))
            rows["clicked"].append(np.int32(clicked))
            rows["next_user"].append(nobs["user"])
            rows["next_docs"].append(nobs["docs"])
            rows["next_quality"].append(nobs["quality"])
            rows["dones"].append(np.float32(done))
            self._ep_reward += r
            self._timesteps_total += 1
            self._steps_since_sync += 1
            self._obs = nobs
            if done:
                self._reward_window.append(self._ep_reward)
                self._episodes_total += 1
                self._ep_reward = 0.0
                self._obs = self.env.reset()
        self._reward_window = self._reward_window[-100:]
        self.buffer.add(SampleBatch(
            {k: np.stack(v) for k, v in rows.items()}))

        info: Dict[str, Any] = {"epsilon": self._epsilon(),
                                "buffer_size": len(self.buffer)}
        aux: Dict[str, Any] = {}
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.n_updates_per_iter):
                sample = self.buffer.sample(cfg.train_batch_size)
                batch = {k: jnp.asarray(v) for k, v in sample.items()}
                self.params, self.opt_state, aux = self._td_step(
                    self.params, self.target_params, self.opt_state,
                    batch)
            info.update({k: float(v) for k, v in aux.items()})
        if self._steps_since_sync >= cfg.target_update_freq:
            self.target_params = self._jax.tree.map(jnp.copy, self.params)
            self._steps_since_sync = 0
            info["target_synced"] = True
        self.iteration += 1
        return {"info": info, "training_iteration": self.iteration,
                "timesteps_total": self._timesteps_total,
                "episodes_total": self._episodes_total,
                "episode_reward_mean": float(
                    np.mean(self._reward_window))
                if self._reward_window else float("nan")}

    def evaluate(self, episodes: int = 10) -> float:
        # dedicated env when a ctor exists (same parameters as training);
        # else fall back to the shared instance and restore its state
        env = self._env_ctor() if self._env_ctor is not None else self.env
        totals = []
        for ep in range(episodes):
            obs = env.reset(seed=9000 + ep)
            total, done = 0.0, False
            while not done:
                slate = self._slate(obs, explore=False)
                obs, r, done, _ = env.step(slate)
                total += r
            totals.append(total)
        if env is self.env:
            self._obs = self.env.reset()
            self._ep_reward = 0.0
        else:
            env.close()
        return float(np.mean(totals))

    def get_weights(self) -> Any:
        return self._jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = self._jax.tree.map(self._jnp.asarray, weights)
        self.target_params = self._jax.tree.map(self._jnp.copy,
                                                self.params)

    def save(self) -> Checkpoint:
        return Checkpoint.from_dict({
            "weights": self.get_weights(), "iteration": self.iteration,
            "timesteps_total": self._timesteps_total,
            "episodes_total": self._episodes_total})

    def restore(self, checkpoint: Checkpoint) -> None:
        d = checkpoint.to_dict()
        self.set_weights(d["weights"])
        self.iteration = d.get("iteration", 0)
        self._timesteps_total = d.get("timesteps_total", 0)
        self._episodes_total = d.get("episodes_total", 0)

    def stop(self) -> None:
        self.env.close()
