"""Dreamer: world-model RL — learn latent dynamics, act by imagination.

Analog of /root/reference/rllib/algorithms/dreamer/dreamer.py (Hafner et
al.): an RSSM world model (deterministic GRU path + stochastic latent)
trained on replayed sequences by reconstruction + reward prediction +
KL, and an actor-critic trained entirely inside the model — latent
trajectories "dreamed" forward with lambda-return targets, gradients
flowing through the learned dynamics. This implementation targets the
repo's low-dimensional state envs (the reference's DreamerV1 targets
DMC pixels; the dense decoder replaces its conv decoder — same losses,
same imagination machinery). Continuous actions (tanh).

Everything — model update and imagination update — is two jitted
programs; sequence collection runs on the driver env.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rl.algorithm import AlgorithmConfig
from ray_tpu.rl.env import Box, make_env


class DreamerConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = Dreamer
        self.deter_size = 64            # GRU (deterministic) state
        self.stoch_size = 16            # stochastic latent
        self.model_hidden = 64
        self.model_lr = 3e-4
        self.actor_lr = 4e-5
        self.critic_lr = 1e-4
        self.free_nats = 1.0
        self.kl_scale = 1.0
        self.imagine_horizon = 10
        self.lambda_ = 0.95
        self.seq_len = 25
        self.batch_seqs = 16
        self.buffer_size = 500          # stored sequences
        self.learning_starts = 32
        self.n_updates_per_iter = 20
        self.steps_per_iter = 250
        self.expl_noise = 0.3


class Dreamer:
    def __init__(self, config: DreamerConfig):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.rl.replay_buffer import ReplayBuffer
        from ray_tpu.rl.sample_batch import SampleBatch  # noqa: F401

        self.config = config
        env = make_env(config.env_spec)
        if not isinstance(env.action_space, Box):
            raise ValueError("Dreamer requires a continuous action space")
        self.env = env
        self.act_dim = int(np.prod(env.action_space.shape))
        self.obs_dim = int(np.prod(env.observation_space.shape))
        low = np.asarray(env.action_space.low, np.float32).reshape(-1)
        high = np.asarray(env.action_space.high, np.float32).reshape(-1)
        self._scale = (high - low) / 2.0
        self._shift = (high + low) / 2.0
        D, S, H = config.deter_size, config.stoch_size, config.model_hidden
        A = self.act_dim

        class RSSM(nn.Module):
            """prior:  (h, z, a) -> h' -> p(z');  posterior: (h', obs)."""

            def setup(self):
                self.cell = nn.GRUCell(D)
                self.inp = nn.Dense(H)
                self.prior_net = nn.Sequential(
                    [nn.Dense(H), nn.relu, nn.Dense(2 * S)])
                self.post_net = nn.Sequential(
                    [nn.Dense(H), nn.relu, nn.Dense(2 * S)])

            def step_prior(self, h, z, a):
                x = nn.relu(self.inp(jnp.concatenate([z, a], -1)))
                h, _ = self.cell(h, x)
                stats = self.prior_net(h)
                mean, std = jnp.split(stats, 2, -1)
                std = nn.softplus(std) + 0.1
                return h, mean, std

            def posterior(self, h, obs):
                stats = self.post_net(jnp.concatenate([h, obs], -1))
                mean, std = jnp.split(stats, 2, -1)
                std = nn.softplus(std) + 0.1
                return mean, std

        class Heads(nn.Module):
            obs_dim_: int

            @nn.compact
            def __call__(self, feat):
                obs = nn.Sequential([nn.Dense(H), nn.relu,
                                     nn.Dense(self.obs_dim_)],
                                    name="obs_dec")(feat)
                reward = nn.Sequential([nn.Dense(H), nn.relu,
                                        nn.Dense(1)],
                                       name="reward_dec")(feat)[..., 0]
                return obs, reward

        class Actor(nn.Module):
            @nn.compact
            def __call__(self, feat):
                x = nn.relu(nn.Dense(H, name="fc")(feat))
                return nn.tanh(nn.Dense(A, name="out")(x))

        class Critic(nn.Module):
            @nn.compact
            def __call__(self, feat):
                x = nn.relu(nn.Dense(H, name="fc")(feat))
                return nn.Dense(1, name="out")(x)[..., 0]

        self.rssm = RSSM()
        self.heads = Heads(obs_dim_=self.obs_dim)
        self.actor = Actor()
        self.critic = Critic()
        rng = jax.random.PRNGKey(config.seed or 0)
        r1, r2, r3, r4 = jax.random.split(rng, 4)
        h0 = jnp.zeros((1, D))
        z0 = jnp.zeros((1, S))
        a0 = jnp.zeros((1, A))
        obs0 = jnp.zeros((1, self.obs_dim))
        rssm_params = self.rssm.init(
            r1, h0, z0, a0, method=RSSM.step_prior)["params"]
        # posterior params too: init with a combined dummy trace
        post_params = self.rssm.init(
            r2, h0, obs0, method=RSSM.posterior)["params"]
        rssm_params = {**post_params, **rssm_params}
        feat0 = jnp.zeros((1, D + S))
        self.params = {
            "rssm": rssm_params,
            "heads": self.heads.init(r2, feat0)["params"],
            "actor": self.actor.init(r3, feat0)["params"],
            "critic": self.critic.init(r4, feat0)["params"],
        }
        self.model_tx = optax.chain(optax.clip_by_global_norm(100.0),
                                    optax.adam(config.model_lr))
        self.actor_tx = optax.adam(config.actor_lr)
        self.critic_tx = optax.adam(config.critic_lr)
        self.opt = {
            "model": self.model_tx.init(
                {"rssm": self.params["rssm"],
                 "heads": self.params["heads"]}),
            "actor": self.actor_tx.init(self.params["actor"]),
            "critic": self.critic_tx.init(self.params["critic"]),
        }
        self.buffer = ReplayBuffer(config.buffer_size, seed=config.seed)

        rssm, heads, actor, critic = (self.rssm, self.heads, self.actor,
                                      self.critic)
        free_nats, kl_scale = config.free_nats, config.kl_scale
        horizon, lam, gamma = (config.imagine_horizon, config.lambda_,
                               config.gamma)

        def kl_div(m1, s1, m2, s2):
            return (jnp.log(s2 / s1)
                    + (s1 ** 2 + (m1 - m2) ** 2) / (2 * s2 ** 2)
                    - 0.5).sum(-1)

        def observe(rssm_p, obs_seq, act_seq, rng):
            """Filter a [B, T, ...] sequence into posterior latents."""
            B = obs_seq.shape[0]
            h = jnp.zeros((B, D))
            z = jnp.zeros((B, S))

            def step(carry, xs):
                h, z, key = carry
                obs_t, act_prev = xs
                h, pm, ps = rssm.apply({"params": rssm_p}, h, z, act_prev,
                                       method=RSSM.step_prior)
                qm, qs = rssm.apply({"params": rssm_p}, h, obs_t,
                                    method=RSSM.posterior)
                key, sub = jax.random.split(key)
                z = qm + qs * jax.random.normal(sub, qm.shape)
                return (h, z, key), (h, z, pm, ps, qm, qs)

            xs = (jnp.swapaxes(obs_seq, 0, 1),
                  jnp.swapaxes(act_seq, 0, 1))
            (_, _, _), outs = jax.lax.scan(step, (h, z, rng), xs)
            return [jnp.swapaxes(o, 0, 1) for o in outs]  # [B, T, ...]

        def model_loss(model_p, batch, rng):
            hs, zs, pm, ps, qm, qs = observe(
                model_p["rssm"], batch["obs"], batch["prev_actions"], rng)
            feat = jnp.concatenate([hs, zs], -1)
            obs_hat, reward_hat = heads.apply(
                {"params": model_p["heads"]}, feat)
            recon = jnp.square(obs_hat - batch["obs"]).sum(-1).mean()
            mask = batch["reward_mask"]
            rew = (mask * jnp.square(reward_hat - batch["rewards"])
                   ).sum() / jnp.maximum(mask.sum(), 1.0)
            kl = jnp.maximum(kl_div(qm, qs, pm, ps), free_nats).mean()
            loss = recon + rew + kl_scale * kl
            return loss, (feat, {"recon_loss": recon, "reward_loss": rew,
                                 "kl": kl, "model_loss": loss})

        def imagine(rssm_p, actor_p, feat_flat, rng):
            """Dream forward from posterior states with the actor."""
            h, z = jnp.split(feat_flat, [D], -1)

            def step(carry, key):
                h, z = carry
                a = actor.apply({"params": actor_p},
                                jnp.concatenate([h, z], -1))
                h, pm, ps = rssm.apply({"params": rssm_p}, h, z, a,
                                       method=RSSM.step_prior)
                z = pm + ps * jax.random.normal(key, pm.shape)
                return (h, z), jnp.concatenate([h, z], -1)

            keys = jax.random.split(rng, horizon)
            _, feats = jax.lax.scan(step, (h, z), keys)
            return feats                                  # [Hz, N, D+S]

        def lambda_returns(rewards, values):
            def step(nxt, xs):
                r, v_next = xs
                ret = r + gamma * ((1 - lam) * v_next + lam * nxt)
                return ret, ret
            last = values[-1]
            _, rets = jax.lax.scan(
                step, last, (rewards[:-1], values[1:]), reverse=True)
            return rets                                   # [Hz-1, N]

        def actor_loss(actor_p, model_p, critic_p, feat_flat, rng):
            feats = imagine(model_p["rssm"], actor_p, feat_flat, rng)
            _, rewards = heads.apply({"params": model_p["heads"]}, feats)
            values = critic.apply({"params": critic_p}, feats)
            rets = lambda_returns(rewards, values)
            return -rets.mean(), (jax.lax.stop_gradient(feats),
                                  jax.lax.stop_gradient(rets))

        def joint_update(params, opt, batch, rng):
            r1, r2 = jax.random.split(rng)
            model_p = {"rssm": params["rssm"], "heads": params["heads"]}
            (m_loss, (feat, m_aux)), m_grads = jax.value_and_grad(
                model_loss, has_aux=True)(model_p, batch, r1)
            m_updates, model_opt = self.model_tx.update(
                m_grads, opt["model"], model_p)
            model_p = optax.apply_updates(model_p, m_updates)

            feat_flat = jax.lax.stop_gradient(
                feat.reshape(-1, D + S))
            (a_loss, (im_feats, rets)), a_grads = jax.value_and_grad(
                actor_loss, has_aux=True)(
                params["actor"], model_p, params["critic"], feat_flat, r2)
            a_updates, actor_opt = self.actor_tx.update(
                a_grads, opt["actor"], params["actor"])
            actor_p = optax.apply_updates(params["actor"], a_updates)

            def critic_loss(cp):
                v = critic.apply({"params": cp}, im_feats[:-1])
                return jnp.square(v - rets).mean()

            c_loss, c_grads = jax.value_and_grad(critic_loss)(
                params["critic"])
            c_updates, critic_opt = self.critic_tx.update(
                c_grads, opt["critic"], params["critic"])
            critic_p = optax.apply_updates(params["critic"], c_updates)

            new_params = {"rssm": model_p["rssm"],
                          "heads": model_p["heads"],
                          "actor": actor_p, "critic": critic_p}
            new_opt = {"model": model_opt, "actor": actor_opt,
                       "critic": critic_opt}
            aux = dict(m_aux)
            aux["actor_loss"] = a_loss
            aux["critic_loss"] = c_loss
            return new_params, new_opt, aux

        @jax.jit
        def update(params, opt, batch, rng):
            return joint_update(params, opt, batch, rng)

        @jax.jit
        def policy_step(params, h, z, obs, prev_a, rng):
            """Filter one real step, then act from the posterior."""
            h, _, _ = rssm.apply({"params": params["rssm"]}, h, z, prev_a,
                                 method=RSSM.step_prior)
            qm, qs = rssm.apply({"params": params["rssm"]}, h, obs,
                                method=RSSM.posterior)
            z = qm + qs * jax.random.normal(rng, qm.shape)
            a = actor.apply({"params": params["actor"]},
                            jnp.concatenate([h, z], -1))
            return h, z, a

        self._update = update
        self._policy_step = policy_step
        self._jnp = jnp
        self._jax = jax
        self._rng = jax.random.PRNGKey((config.seed or 0) + 7)
        self._np_rng = np.random.default_rng(config.seed or 0)
        self.iteration = 0
        self._timesteps_total = 0
        self._episodes_total = 0
        self._reward_window: List[float] = []
        self.D, self.S = D, S
        self._reset_episode_state()

    def _reset_episode_state(self):
        jnp = self._jnp
        self._episode_seed = getattr(self, "_episode_seed", -1) + 1
        self._obs, _ = self.env.reset(
            seed=(self.config.seed or 0) * 100_000 + self._episode_seed)
        self._h = jnp.zeros((1, self.D))
        self._z = jnp.zeros((1, self.S))
        self._prev_a = np.zeros(self.act_dim, np.float32)
        self._ep_reward = 0.0
        self._ep_obs: List[np.ndarray] = []
        self._ep_act: List[np.ndarray] = []
        self._ep_rew: List[float] = []

    def _act(self, explore: bool) -> np.ndarray:
        jnp = self._jnp
        self._rng, key = self._jax.random.split(self._rng)
        self._h, self._z, a = self._policy_step(
            self.params, self._h, self._z,
            jnp.asarray(np.asarray(self._obs, np.float32))[None],
            jnp.asarray(self._prev_a)[None], key)
        a = np.asarray(a)[0]
        if explore:
            a = np.clip(a + self.config.expl_noise *
                        self._np_rng.standard_normal(a.shape), -1, 1)
        return a

    def _store_episode(self):
        """Chop the finished episode into fixed-length training rows."""
        from ray_tpu.rl.sample_batch import SampleBatch
        L = self.config.seq_len
        T = len(self._ep_rew)
        if T + 1 < L:
            return
        # include the post-step terminal observation so every reward —
        # including the episode's last (the only one in sparse tasks) —
        # has a feat to be predicted from: feat_t embeds a_{t-1}, so the
        # reward head is trained on a_{t-1}'s reward, and r_{T-1} aligns
        # at feat_T (built from the terminal obs)
        obs = np.stack(self._ep_obs
                       + [np.asarray(self._obs, np.float32)])  # [T+1, obs]
        acts = np.stack(self._ep_act)                          # [T, A]
        prev = np.concatenate([np.zeros((1, self.act_dim), np.float32),
                               acts], 0)                       # [T+1, A]
        rews = np.concatenate(
            [np.zeros(1, np.float32),
             np.asarray(self._ep_rew, np.float32)])            # [T+1]
        # row 0 has no previous action: its zero reward is synthetic and
        # must not train the reward head
        mask = np.ones(T + 1, np.float32)
        mask[0] = 0.0
        rows = {"obs": [], "prev_actions": [], "rewards": [],
                "reward_mask": []}
        starts = list(range(0, T + 1 - L + 1, L))
        # anchor a final (possibly overlapping) window at the episode end:
        # without it the terminal obs and last reward — the point of the
        # T+1 extension, and the only reward in sparse tasks — are dropped
        # whenever T+1 isn't a multiple of L
        if starts[-1] != T + 1 - L:
            starts.append(T + 1 - L)
        for start in starts:
            rows["obs"].append(obs[start:start + L])
            rows["prev_actions"].append(prev[start:start + L])
            rows["rewards"].append(rews[start:start + L])
            rows["reward_mask"].append(mask[start:start + L])
        self.buffer.add(SampleBatch(
            {k: np.stack(v).astype(np.float32) for k, v in rows.items()}))

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        jnp = self._jnp
        for _ in range(cfg.steps_per_iter):
            a = self._act(explore=True)
            env_a = a * self._scale + self._shift
            obs, r, term, trunc, _ = self.env.step(env_a)
            self._ep_obs.append(np.asarray(self._obs, np.float32))
            self._ep_act.append(a.astype(np.float32))
            self._ep_rew.append(float(r))
            self._ep_reward += float(r)
            self._prev_a = a.astype(np.float32)
            self._obs = obs
            self._timesteps_total += 1
            if term or trunc:
                self._reward_window.append(self._ep_reward)
                self._episodes_total += 1
                self._store_episode()
                self._reset_episode_state()
        self._reward_window = self._reward_window[-50:]

        info: Dict[str, Any] = {"buffer_sequences": len(self.buffer)}
        aux: Dict[str, Any] = {}
        # gate on a full batch: a growing batch shape would recompile the
        # jitted model+imagination update once per intermediate size
        threshold = max(cfg.learning_starts, cfg.batch_seqs)
        if len(self.buffer) >= threshold:
            for _ in range(cfg.n_updates_per_iter):
                sample = self.buffer.sample(cfg.batch_seqs)
                batch = {k: jnp.asarray(v) for k, v in sample.items()}
                self._rng, key = self._jax.random.split(self._rng)
                self.params, self.opt, aux = self._update(
                    self.params, self.opt, batch, key)
            info.update({k: float(v) for k, v in aux.items()})
        self.iteration += 1
        return {"info": info, "training_iteration": self.iteration,
                "timesteps_total": self._timesteps_total,
                "episodes_total": self._episodes_total,
                "episode_reward_mean": float(
                    np.mean(self._reward_window))
                if self._reward_window else float("nan")}

    def get_weights(self) -> Any:
        return self._jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = self._jax.tree.map(self._jnp.asarray, weights)

    def save(self) -> Checkpoint:
        return Checkpoint.from_dict({
            "weights": self.get_weights(), "iteration": self.iteration,
            "timesteps_total": self._timesteps_total,
            "episodes_total": self._episodes_total})

    def restore(self, checkpoint: Checkpoint) -> None:
        d = checkpoint.to_dict()
        self.set_weights(d["weights"])
        self.iteration = d.get("iteration", 0)
        self._timesteps_total = d.get("timesteps_total", 0)
        self._episodes_total = d.get("episodes_total", 0)

    def stop(self) -> None:
        self.env.close()
