"""SimpleQ: the minimal DQN variant (no double-Q, no dueling, no PER).

Analog of /root/reference/rllib/algorithms/simple_q/simple_q.py — kept as
a distinct entry point because RLlib treats it as the pedagogical baseline
the full DQN is measured against. Implementation shares the DQN learner
with the extensions switched off.
"""

from __future__ import annotations

from ray_tpu.rl.dqn import DQN, DQNConfig


class SimpleQConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = SimpleQ
        self.double_q = False
        self.dueling = False
        self.prioritized_replay = False
        self.target_update_freq = 500


class SimpleQ(DQN):
    pass
