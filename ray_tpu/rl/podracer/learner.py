"""Podracer learner actor: the compiled-DAG step + weight publishing.

``step()`` is the DAG op — the executor compiles ``inp ->
learner.step.bind(inp)`` once, then every fragment travels a shm
channel write + one get: zero classic task submissions in steady state.
Weight publishing happens INSIDE ``step()`` (every
``podracer_sync_every_steps`` optimizer steps): ``ray_tpu.put`` + the
KV pointer bump are object/KV-plane operations issued from the learner
process, so a steady-state training loop moves the driver's
``ray_tpu_actor_tasks_submitted_total`` counter by exactly zero.

The loss/step math is built by the SAME module-level builders the
classic drivers use (``make_impala_sgd_step`` / ``make_ppo_sgd_step``),
so podracer and blocking training are numerically the same algorithm —
the data plane is the only thing that changed.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu._private.config import CONFIG
from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.podracer.weights import WeightPublisher

# fragment columns each algorithm's loss actually consumes — extra
# rollout columns stay host-side instead of riding device_put
_IMPALA_KEYS = (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.TERMINATEDS,
                SB.ACTION_LOGP, "bootstrap_obs")
_PPO_KEYS = (SB.OBS, SB.ACTIONS, SB.ACTION_LOGP, SB.ADVANTAGES,
             SB.VALUE_TARGETS, SB.VF_PREDS)


class LearnerActor:
    """Owns params/opt_state; steps on fragments; publishes weights."""

    def __init__(self, algo: str, config, weights_name: str):
        import jax.numpy as jnp
        from ray_tpu.rl.algorithm import init_actor_critic
        self.algo = algo
        self.config = config
        model, params, _, logp_fn, ent_fn = init_actor_critic(config)
        self.model = model
        if algo == "impala":
            from ray_tpu.rl.impala import (make_impala_optimizer,
                                           make_impala_sgd_step)
            self.tx = make_impala_optimizer(config)
            self._sgd_step = make_impala_sgd_step(
                model, logp_fn, ent_fn, self.tx, config)
            self._keys = _IMPALA_KEYS
        elif algo == "ppo":
            from ray_tpu.rl.ppo import make_ppo_optimizer, make_ppo_sgd_step
            self.tx = make_ppo_optimizer(config)
            self._sgd_step = make_ppo_sgd_step(
                model, logp_fn, ent_fn, self.tx, config)
            self._keys = _PPO_KEYS
        else:
            raise ValueError(
                f"podracer supports impala/ppo, got {algo!r}")
        self.params = params
        self.opt_state = self.tx.init(params)
        self._jnp = jnp
        self._publisher = WeightPublisher(weights_name)
        self._step_no = 0
        self._frames = 0
        self._sync_every = max(1, int(CONFIG.podracer_sync_every_steps))

    # --------------------------------------------------- classic methods
    def ready(self) -> bool:
        """Creation fence (the DAG compiler requires a live actor)."""
        return True

    def publish_now(self) -> int:
        """Initial version so the fleet rendezvous has weights to pull
        before the first learner step."""
        return self._publisher.publish(self.get_weights())

    def get_weights(self) -> Any:
        import jax
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        import jax
        self.params = jax.tree.map(self._jnp.asarray, weights)

    def get_state(self) -> dict:
        """Checkpoint envelope (same v2 protocol as Algorithm)."""
        from ray_tpu.rl.algorithm import full_training_state
        state = full_training_state(self) or {}
        state["_step_no"] = self._step_no
        state["_frames"] = self._frames
        return state

    def set_state(self, state: dict) -> int:
        from ray_tpu.rl.algorithm import apply_full_training_state
        self._step_no = int(state.pop("_step_no", 0))
        self._frames = int(state.pop("_frames", 0))
        apply_full_training_state(self, state)
        return self.publish_now()

    def stats(self) -> dict:
        return {"steps": self._step_no, "frames": self._frames,
                "weight_version": self._publisher.version,
                "weight_payload_nbytes":
                    self._publisher.last_payload_nbytes}

    # ---------------------------------------------------- compiled-DAG op
    def step(self, payload: Tuple[Any, dict]) -> dict:
        fragment, meta = payload
        jnp = self._jnp
        batch = {k: jnp.asarray(fragment[k]) for k in self._keys
                 if k in fragment}
        self.params, self.opt_state, aux = self._sgd_step(
            self.params, self.opt_state, batch)
        self._step_no += 1
        frames = int(np.asarray(fragment[SB.REWARDS]).size)
        self._frames += frames
        published = 0
        if self._step_no % self._sync_every == 0:
            published = self._publisher.publish(self.get_weights())
        return {"aux": {k: float(v) for k, v in aux.items()},
                "step": self._step_no,
                "frames": frames,
                "published_version": published,
                "weight_payload_nbytes":
                    self._publisher.last_payload_nbytes,
                "learner_ts": time.time()}


def learner_actor_class(num_cpus: float = 1.0, num_tpus: float = 0.0):
    import ray_tpu
    return ray_tpu.remote(num_cpus=num_cpus,
                          num_tpus=num_tpus)(LearnerActor)
