"""PodracerExecutor: streaming ingest + compiled-DAG learner + elastic fleet.

Data plane (docs/rl_podracer.md):

    rollout actor --stream()--> per-yield ObjectRefs --ingest thread-->
    bounded prefetch queue --main loop--> compiled DAG execute/get
                                             |
                                             +--> weight put() + KV bump
    rollout actor <--striped multi-source pull-- (between fragments)

* Each rollout actor runs ONE ``num_returns="streaming"`` generator for
  its whole lifetime; ``podracer_backpressure_fragments`` is stamped
  into the stream at submit time, bounding per-actor staleness.
* One ingest thread per actor drains item refs into a
  ``podracer_prefetch_depth``-bounded queue, overlapping fragment
  download/deserialization with the learner step.  A full queue blocks
  the thread, which stops acking the stream, which pauses the producer:
  backpressure propagates end to end with no polling.
* The learner step is a compiled DAG op (``inp -> learner.step``): the
  steady-state loop performs ZERO classic task submissions, asserted
  against ``ray_tpu_actor_tasks_submitted_total`` exactly like the
  MPMD pipeline runner.
* The fleet is elastic: a dead stream emits RL_ACTOR_LOST and a
  replacement rendezvous (pull current weights multi-source, new
  stream) runs on a side thread — the learner keeps consuming the
  survivors' fragments and never stalls beyond one backpressure
  window.  RL_ACTOR_JOINED closes the recovery-auditor episode.
"""

from __future__ import annotations

import queue
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu._private import runtime_metrics as rtm
from ray_tpu._private import step_stats
from ray_tpu._private.cluster_events import (RL_ACTOR_JOINED,
                                             RL_ACTOR_LOST, emit)
from ray_tpu._private.config import CONFIG
from ray_tpu.dag.dag_node import InputNode

_SUBMIT_METRIC = "ray_tpu_actor_tasks_submitted_total"

_M_FRAGMENTS = rtm.counter(
    "ray_tpu_rl_fragments_consumed_total",
    "Rollout fragments the podracer learner consumed.")
_M_FRAMES = rtm.counter(
    "ray_tpu_rl_env_frames_total",
    "Env frames (timesteps) trained on by podracer learners.")
_M_ADOPTION_S = rtm.histogram(
    "ray_tpu_rl_weight_adoption_s",
    "Weight version publish -> adopted by the whole live fleet (s): "
    "the end-to-end multi-source broadcast latency the bench tables.")
_M_REPLACEMENTS = rtm.counter(
    "ray_tpu_rl_actor_replacements_total",
    "Rollout actors replaced after stream loss (elastic fleet).")


def _actor_submit_count() -> Optional[float]:
    """Owner-process total of classic actor-task submissions, or None
    when runtime metrics are disabled (the zero-submission assert then
    degrades to unchecked)."""
    snap = rtm.snapshot().get(_SUBMIT_METRIC)
    if not snap:
        return None
    return float(sum((snap.get("values") or {}).values()))


def _fragment_nbytes(fragment) -> int:
    return sum(int(np.asarray(v).nbytes) for v in fragment.values())


class PodracerExecutor:
    """Sebulba-style learner–actor executor for IMPALA and PPO."""

    def __init__(self, algo: str, config, *,
                 strict_zero_submit: bool = True):
        from ray_tpu.rl.podracer.learner import learner_actor_class
        from ray_tpu.rl.podracer.rollout import podracer_actor_class
        self.algo = algo
        self.config = config
        self.run_id = f"podracer-{algo}-{uuid.uuid4().hex[:6]}"
        self.weights_name = self.run_id
        self._mode = "time_major" if algo == "impala" else "gae"
        self._strict_zero_submit = strict_zero_submit
        self._stopping = False
        self._lock = threading.Lock()
        self._replacing = 0     # in-flight replacement rendezvous

        depth = max(1, int(CONFIG.podracer_prefetch_depth))
        self._queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._window = int(CONFIG.podracer_backpressure_fragments)

        # --- learner -----------------------------------------------------
        self._learner_cls = learner_actor_class()
        self.learner = self._learner_cls.remote(
            algo, config, self.weights_name)
        ray_tpu.get(self.learner.ready.remote(), timeout=300.0)
        # version 1 exists before any actor rendezvous
        ray_tpu.get(self.learner.publish_now.remote(), timeout=300.0)
        self._publish_wall: Dict[int, float] = {1: time.time()}

        # --- fleet -------------------------------------------------------
        self._actor_cls = podracer_actor_class()
        self.num_actors = max(1, config.num_rollout_workers)
        self._slots: List[Dict[str, Any]] = [
            {"actor": None, "thread": None, "version": 0, "gen": None}
            for _ in range(self.num_actors)]
        for slot in range(self.num_actors):
            self._start_slot(slot)

        # --- telemetry ---------------------------------------------------
        self.telemetry: Dict[str, Any] = {
            "fragments": 0, "frames": 0, "learner_steps": 0,
            "replacements": 0, "versions_published": 1,
            "classic_submits_steady": 0.0 if _actor_submit_count()
            is not None else None,
            "weight_adoption_s": [],
        }
        self._episode_history: List[Dict[str, float]] = []
        self._dag = None
        self._pending: List[Tuple[Any, dict]] = []
        self._run = step_stats.start_run(
            self.run_id, group=f"podracer-{algo}",
            meta={"algo": algo, "actors": self.num_actors})
        self._clock = step_stats.step_clock()

    # ------------------------------------------------------------ fleet
    def _make_actor(self, slot: int):
        cfg = self.config
        return self._actor_cls.remote(
            cfg.env_spec, worker_index=slot,
            num_envs=cfg.num_envs_per_worker,
            rollout_fragment_length=cfg.rollout_fragment_length,
            gamma=cfg.gamma, lam=cfg.lam, hidden=cfg.hidden,
            seed=cfg.seed)

    def _start_slot(self, slot: int, *, rejoin: bool = False) -> dict:
        """Spawn the slot's actor, rendezvous (multi-source weight
        pull), and open its fragment stream."""
        actor = self._make_actor(slot)
        report = ray_tpu.get(
            actor.pull_weights.remote(self.weights_name), timeout=300.0)
        # the OWNER's config is stamped into the stream at submit time:
        # scope the override to this submission
        prev = CONFIG.generator_backpressure_num_objects
        CONFIG.set("generator_backpressure_num_objects",
                   self._window if self._window > 0 else -1)
        try:
            gen = actor.stream.options(num_returns="streaming").remote(
                self.weights_name, mode=self._mode)
        finally:
            CONFIG.set("generator_backpressure_num_objects", prev)
        st = self._slots[slot]
        st["actor"], st["gen"] = actor, gen
        st["version"] = int(report.get("weight_version", 0))
        thread = threading.Thread(
            target=self._ingest, args=(slot, gen),
            name=f"podracer-ingest-{slot}", daemon=True)
        st["thread"] = thread
        thread.start()
        if rejoin:
            emit(RL_ACTOR_JOINED,
                 f"rollout actor rejoined slot {slot}",
                 run_id=self.run_id, slot=slot,
                 weight_version=report.get("weight_version"),
                 weight_pull_ms=report.get("weight_pull_ms"))
        return report

    def _ingest(self, slot: int, gen) -> None:
        """Per-actor drain loop: stream item ref -> fragment -> queue.
        Runs until the stream ends (bounded runs), the executor stops,
        or the actor dies (-> loss marker; a replacement thread takes
        over the slot)."""
        try:
            for item_ref in gen:
                value = ray_tpu.get(item_ref)
                if not self._put(("frag", slot, value)):
                    return
            self._put(("end", slot, None))
        except Exception as e:  # stream died: actor lost
            if not self._stopping:
                self._put(("lost", slot, repr(e)))

    def _put(self, item) -> bool:
        """Bounded put that gives up when the executor is stopping (so
        ingest threads never deadlock against a gone consumer)."""
        while not self._stopping:
            try:
                self._queue.put(item, timeout=0.25)
                return True
            except queue.Full:
                continue
        return False

    def _replace_slot(self, slot: int) -> None:
        """Side-thread replacement: the learner keeps consuming other
        actors' fragments while the replacement rendezvous runs."""
        try:
            old = self._slots[slot]["actor"]
            if old is not None:
                try:
                    ray_tpu.kill(old)
                except Exception:
                    pass
            self._start_slot(slot, rejoin=True)
            with self._lock:
                self.telemetry["replacements"] += 1
                self._replacing -= 1
            _M_REPLACEMENTS.inc()
        except Exception:
            if not self._stopping:
                # retry once after a beat; a dead cluster stops anyway
                time.sleep(1.0)
                if not self._stopping:
                    self._replace_slot(slot)
                    return
            with self._lock:
                self._replacing -= 1

    # --------------------------------------------------------- ingestion
    def _next_fragment(self, timeout: float = 120.0):
        """(slot, fragment, meta) from the prefetch queue, transparently
        folding loss markers into replacement kicks."""
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"no fragment within {timeout}s "
                    f"(live actors: {self._live_actors()})")
            try:
                kind, slot, value = self._queue.get(timeout=min(
                    remaining, 1.0))
            except queue.Empty:
                continue
            if kind == "frag":
                return slot, value[0], value[1]
            if kind == "lost":
                emit(RL_ACTOR_LOST,
                     f"rollout stream {slot} died: {value}",
                     severity="WARNING", run_id=self.run_id,
                     slot=slot, reason=str(value)[:200])
                with self._lock:
                    self._replacing += 1
                threading.Thread(
                    target=self._replace_slot, args=(slot,),
                    name=f"podracer-replace-{slot}",
                    daemon=True).start()
                continue
            # "end": a bounded stream finished; nothing to do

    def _live_actors(self) -> int:
        return sum(1 for s in self._slots if s["actor"] is not None)

    # ---------------------------------------------------------- learner
    def _compile(self, payload) -> None:
        frag_bytes = _fragment_nbytes(payload[0])
        buf = max(1 << 16, 2 * frag_bytes + 16384)
        with InputNode() as inp:
            node = self.learner.step.bind(inp)
        self._dag = node.experimental_compile(
            max_inflight=2, buffer_size_bytes=buf,
            name=f"podracer-{self.algo}")

    def _observe_result(self, out: dict, meta: dict) -> None:
        t = self.telemetry
        t["learner_steps"] = out["step"]
        t["fragments"] += 1
        t["frames"] += out["frames"]
        _M_FRAGMENTS.inc()
        _M_FRAMES.inc(out["frames"])
        v = int(out.get("published_version") or 0)
        if v:
            t["versions_published"] = v
            self._publish_wall[v] = time.time()
        # fleet-wide adoption: version v is adopted when every live
        # slot's newest meta reports >= v
        slot = int(meta.get("worker_index", 0))
        if 0 <= slot < len(self._slots):
            self._slots[slot]["version"] = max(
                self._slots[slot]["version"],
                int(meta.get("weight_version", 0)))
        floor = min((s["version"] for s in self._slots
                     if s["actor"] is not None), default=0)
        for pv in sorted(self._publish_wall):
            if pv <= floor:
                lat = time.time() - self._publish_wall.pop(pv)
                t["weight_adoption_s"].append(lat)
                _M_ADOPTION_S.observe(lat)
        for ep in meta.get("episodes") or []:
            self._episode_history.append(ep)
        self._episode_history = self._episode_history[-100:]

    def train_iteration(self, num_steps: Optional[int] = None,
                        timeout: float = 120.0) -> Dict[str, Any]:
        """Consume ``num_steps`` fragments through the compiled learner.

        A two-deep software pipeline (matching the DAG's max_inflight)
        keeps one execute in flight while the previous result is
        fetched, so device upload overlaps the next dequeue."""
        n = num_steps or getattr(self.config, "batches_per_step", None)
        if not n:
            # PPO-style configs budget by frames, not fragments: consume
            # the same env-frame budget per iteration as the classic
            # executor's train_batch_size gather
            tb = getattr(self.config, "train_batch_size", 0)
            fl = getattr(self.config, "rollout_fragment_length", 0) or 1
            n = max(1, tb // fl) if tb else 4
        aux_last: Dict[str, Any] = {}
        inflight: List[Tuple[Any, dict]] = []
        c0 = c1 = None
        repl0 = self.telemetry["replacements"]
        for i in range(n):
            self._clock.begin()
            with self._clock.phase("dequeue_wait"):
                slot, frag, meta = self._next_fragment(timeout)
            if self._dag is None:
                self._compile((frag, meta))
            if i == 0:
                # steady-state window starts after compile (compile and
                # replacements legitimately submit classic tasks)
                c0 = _actor_submit_count()
            with self._clock.phase("learner_step"):
                inflight.append((self._dag.execute((frag, meta)), meta))
                if len(inflight) >= 2:
                    ref, m = inflight.pop(0)
                    out = ref.get(timeout=timeout)
                    aux_last = out["aux"]
                    self._observe_result(out, m)
            self._clock.end(tokens=int(np.asarray(frag["rewards"]).size))
        with self._clock.phase("learner_step"):
            for ref, m in inflight:
                out = ref.get(timeout=timeout)
                aux_last = out["aux"]
                self._observe_result(out, m)
        c1 = _actor_submit_count()
        with self._lock:
            replaced = (self.telemetry["replacements"] - repl0
                        + self._replacing)
        if c0 is not None and c1 is not None and not replaced:
            delta = c1 - c0
            self.telemetry["classic_submits_steady"] += delta
            if delta and self._strict_zero_submit:
                raise RuntimeError(
                    f"podracer steady-state loop issued {delta} classic "
                    "task submissions; the zero-submission contract is "
                    "broken (docs/rl_podracer.md)")
        info = dict(aux_last)
        info["batches_processed"] = n
        info["weight_version"] = self.telemetry["versions_published"]
        info["replacements"] = self.telemetry["replacements"]
        return {"info": info,
                "timesteps_this_iter": int(self.telemetry["frames"])}

    # ----------------------------------------------------------- driver
    def collect_episode_metrics(self) -> List[Dict[str, float]]:
        out = list(self._episode_history)
        return out

    def get_weights(self):
        return ray_tpu.get(self.learner.get_weights.remote(),
                           timeout=120.0)

    def set_weights(self, weights) -> None:
        ray_tpu.get(self.learner.set_weights.remote(weights),
                    timeout=120.0)

    def get_full_state(self):
        return ray_tpu.get(self.learner.get_state.remote(), timeout=120.0)

    def set_full_state(self, state) -> None:
        # the set_state publish bump makes every actor adopt the
        # restored weights at its next fragment boundary
        ray_tpu.get(self.learner.set_state.remote(state), timeout=120.0)

    def learner_stats(self) -> dict:
        return ray_tpu.get(self.learner.stats.remote(), timeout=120.0)

    def goodput_summary(self) -> Optional[dict]:
        run = self._run
        if run is None:
            return None
        return run.ledger.summary()

    def stop(self) -> None:
        self._stopping = True
        for st in self._slots:
            gen = st.get("gen")
            if gen is not None:
                try:
                    gen.close()
                except Exception:
                    pass
            if st["actor"] is not None:
                try:
                    ray_tpu.kill(st["actor"])
                except Exception:
                    pass
                st["actor"] = None
        if self._dag is not None:
            try:
                self._dag.teardown()
            except Exception:
                pass
            self._dag = None
        try:
            ray_tpu.kill(self.learner)
        except Exception:
            pass
        for st in self._slots:
            t = st.get("thread")
            if t is not None and t.is_alive():
                t.join(timeout=2.0)
        step_stats.end_run(self._run)
        self._run = None
