"""Podracer RL data plane (docs/rl_podracer.md).

Sebulba-style learner–actor executor (arXiv:2104.06272; RLAX
arXiv:2512.06392): free-running rollout actors stream fragments
per-yield with bounded staleness, the learner step runs as a compiled
DAG (zero steady-state task submissions), and weight versions
broadcast multi-source striped over the transfer plane.  IMPALA and
PPO ride it via ``config.podracer()``.
"""

from ray_tpu.rl.podracer.executor import PodracerExecutor
from ray_tpu.rl.podracer.learner import LearnerActor
from ray_tpu.rl.podracer.rollout import PodracerRolloutActor
from ray_tpu.rl.podracer.weights import (WeightFollower, WeightPublisher,
                                         decode_weights, encode_weights)

__all__ = [
    "PodracerExecutor", "LearnerActor", "PodracerRolloutActor",
    "WeightPublisher", "WeightFollower", "encode_weights",
    "decode_weights",
]
