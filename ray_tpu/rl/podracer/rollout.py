"""Streaming rollout actor: free-running fragments over one generator.

One ``stream()`` call per actor lifetime: the executor consumes it via
``num_returns="streaming"``, so every fragment arrives per-yield with
ZERO further task submissions — the generator backpressure window
(``podracer_backpressure_fragments``, stamped at submit time) pauses
env stepping when the learner falls behind, which is the staleness
contract: at most ``window`` unconsumed fragments ever separate an
actor's policy from the fragment the learner trains on.

Weight adoption happens BETWEEN fragments: the actor polls the KV
pointer (one GCS RPC — cheap against a fragment of env steps) and on a
version bump pulls the payload striped from every current holder.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterator, Optional, Tuple

from ray_tpu.rl.rollout_worker import RolloutWorker
from ray_tpu.rl.podracer.weights import WeightFollower


class PodracerRolloutActor(RolloutWorker):
    """RolloutWorker + the streaming/weight-follower surface."""

    def pull_weights(self, weights_name: str) -> Dict[str, Any]:
        """Rendezvous pull (join path): adopt the newest published
        version before the stream starts, so a replacement actor's
        first fragment is already on-policy.  Returns the adoption
        report the executor stamps into RL_ACTOR_JOINED."""
        self._follower = WeightFollower(weights_name)
        update = self._follower.poll()
        if update is not None:
            params, _ = update
            self.set_weights(params)
        return {"weight_version": self._follower.version,
                "weight_pull_ms": self._follower.last_pull_ms,
                "worker_index": self.worker_index}

    def stream(self, weights_name: str, *, mode: str = "time_major",
               max_fragments: int = 0) -> Iterator[Tuple[Any, dict]]:
        """Yield ``(fragment, meta)`` forever (or ``max_fragments``).

        ``mode``: "time_major" yields IMPALA's [T, N] dict fragments
        (V-trace corrects off-policyness on the learner); "gae" yields
        GAE-postprocessed SampleBatches (the podracer PPO path —
        advantages computed under the behavior policy, one version
        stale at most within the backpressure window).
        """
        follower = getattr(self, "_follower", None)
        if follower is None or follower.name != weights_name:
            follower = WeightFollower(weights_name)
        sample = (self.sample_time_major if mode == "time_major"
                  else self.sample)
        n = 0
        while max_fragments <= 0 or n < max_fragments:
            sync_ms = 0.0
            update = follower.poll()
            if update is not None:
                params, _ = update
                self.set_weights(params)
                sync_ms = follower.last_pull_ms
            t0 = time.perf_counter()
            fragment = sample()
            meta = {
                "worker_index": self.worker_index,
                "fragment_index": n,
                "weight_version": follower.version,
                "weight_sync_ms": sync_ms,
                "versions_skipped": follower.versions_skipped,
                "sample_ms": (time.perf_counter() - t0) * 1000.0,
                "yield_ts": time.time(),
                "episodes": self.get_metrics(),
            }
            yield fragment, meta
            n += 1


def podracer_actor_class(num_cpus: float = 1.0):
    """The remote class the executor instantiates per fleet slot."""
    import ray_tpu
    return ray_tpu.remote(num_cpus=num_cpus)(PodracerRolloutActor)
