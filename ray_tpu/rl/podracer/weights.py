"""Weight-version protocol: multi-source striped broadcast + KV pointer.

The learner publishes each fresh weight version exactly once —
``ray_tpu.put()`` of the (optionally int8-quantized) leaf payload —
and bumps a tiny pointer record in the internal KV
(``podracer/<name>/weights`` -> pickled ``{version, ref, ...}``).
Rollout actors poll the pointer at fragment boundaries (one cheap GCS
RPC) and, on a version bump, ``ray_tpu.get()`` the ref: the transfer
plane stripes the pull across every process already holding the object
(the owner reports each completed puller as a new source — the PR 6
store-routed broadcast mechanism), so sync latency grows sub-linearly
with actor count instead of multiplying the learner's egress.

Version-skip rule: the KV pointer only ever names the NEWEST version,
so a slow actor that missed versions N..N+k jumps straight to N+k+1 —
it never replays intermediate versions.  The publisher keeps the last
``podracer_weight_keep_versions`` refs pinned (an in-flight pull of a
just-superseded version still completes); older refs drop and the
store reclaims them.

Wire format: params trees are flattened to ``(path, leaf)`` pairs by
sorted key walk (nested dicts — the flax params layout).  With
``podracer_weight_quantize`` each float leaf ships as an Int8Codec
wire buffer (~4x fewer bytes, blockmax/254 round-trip error, the
PR 16 codec); non-float leaves always ship raw.
"""

from __future__ import annotations

import pickle
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu._private import runtime_metrics as rtm
from ray_tpu._private.config import CONFIG
from ray_tpu.util.collective.quant import Int8Codec

_M_PUBLISH_MS = rtm.histogram(
    "ray_tpu_rl_weight_publish_ms",
    "Learner-side weight-version publish latency (flatten + encode + "
    "put + KV bump, ms).")
_M_PULL_MS = rtm.histogram(
    "ray_tpu_rl_weight_pull_ms",
    "Actor-side weight pull latency (striped get + decode, ms).")
_M_VERSIONS = rtm.counter(
    "ray_tpu_rl_weight_versions_total",
    "Weight versions published by podracer learners.")
_M_SKIPPED = rtm.counter(
    "ray_tpu_rl_weight_versions_skipped_total",
    "Weight versions a follower jumped past (the version-skip rule): "
    "slow actors adopt the newest version, never replaying missed ones.")


def _kv_key(name: str) -> str:
    return f"podracer/{name}/weights"


def _flatten(tree: Any, prefix: str = "") -> List[Tuple[str, np.ndarray]]:
    """Nested dict -> sorted (path, array) leaves; deterministic order so
    publisher and follower agree without shipping a treedef object."""
    if isinstance(tree, dict):
        out: List[Tuple[str, np.ndarray]] = []
        for k in sorted(tree):
            out.extend(_flatten(tree[k], f"{prefix}/{k}"))
        return out
    return [(prefix, np.asarray(tree))]


def _unflatten(leaves: List[Tuple[str, np.ndarray]]) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    for path, arr in leaves:
        parts = path.strip("/").split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root


def encode_weights(params: Any, *, quantize: Optional[bool] = None,
                   block: Optional[int] = None) -> Dict[str, Any]:
    """Params tree -> the payload dict a weight version stores."""
    if quantize is None:
        quantize = CONFIG.podracer_weight_quantize
    block = int(block or CONFIG.collective_quant_block)
    leaves = _flatten(params)
    if not quantize:
        return {"codec": None,
                "leaves": [(p, np.ascontiguousarray(a))
                           for p, a in leaves]}
    codec = Int8Codec(block)
    enc = []
    for path, arr in leaves:
        if arr.dtype.kind != "f":
            enc.append((path, None, arr.shape, arr.dtype.str,
                        np.ascontiguousarray(arr)))
            continue
        enc.append((path, "int8", arr.shape, arr.dtype.str,
                    codec.encode(arr.reshape(-1))))
    return {"codec": "int8", "block": block, "leaves": enc}


def decode_weights(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Payload dict -> params tree (fresh arrays, never payload views)."""
    if payload.get("codec") is None:
        return _unflatten(payload["leaves"])
    codec = Int8Codec(int(payload["block"]))
    leaves = []
    for path, kind, shape, dtype, wire in payload["leaves"]:
        if kind is None:
            leaves.append((path, wire))
            continue
        nelem = int(np.prod(shape)) if shape else 1
        arr = codec.decode(wire, nelem, np.dtype(dtype)).reshape(shape)
        leaves.append((path, arr))
    return _unflatten(leaves)


def payload_nbytes(payload: Dict[str, Any]) -> int:
    """Broadcast bytes of one version (the bench's wire-savings row)."""
    total = 0
    for leaf in payload["leaves"]:
        total += int(np.asarray(leaf[-1]).nbytes)
    return total


class WeightPublisher:
    """Learner side: one put() + one KV bump per version."""

    def __init__(self, name: str, *, quantize: Optional[bool] = None,
                 block: Optional[int] = None,
                 keep_versions: Optional[int] = None):
        self.name = name
        self._quantize = (CONFIG.podracer_weight_quantize
                          if quantize is None else bool(quantize))
        self._block = int(block or CONFIG.collective_quant_block)
        keep = (CONFIG.podracer_weight_keep_versions
                if keep_versions is None else keep_versions)
        self._keep = max(1, int(keep))
        # version -> ref: holding the ref pins the object; dropping it
        # releases the store copy (version-skip makes that safe)
        self._refs: "OrderedDict[int, Any]" = OrderedDict()
        self.version = 0
        self.last_payload_nbytes = 0

    def publish(self, params: Any) -> int:
        import ray_tpu
        from ray_tpu.experimental.internal_kv import _internal_kv_put
        t0 = time.perf_counter()
        payload = encode_weights(params, quantize=self._quantize,
                                 block=self._block)
        self.last_payload_nbytes = payload_nbytes(payload)
        ref = ray_tpu.put(payload)
        self.version += 1
        self._refs[self.version] = ref
        while len(self._refs) > self._keep:
            self._refs.popitem(last=False)
        record = {"version": self.version, "ref": ref,
                  "nbytes": self.last_payload_nbytes,
                  "published_ts": time.time()}
        _internal_kv_put(_kv_key(self.name), pickle.dumps(record))
        _M_PUBLISH_MS.observe((time.perf_counter() - t0) * 1000.0)
        _M_VERSIONS.inc()
        return self.version

    def clear(self) -> None:
        from ray_tpu.experimental.internal_kv import _internal_kv_del
        self._refs.clear()
        try:
            _internal_kv_del(_kv_key(self.name))
        except Exception:
            pass


class WeightFollower:
    """Actor side: poll the KV pointer, pull striped on a version bump."""

    def __init__(self, name: str, *, pull_timeout_s: float = 60.0):
        self.name = name
        self.version = 0
        self.versions_skipped = 0
        self.last_pull_ms = 0.0
        self._pull_timeout_s = float(pull_timeout_s)

    def poll(self) -> Optional[Tuple[Dict[str, Any], int]]:
        """(params, version) when a newer version exists, else None."""
        import ray_tpu
        from ray_tpu.experimental.internal_kv import _internal_kv_get
        raw = _internal_kv_get(_kv_key(self.name))
        if not raw:
            return None
        record = pickle.loads(raw)
        version = int(record["version"])
        if version <= self.version:
            return None
        t0 = time.perf_counter()
        payload = ray_tpu.get(record["ref"],
                              timeout=self._pull_timeout_s)
        params = decode_weights(payload)
        self.last_pull_ms = (time.perf_counter() - t0) * 1000.0
        _M_PULL_MS.observe(self.last_pull_ms)
        if self.version > 0 and version > self.version + 1:
            skipped = version - self.version - 1
            self.versions_skipped += skipped
            _M_SKIPPED.inc(skipped)
        self.version = version
        return params, version
