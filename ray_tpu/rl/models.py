"""Policy/value networks in flax.

Analog of the reference's ModelCatalog defaults
(/root/reference/rllib/models/catalog.py: fcnet 2x256 tanh) — but flax
modules whose apply is jitted into the learner step; the same params run
on CPU in rollout workers and sharded on the TPU mesh in the learner.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class ActorCritic(nn.Module):
    """Shared-nothing actor & critic MLP towers (rllib default
    vf_share_layers=False)."""

    action_dim: int
    hidden: Sequence[int] = (256, 256)
    continuous: bool = False

    @nn.compact
    def __call__(self, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = obs
        for i, h in enumerate(self.hidden):
            x = nn.tanh(nn.Dense(h, name=f"pi_{i}")(x))
        if self.continuous:
            mean = nn.Dense(self.action_dim, name="pi_mean")(x)
            log_std = self.param("pi_log_std", nn.initializers.zeros,
                                 (self.action_dim,))
            logits = jnp.concatenate(
                [mean, jnp.broadcast_to(log_std, mean.shape)], axis=-1)
        else:
            logits = nn.Dense(self.action_dim, name="pi_out")(x)
        v = obs
        for i, h in enumerate(self.hidden):
            v = nn.tanh(nn.Dense(h, name=f"vf_{i}")(v))
        value = nn.Dense(1, name="vf_out")(v)[..., 0]
        return logits, value


def categorical_sample(rng, logits):
    return jax.random.categorical(rng, logits, axis=-1)


def categorical_logp(logits, actions):
    logp = jax.nn.log_softmax(logits)
    return jnp.take_along_axis(logp, actions[..., None].astype(jnp.int32),
                               axis=-1)[..., 0]


def categorical_entropy(logits):
    logp = jax.nn.log_softmax(logits)
    return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


def diag_gaussian_sample(rng, logits):
    mean, log_std = jnp.split(logits, 2, axis=-1)
    noise = jax.random.normal(rng, mean.shape)
    return mean + noise * jnp.exp(log_std)


def diag_gaussian_logp(logits, actions):
    mean, log_std = jnp.split(logits, 2, axis=-1)
    var = jnp.exp(2 * log_std)
    logp = -0.5 * (jnp.square(actions - mean) / var
                   + 2 * log_std + jnp.log(2 * jnp.pi))
    return jnp.sum(logp, axis=-1)


def diag_gaussian_entropy(logits):
    _, log_std = jnp.split(logits, 2, axis=-1)
    return jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)


class QNetwork(nn.Module):
    """MLP state-action value head for DQN-family algorithms
    (cf. reference rllib/algorithms/dqn/dqn_torch_model.py; dueling
    decomposition Q = V + A - mean(A) when ``dueling``)."""

    action_dim: int
    hidden: Sequence[int] = (256, 256)
    dueling: bool = True

    @nn.compact
    def __call__(self, obs: jax.Array) -> jax.Array:
        x = obs
        for i, h in enumerate(self.hidden):
            x = nn.relu(nn.Dense(h, name=f"q_{i}")(x))
        adv = nn.Dense(self.action_dim, name="q_out")(x)
        if not self.dueling:
            return adv
        v = nn.Dense(1, name="v_out")(x)
        return v + adv - jnp.mean(adv, axis=-1, keepdims=True)


class RecurrentQNetwork(nn.Module):
    """LSTM Q-network for R2D2 (cf. reference rllib/algorithms/r2d2 +
    rllib/models/torch/recurrent_net.py): obs -> MLP -> LSTM -> Q values.

    __call__ operates on [B, T, obs] sequences with an explicit carry;
    ``initial_state(batch)`` builds the zero carry.
    """

    action_dim: int
    hidden: Sequence[int] = (64,)
    lstm_size: int = 64

    @nn.compact
    def __call__(self, obs_seq: jax.Array, carry):
        x = obs_seq
        for i, h in enumerate(self.hidden):
            x = nn.relu(nn.Dense(h, name=f"fc_{i}")(x))
        rnn = nn.RNN(nn.OptimizedLSTMCell(self.lstm_size), name="lstm",
                     return_carry=True)
        carry, outs = rnn(x, initial_carry=carry)
        q = nn.Dense(self.action_dim, name="q_out")(outs)
        return q, carry

    @nn.nowrap
    def initial_state(self, batch_size: int):
        shape = (batch_size, self.lstm_size)
        return (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))


class SquashedGaussianActor(nn.Module):
    """SAC actor: tanh-squashed diagonal Gaussian (cf. reference
    rllib/algorithms/sac/sac_torch_model.py policy head)."""

    action_dim: int
    hidden: Sequence[int] = (256, 256)
    log_std_min: float = -20.0
    log_std_max: float = 2.0

    @nn.compact
    def __call__(self, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = obs
        for i, h in enumerate(self.hidden):
            x = nn.relu(nn.Dense(h, name=f"pi_{i}")(x))
        mean = nn.Dense(self.action_dim, name="pi_mean")(x)
        log_std = nn.Dense(self.action_dim, name="pi_log_std")(x)
        log_std = jnp.clip(log_std, self.log_std_min, self.log_std_max)
        return mean, log_std


def squashed_sample_logp(rng, mean, log_std):
    """Reparameterized tanh-Gaussian sample + its log-prob."""
    std = jnp.exp(log_std)
    eps = jax.random.normal(rng, mean.shape)
    pre = mean + std * eps
    act = jnp.tanh(pre)
    logp = (-0.5 * (eps ** 2) - log_std
            - 0.5 * jnp.log(2.0 * jnp.pi)).sum(-1)
    # tanh change of variables (numerically stable form)
    logp -= (2.0 * (jnp.log(2.0) - pre - jax.nn.softplus(-2.0 * pre))).sum(-1)
    return act, logp


class DeterministicActor(nn.Module):
    """DDPG/TD3 actor: MLP → tanh, output in [-1, 1]^action_dim (cf.
    reference rllib/algorithms/ddpg/ddpg_torch_model.py policy head)."""

    action_dim: int
    hidden: Sequence[int] = (256, 256)

    @nn.compact
    def __call__(self, obs: jax.Array) -> jax.Array:
        x = obs
        for i, h in enumerate(self.hidden):
            x = nn.relu(nn.Dense(h, name=f"pi_{i}")(x))
        return jnp.tanh(nn.Dense(self.action_dim, name="pi_out")(x))


class ContinuousQ(nn.Module):
    """Q(s, a) tower for SAC twin critics."""

    hidden: Sequence[int] = (256, 256)

    @nn.compact
    def __call__(self, obs: jax.Array, act: jax.Array) -> jax.Array:
        x = jnp.concatenate([obs, act], axis=-1)
        for i, h in enumerate(self.hidden):
            x = nn.relu(nn.Dense(h, name=f"q_{i}")(x))
        return nn.Dense(1, name="q_out")(x)[..., 0]


class TwinQ(nn.Module):
    hidden: Sequence[int] = (256, 256)

    @nn.compact
    def __call__(self, obs: jax.Array, act: jax.Array):
        q1 = ContinuousQ(self.hidden, name="q1")(obs, act)
        q2 = ContinuousQ(self.hidden, name="q2")(obs, act)
        return q1, q2
