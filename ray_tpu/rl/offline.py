"""Offline RL: SampleBatch JSON I/O, BC, MARWIL, off-policy estimators.

Analog of /root/reference/rllib/offline/ (json_writer.py / json_reader.py:
newline-delimited JSON of column batches; output config on any algorithm)
plus rllib/algorithms/{bc,marwil}: MARWIL's advantage-weighted regression
loss (marwil_torch_policy.py) with BC as its beta=0 special case, and the
importance-sampling / weighted-IS off-policy estimators
(rllib/offline/estimators/{importance_sampling,weighted_importance_sampling}.py).
TPU-native: the dataset is loaded once, minibatches stream through one
jitted update on the mesh's data axis — no rollout workers needed.
"""

from __future__ import annotations

import base64
import glob
import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.algorithm import AlgorithmConfig
from ray_tpu.rl.env import Box, make_env
from ray_tpu.rl.sample_batch import SampleBatch


# ---------------------------------------------------------------------------
# JSON I/O (newline-delimited column batches, numpy arrays b64-encoded)
# ---------------------------------------------------------------------------

def _encode_array(a: np.ndarray) -> Dict[str, Any]:
    a = np.ascontiguousarray(a)
    return {"__np__": base64.b64encode(a.tobytes()).decode("ascii"),
            "dtype": str(a.dtype), "shape": list(a.shape)}


def _decode_array(d: Dict[str, Any]) -> np.ndarray:
    buf = base64.b64decode(d["__np__"])
    return np.frombuffer(buf, dtype=d["dtype"]).reshape(d["shape"]).copy()


class JsonWriter:
    """Writes SampleBatches as newline-delimited JSON rows of columns."""

    def __init__(self, path: str, max_file_size: int = 64 * 1024 * 1024):
        os.makedirs(path, exist_ok=True)
        self.path = path
        self.max_file_size = max_file_size
        self._file = None
        self._file_idx = 0

    def _ensure_file(self):
        if self._file is None or self._file.tell() > self.max_file_size:
            if self._file is not None:
                self._file.close()
            name = os.path.join(self.path,
                                f"output-{self._file_idx:05d}.json")
            self._file = open(name, "a")
            self._file_idx += 1

    def write(self, batch: SampleBatch) -> None:
        self._ensure_file()
        row = {k: _encode_array(np.asarray(v)) for k, v in batch.items()}
        self._file.write(json.dumps(row) + "\n")
        self._file.flush()

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


class JsonReader:
    """Reads every batch from a path (file, glob, or directory)."""

    def __init__(self, path: str):
        if os.path.isdir(path):
            self.files = sorted(glob.glob(os.path.join(path, "*.json")))
        else:
            self.files = sorted(glob.glob(path)) or [path]

    def read_all(self) -> SampleBatch:
        batches = list(self)
        if not batches:
            raise ValueError(f"no batches found under {self.files}")
        return SampleBatch.concat_samples(batches)

    def __iter__(self):
        for fname in self.files:
            with open(fname) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    row = json.loads(line)
                    yield SampleBatch({k: _decode_array(v)
                                       for k, v in row.items()})


# ---------------------------------------------------------------------------
# Off-policy estimators
# ---------------------------------------------------------------------------

def importance_sampling_estimate(batch: SampleBatch, new_logp: np.ndarray,
                                 gamma: float = 0.99,
                                 weighted: bool = False) -> Dict[str, float]:
    """(W)IS estimate of the new policy's value from behavior data.

    cf. reference rllib/offline/estimators/importance_sampling.py — the
    per-episode cumulative ratio weights the behavior return.
    """
    out_v, out_v_b = [], []
    total_w = 0.0
    for ep in batch.split_by_episode():
        idx = np.flatnonzero(
            np.asarray(batch[SB.EPS_ID]) == ep[SB.EPS_ID][0])
        ratios = np.exp(np.clip(
            new_logp[idx] - np.asarray(ep[SB.ACTION_LOGP]), -20, 20))
        p_t = np.cumprod(ratios)
        discounts = gamma ** np.arange(len(idx))
        rew = np.asarray(ep[SB.REWARDS])
        out_v.append(float(np.sum(p_t * discounts * rew)))
        out_v_b.append(float(np.sum(discounts * rew)))
        total_w += float(p_t[-1])
    v_behavior = float(np.mean(out_v_b))
    if weighted and total_w > 0:
        v_target = float(np.sum(out_v) / total_w)
    else:
        v_target = float(np.mean(out_v))
    return {"v_behavior": v_behavior, "v_target": v_target,
            "v_gain": v_target / v_behavior if v_behavior else float("nan")}


# ---------------------------------------------------------------------------
# MARWIL / BC
# ---------------------------------------------------------------------------

class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = MARWIL
        self.input_path: Optional[str] = None
        self.beta = 1.0                 # 0.0 => plain behavior cloning
        self.vf_loss_coeff = 1.0
        self.lr = 1e-4
        self.train_batch_size = 2000
        self.sgd_minibatch_size = 256
        self.num_sgd_iter = 10
        self.moving_average_sqd_adv_norm = 100.0

    def offline_data(self, *, input_path: Optional[str] = None,
                     **kwargs) -> "MARWILConfig":
        if input_path is not None:
            self.input_path = input_path
        self.extra.update(kwargs)
        return self


class BCConfig(MARWILConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = BC
        self.beta = 0.0


class MARWIL:
    """Offline advantage-weighted regression. No WorkerSet: the dataset is
    the experience source; evaluation (if env given) runs a local policy.
    """

    def __init__(self, config: MARWILConfig):
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.rl import models as M

        self.config = config
        if config.input_path is None:
            raise ValueError("config.offline_data(input_path=...) required")
        self.dataset = JsonReader(config.input_path).read_all()
        if SB.ADVANTAGES not in self.dataset:
            self._add_value_targets(self.dataset, config.gamma)
        self.iteration = 0
        self._timesteps_total = 0

        # infer spaces from the env spec (for evaluation + action dims)
        probe = make_env(config.env_spec)
        continuous = isinstance(probe.action_space, Box)
        act_dim = int(np.prod(probe.action_space.shape)) if continuous \
            else probe.action_space.n
        obs_dim = int(np.prod(probe.observation_space.shape))
        probe.close()
        self.continuous = continuous

        self.model = M.ActorCritic(action_dim=act_dim,
                                   hidden=tuple(config.hidden),
                                   continuous=continuous)
        self.params = self.model.init(
            jax.random.PRNGKey(config.seed or 0),
            jnp.zeros((1, obs_dim)))["params"]
        self.tx = optax.chain(optax.clip_by_global_norm(config.grad_clip),
                              optax.adam(config.lr))
        self.opt_state = self.tx.init(self.params)
        # running avg of squared advantage norm (marwil_torch_policy.py)
        self.ma_adv_norm = float(config.moving_average_sqd_adv_norm)

        logp_fn = M.diag_gaussian_logp if continuous else M.categorical_logp
        model, tx = self.model, self.tx
        beta, vf_coeff = config.beta, config.vf_loss_coeff

        def loss_fn(params, batch, ma_norm):
            logits, values = model.apply({"params": params}, batch[SB.OBS])
            logp = logp_fn(logits, batch[SB.ACTIONS])
            adv = batch[SB.VALUE_TARGETS] - values
            if beta > 0.0:
                exp_adv = jnp.exp(beta * jax.lax.stop_gradient(
                    adv / jnp.maximum(jnp.sqrt(ma_norm), 1e-8)))
                exp_adv = jnp.minimum(exp_adv, 20.0)
            else:
                exp_adv = jnp.ones_like(adv)
            pg_loss = -(exp_adv * logp).mean()
            vf_loss = jnp.square(adv).mean()
            total = pg_loss + (vf_coeff * vf_loss if beta > 0.0 else 0.0)
            return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                           "mean_adv": adv.mean(),
                           "sqd_adv": jnp.square(adv).mean(),
                           "logp": logp.mean()}

        @jax.jit
        def sgd_step(params, opt_state, batch, ma_norm):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch, ma_norm)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = loss
            return params, opt_state, aux

        self._sgd_step = sgd_step
        self._jax = jax
        self._jnp = jnp

    @staticmethod
    def _add_value_targets(batch: SampleBatch, gamma: float) -> None:
        """Monte-Carlo returns as value targets per episode."""
        n = batch.count
        targets = np.zeros(n, np.float32)
        eps_ids = np.asarray(batch[SB.EPS_ID])
        rewards = np.asarray(batch[SB.REWARDS], np.float32)
        for eid in np.unique(eps_ids):
            idx = np.flatnonzero(eps_ids == eid)
            ret = 0.0
            for i in idx[::-1]:
                ret = rewards[i] + gamma * ret
                targets[i] = ret
        batch[SB.VALUE_TARGETS] = targets
        batch[SB.ADVANTAGES] = targets.copy()

    def get_weights(self) -> Any:
        import jax
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        import jax
        self.params = jax.tree.map(self._jnp.asarray, weights)

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        aux_last: Dict[str, Any] = {}
        # small datasets: shrink the minibatch so updates still happen
        mb_size = min(cfg.sgd_minibatch_size, self.dataset.count)
        for epoch in range(cfg.num_sgd_iter):
            for mb in self.dataset.minibatches(
                    mb_size,
                    seed=(cfg.seed or 0) + self.iteration * 100 + epoch):
                device_batch = {
                    k: self._jnp.asarray(v) for k, v in mb.items()
                    if k in (SB.OBS, SB.ACTIONS, SB.VALUE_TARGETS)}
                self.params, self.opt_state, aux = self._sgd_step(
                    self.params, self.opt_state, device_batch,
                    self.ma_adv_norm)
                # update the advantage-norm moving average on host
                self.ma_adv_norm += 1e-8 * (
                    float(aux["sqd_adv"]) - self.ma_adv_norm)
                aux_last = aux
                self._timesteps_total += mb.count
        self.iteration += 1
        info = {k: float(v) for k, v in aux_last.items()}
        # report the DATASET-wide action log-likelihood, not the last
        # minibatch's: a shuffle-dependent 64-row tail is too noisy to
        # claim "training improved" against (near convergence its
        # sampling error exceeds a whole train() call's progress)
        info["logp"] = self._dataset_logp()
        result = {"info": info, "training_iteration": self.iteration,
                  "timesteps_total": self._timesteps_total}
        result.update(self.evaluate())
        return result

    def _dataset_logp(self, cap: int = 16384) -> float:
        """Mean log-likelihood of the dataset's actions under the
        current policy (one forward pass; first ``cap`` rows for very
        large datasets — deterministic, unlike a shuffled tail)."""
        from ray_tpu.rl import models as M
        n = min(self.dataset.count, cap)
        obs = self._jnp.asarray(np.asarray(self.dataset[SB.OBS])[:n])
        acts = self._jnp.asarray(np.asarray(self.dataset[SB.ACTIONS])[:n])
        logits, _ = self.model.apply({"params": self.params}, obs)
        logp_fn = M.diag_gaussian_logp if self.continuous \
            else M.categorical_logp
        return float(logp_fn(logits, acts).mean())

    def evaluate(self, episodes: int = 5) -> Dict[str, Any]:
        """Greedy rollouts in the real env to score the cloned policy."""
        import jax.numpy as jnp
        env = make_env(self.config.env_spec)
        rewards = []
        for ep in range(episodes):
            obs, _ = env.reset(seed=1000 + ep)
            done, total = False, 0.0
            steps = 0
            while not done and steps < 1000:
                logits, _ = self.model.apply(
                    {"params": self.params},
                    jnp.asarray(np.asarray(obs, np.float32)[None]))
                if self.continuous:
                    mean, _ = jnp.split(logits, 2, axis=-1)
                    action = np.asarray(mean)[0]
                else:
                    action = int(np.argmax(np.asarray(logits)[0]))
                obs, r, term, trunc, _ = env.step(action)
                total += r
                done = term or trunc
                steps += 1
            rewards.append(total)
        env.close()
        return {"episode_reward_mean": float(np.mean(rewards)),
                "episodes_total": episodes}

    def estimate_off_policy(self) -> Dict[str, float]:
        """IS/WIS value of the learned policy against the dataset."""
        import jax.numpy as jnp
        from ray_tpu.rl import models as M
        logits, _ = self.model.apply({"params": self.params},
                                     jnp.asarray(self.dataset[SB.OBS]))
        logp_fn = M.diag_gaussian_logp if self.continuous \
            else M.categorical_logp
        new_logp = np.asarray(logp_fn(
            logits, jnp.asarray(self.dataset[SB.ACTIONS])))
        out = importance_sampling_estimate(
            self.dataset, new_logp, self.config.gamma, weighted=False)
        wis = importance_sampling_estimate(
            self.dataset, new_logp, self.config.gamma, weighted=True)
        out["v_target_wis"] = wis["v_target"]
        return out

    def save(self) -> Checkpoint:
        return Checkpoint.from_dict({
            "weights": self.get_weights(), "iteration": self.iteration})

    def restore(self, checkpoint: Checkpoint) -> None:
        d = checkpoint.to_dict()
        self.set_weights(d["weights"])
        self.iteration = d.get("iteration", 0)

    def stop(self) -> None:
        pass


class BC(MARWIL):
    """Behavior cloning = MARWIL with beta=0 (pure log-likelihood)."""


def collect_dataset(env_spec, path: str, *, n_steps: int = 2000,
                    seed: int = 0) -> str:
    """Roll a behavior policy and persist its experience (the offline-data
    generation step of reference BC/MARWIL examples)."""
    from ray_tpu.rl.policy import JaxPolicy
    from ray_tpu.rl.env import VectorEnv

    vec = VectorEnv(env_spec, 4, seed=seed)
    pol = JaxPolicy(vec.observation_space, vec.action_space, seed=seed)
    writer = JsonWriter(path)
    obs = vec.reset()
    eps_id = np.arange(4)
    next_eps = 4
    cols: Dict[str, List[np.ndarray]] = {
        SB.OBS: [], SB.NEXT_OBS: [], SB.ACTIONS: [], SB.REWARDS: [],
        SB.TERMINATEDS: [], SB.TRUNCATEDS: [], SB.VF_PREDS: [],
        SB.ACTION_LOGP: [], SB.EPS_ID: []}
    steps = 0
    while steps < n_steps:
        actions, logp, values = pol.compute_actions(obs)
        next_obs, rewards, terms, truncs, infos = vec.step(actions)
        # auto-reset swaps in the NEXT episode's start obs; TD targets
        # must bootstrap from the real final obs (cf. sample_transitions)
        row_next = next_obs.copy()
        for i, info in enumerate(infos):
            if "terminal_observation" in info:
                row_next[i] = info["terminal_observation"]
        cols[SB.OBS].append(obs)
        cols[SB.NEXT_OBS].append(row_next)
        cols[SB.ACTIONS].append(actions)
        cols[SB.REWARDS].append(rewards)
        cols[SB.TERMINATEDS].append(terms)
        cols[SB.TRUNCATEDS].append(truncs)
        cols[SB.VF_PREDS].append(values)
        cols[SB.ACTION_LOGP].append(logp)
        cols[SB.EPS_ID].append(eps_id.copy())
        for i in range(4):
            if terms[i] or truncs[i]:
                eps_id[i] = next_eps
                next_eps += 1
        obs = next_obs
        steps += 4
    # stack time-major then flatten env-major so episodes are contiguous
    T = len(cols[SB.REWARDS])
    fixed = {}
    for k, v in cols.items():
        arr = np.stack([np.asarray(x) for x in v], axis=0)  # [T, B, ...]
        arr = np.swapaxes(arr, 0, 1)                         # [B, T, ...]
        fixed[k] = arr.reshape((4 * T,) + arr.shape[2:])
    batch = SampleBatch(fixed)
    writer.write(batch)
    writer.close()
    return path
