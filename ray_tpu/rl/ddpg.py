"""DDPG + TD3: deterministic-policy off-policy continuous control.

Analog of /root/reference/rllib/algorithms/ddpg/ddpg.py and td3/td3.py
(ddpg_torch_policy.py losses): deterministic actor trained through the
critic, target networks with soft (tau) updates; TD3 layers on twin
critics with min-Q targets, target-policy smoothing noise, and delayed
actor updates (td3.py: policy_delay=2). Same TPU shape as SAC: one jitted
update on the mesh's data axis, DDPGPolicy rollouts on CPU actors.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import models as M
from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import Box, make_env
from ray_tpu.rl.replay_buffer import ReplayBuffer


class DDPGConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = DDPG
        self.actor_lr = 1e-3
        self.critic_lr = 1e-3
        self.train_batch_size = 256
        self.buffer_size = 100_000
        self.learning_starts = 1000
        self.tau = 0.005
        self.exploration_noise = 0.1
        self.n_updates_per_iter = 32
        self.rollout_fragment_length = 64
        # TD3 extensions (off for plain DDPG)
        self.twin_q = False
        self.policy_delay = 1
        self.target_noise = 0.0
        self.target_noise_clip = 0.5


class TD3Config(DDPGConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = TD3
        self.twin_q = True
        self.policy_delay = 2
        self.target_noise = 0.2


class DDPG(Algorithm):
    @classmethod
    def extra_worker_kwargs(cls, config: AlgorithmConfig) -> Dict[str, Any]:
        return {"policy": "ddpg",
                "policy_kwargs": {
                    "exploration_noise": getattr(config, "exploration_noise",
                                                 0.1)}}

    def setup_learner(self) -> None:
        cfg: DDPGConfig = self.config
        probe = make_env(cfg.env_spec)
        if not isinstance(probe.action_space, Box):
            raise ValueError("DDPG requires a continuous action space")
        act_dim = int(np.prod(probe.action_space.shape))
        obs_dim = int(np.prod(probe.observation_space.shape))
        low = np.asarray(probe.action_space.low, np.float32).reshape(-1)
        high = np.asarray(probe.action_space.high, np.float32).reshape(-1)
        probe.close()

        self.actor = M.DeterministicActor(action_dim=act_dim,
                                          hidden=tuple(cfg.hidden))
        self.critic = M.TwinQ(hidden=tuple(cfg.hidden))
        rng = jax.random.PRNGKey(cfg.seed or 0)
        r1, r2 = jax.random.split(rng)
        actor_params = self.actor.init(r1, jnp.zeros((1, obs_dim)))["params"]
        critic_params = self.critic.init(
            r2, jnp.zeros((1, obs_dim)), jnp.zeros((1, act_dim)))["params"]
        self.actor_tx = optax.adam(cfg.actor_lr)
        self.critic_tx = optax.adam(cfg.critic_lr)

        self.build_learner_mesh()
        put = lambda t: jax.device_put(t, self.repl_sharding)  # noqa: E731
        self.state = {
            "actor": put(actor_params),
            "critic": put(critic_params),
            "target_actor": put(jax.tree.map(jnp.copy, actor_params)),
            "target_critic": put(jax.tree.map(jnp.copy, critic_params)),
            "actor_opt": put(self.actor_tx.init(actor_params)),
            "critic_opt": put(self.critic_tx.init(critic_params)),
        }
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._updates = 0

        actor, critic = self.actor, self.critic
        actor_tx, critic_tx = self.actor_tx, self.critic_tx
        gamma, tau = cfg.gamma, cfg.tau
        twin_q = cfg.twin_q
        target_noise = cfg.target_noise
        noise_clip = cfg.target_noise_clip
        scale, shift = (high - low) / 2.0, (high + low) / 2.0

        def rescale(a_tanh):
            return a_tanh * scale + shift

        def update(state, batch, rng, do_actor):
            # -- critic: TD target from the target actor -------------------
            a_next = actor.apply({"params": state["target_actor"]},
                                 batch[SB.NEXT_OBS])
            if target_noise > 0.0:
                # TD3 target-policy smoothing
                noise = jnp.clip(
                    target_noise * jax.random.normal(rng, a_next.shape),
                    -noise_clip, noise_clip)
                a_next = jnp.clip(a_next + noise, -1.0, 1.0)
            q1_t, q2_t = critic.apply({"params": state["target_critic"]},
                                      batch[SB.NEXT_OBS], rescale(a_next))
            q_next = jnp.minimum(q1_t, q2_t) if twin_q else q1_t
            not_done = 1.0 - batch[SB.TERMINATEDS].astype(jnp.float32)
            target = jax.lax.stop_gradient(
                batch[SB.REWARDS] + gamma * not_done * q_next)

            def critic_loss(p):
                q1, q2 = critic.apply({"params": p}, batch[SB.OBS],
                                      batch[SB.ACTIONS])
                loss = jnp.square(q1 - target).mean()
                if twin_q:
                    loss = loss + jnp.square(q2 - target).mean()
                return loss, q1.mean()

            (c_loss, mean_q), c_grads = jax.value_and_grad(
                critic_loss, has_aux=True)(state["critic"])
            c_updates, critic_opt = critic_tx.update(
                c_grads, state["critic_opt"], state["critic"])
            critic_params = optax.apply_updates(state["critic"], c_updates)

            # -- actor: maximize Q1 of the fresh critic (delayed for TD3) --
            def actor_loss(p):
                a = actor.apply({"params": p}, batch[SB.OBS])
                q1, _ = critic.apply({"params": critic_params},
                                     batch[SB.OBS], rescale(a))
                return -q1.mean()

            def do_actor_update(_):
                a_loss, a_grads = jax.value_and_grad(actor_loss)(
                    state["actor"])
                a_updates, actor_opt = actor_tx.update(
                    a_grads, state["actor_opt"], state["actor"])
                actor_params = optax.apply_updates(state["actor"], a_updates)
                target_actor = jax.tree.map(
                    lambda t, o: t * (1.0 - tau) + o * tau,
                    state["target_actor"], actor_params)
                return actor_params, actor_opt, target_actor, a_loss

            def skip_actor_update(_):
                return (state["actor"], state["actor_opt"],
                        state["target_actor"], jnp.float32(0.0))

            actor_params, actor_opt, target_actor, a_loss = jax.lax.cond(
                do_actor, do_actor_update, skip_actor_update, None)

            target_critic = jax.tree.map(
                lambda t, o: t * (1.0 - tau) + o * tau,
                state["target_critic"], critic_params)
            new_state = {
                "actor": actor_params, "critic": critic_params,
                "target_actor": target_actor,
                "target_critic": target_critic,
                "actor_opt": actor_opt, "critic_opt": critic_opt,
            }
            return new_state, {"critic_loss": c_loss, "actor_loss": a_loss,
                               "mean_q": mean_q}

        self._update = jax.jit(update, donate_argnums=(0,))
        self._rng = jax.random.PRNGKey((cfg.seed or 0) + 23)

    def get_weights(self) -> Any:
        return jax.tree.map(np.asarray, self.state["actor"])

    def set_weights(self, weights: Any) -> None:
        self.state["actor"] = jax.device_put(
            jax.tree.map(jnp.asarray, weights), self.repl_sharding)

    def training_step(self) -> Dict[str, Any]:
        cfg: DDPGConfig = self.config
        batches = self.workers.foreach_worker("sample_transitions")
        for b in batches:
            self.buffer.add(b)
            self._timesteps_total += b.count

        info: Dict[str, Any] = {"buffer_size": len(self.buffer)}
        if len(self.buffer) < cfg.learning_starts:
            return {"info": info}

        mb = self.round_minibatch(cfg.train_batch_size)
        metrics: Dict[str, Any] = {}
        for _ in range(cfg.n_updates_per_iter):
            sample = self.buffer.sample(mb)
            device_batch = self.stage_batch(
                sample, (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.NEXT_OBS,
                         SB.TERMINATEDS))
            self._rng, key = jax.random.split(self._rng)
            self._updates += 1
            do_actor = (self._updates % max(cfg.policy_delay, 1)) == 0
            self.state, metrics = self._update(self.state, device_batch,
                                               key, do_actor)

        self.workers.sync_weights(self.get_weights())
        info.update({k: float(v) for k, v in metrics.items()})
        return {"info": info}


class TD3(DDPG):
    pass
