"""SAC: off-policy maximum-entropy RL for continuous control.

Analog of /root/reference/rllib/algorithms/sac/sac.py (+ sac_torch_policy.py
losses): twin Q critics with soft target updates, tanh-Gaussian actor
trained by reparameterization, and automatic entropy-temperature tuning
toward -|A| target entropy.  TPU-native like DQN: one jitted update over
the mesh's data axis; CPU rollout actors run the squashed-Gaussian policy
(ray_tpu/rl/policy.py SACPolicy) and feed the replay buffer via
sample_transitions().
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import models as M
from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import Box, make_env
from ray_tpu.rl.replay_buffer import ReplayBuffer


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = SAC
        self.lr = 3e-4
        self.train_batch_size = 256
        self.buffer_size = 100_000
        self.learning_starts = 1000
        self.tau = 0.005                    # soft target update rate
        self.initial_alpha = 1.0
        self.target_entropy = "auto"        # -> -action_dim
        self.n_updates_per_iter = 32
        self.rollout_fragment_length = 64


class SAC(Algorithm):
    @classmethod
    def extra_worker_kwargs(cls, config: AlgorithmConfig) -> Dict[str, Any]:
        return {"policy": "sac"}

    def setup_learner(self) -> None:
        cfg: SACConfig = self.config
        probe = make_env(cfg.env_spec)
        if not isinstance(probe.action_space, Box):
            raise ValueError("SAC requires a continuous action space")
        act_dim = int(np.prod(probe.action_space.shape))
        obs_dim = int(np.prod(probe.observation_space.shape))
        low = np.asarray(probe.action_space.low, np.float32).reshape(-1)
        high = np.asarray(probe.action_space.high, np.float32).reshape(-1)
        probe.close()

        self.actor = M.SquashedGaussianActor(action_dim=act_dim,
                                             hidden=tuple(cfg.hidden))
        self.critic = M.TwinQ(hidden=tuple(cfg.hidden))
        rng = jax.random.PRNGKey(cfg.seed or 0)
        r1, r2 = jax.random.split(rng)
        actor_params = self.actor.init(r1, jnp.zeros((1, obs_dim)))["params"]
        critic_params = self.critic.init(
            r2, jnp.zeros((1, obs_dim)), jnp.zeros((1, act_dim)))["params"]
        log_alpha = jnp.asarray(np.log(cfg.initial_alpha), jnp.float32)
        target_entropy = -float(act_dim) if cfg.target_entropy == "auto" \
            else float(cfg.target_entropy)

        self.actor_tx = optax.adam(cfg.lr)
        self.critic_tx = optax.adam(cfg.lr)
        self.alpha_tx = optax.adam(cfg.lr)

        self.build_learner_mesh()
        put = lambda t: jax.device_put(t, self.repl_sharding)  # noqa: E731
        self.state = {
            "actor": put(actor_params),
            "critic": put(critic_params),
            # distinct buffers: the donated update would otherwise see the
            # same buffer twice (critic and target start identical)
            "target_critic": put(jax.tree.map(jnp.copy, critic_params)),
            "log_alpha": put(log_alpha),
            "actor_opt": put(self.actor_tx.init(actor_params)),
            "critic_opt": put(self.critic_tx.init(critic_params)),
            "alpha_opt": put(self.alpha_tx.init(log_alpha)),
        }
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)

        actor, critic = self.actor, self.critic
        actor_tx, critic_tx, alpha_tx = self.actor_tx, self.critic_tx, \
            self.alpha_tx
        gamma, tau = cfg.gamma, cfg.tau
        scale, shift = (high - low) / 2.0, (high + low) / 2.0

        def rescale(a_tanh):
            return a_tanh * scale + shift

        def update(state, batch, rng):
            r_next, r_pi = jax.random.split(rng)
            alpha = jnp.exp(state["log_alpha"])

            # -- critic: soft Bellman target from the fresh policy ---------
            mean_n, log_std_n = actor.apply({"params": state["actor"]},
                                            batch[SB.NEXT_OBS])
            a_next, logp_next = M.squashed_sample_logp(r_next, mean_n,
                                                       log_std_n)
            q1_t, q2_t = critic.apply({"params": state["target_critic"]},
                                      batch[SB.NEXT_OBS], rescale(a_next))
            q_next = jnp.minimum(q1_t, q2_t) - alpha * logp_next
            not_done = 1.0 - batch[SB.TERMINATEDS].astype(jnp.float32)
            target = batch[SB.REWARDS] + gamma * not_done * q_next
            target = jax.lax.stop_gradient(target)

            def critic_loss(p):
                q1, q2 = critic.apply({"params": p}, batch[SB.OBS],
                                      batch[SB.ACTIONS])
                return (jnp.square(q1 - target)
                        + jnp.square(q2 - target)).mean() * 0.5, \
                    (q1.mean() + q2.mean()) * 0.5

            (c_loss, mean_q), c_grads = jax.value_and_grad(
                critic_loss, has_aux=True)(state["critic"])
            c_updates, critic_opt = critic_tx.update(
                c_grads, state["critic_opt"], state["critic"])
            critic_params = optax.apply_updates(state["critic"], c_updates)

            # -- actor: reparameterized max-entropy objective --------------
            def actor_loss(p):
                mean, log_std = actor.apply({"params": p}, batch[SB.OBS])
                a, logp = M.squashed_sample_logp(r_pi, mean, log_std)
                q1, q2 = critic.apply({"params": critic_params},
                                      batch[SB.OBS], rescale(a))
                return (alpha * logp - jnp.minimum(q1, q2)).mean(), logp

            (a_loss, logp_pi), a_grads = jax.value_and_grad(
                actor_loss, has_aux=True)(state["actor"])
            a_updates, actor_opt = actor_tx.update(
                a_grads, state["actor_opt"], state["actor"])
            actor_params = optax.apply_updates(state["actor"], a_updates)

            # -- temperature: drive entropy toward the target --------------
            def alpha_loss(log_a):
                return -(log_a * jax.lax.stop_gradient(
                    logp_pi + target_entropy)).mean()

            al_loss, al_grad = jax.value_and_grad(alpha_loss)(
                state["log_alpha"])
            al_updates, alpha_opt = alpha_tx.update(
                al_grad, state["alpha_opt"], state["log_alpha"])
            log_alpha = optax.apply_updates(state["log_alpha"], al_updates)

            target_critic = jax.tree.map(
                lambda t, o: t * (1.0 - tau) + o * tau,
                state["target_critic"], critic_params)
            new_state = {
                "actor": actor_params, "critic": critic_params,
                "target_critic": target_critic, "log_alpha": log_alpha,
                "actor_opt": actor_opt, "critic_opt": critic_opt,
                "alpha_opt": alpha_opt,
            }
            metrics = {"critic_loss": c_loss, "actor_loss": a_loss,
                       "alpha_loss": al_loss, "alpha": alpha,
                       "mean_q": mean_q, "entropy": -logp_pi.mean()}
            return new_state, metrics

        self._update = jax.jit(update, donate_argnums=(0,))
        self._rng = jax.random.PRNGKey((cfg.seed or 0) + 17)

    def get_weights(self) -> Any:
        return jax.tree.map(np.asarray, self.state["actor"])

    def set_weights(self, weights: Any) -> None:
        self.state["actor"] = jax.device_put(
            jax.tree.map(jnp.asarray, weights), self.repl_sharding)

    def training_step(self) -> Dict[str, Any]:
        cfg: SACConfig = self.config
        batches = self.workers.foreach_worker("sample_transitions")
        for b in batches:
            self.buffer.add(b)
            self._timesteps_total += b.count

        info: Dict[str, Any] = {"buffer_size": len(self.buffer)}
        if len(self.buffer) < cfg.learning_starts:
            return {"info": info}

        mb = self.round_minibatch(cfg.train_batch_size)
        metrics: Dict[str, Any] = {}
        for _ in range(cfg.n_updates_per_iter):
            sample = self.buffer.sample(mb)
            device_batch = self.stage_batch(
                sample, (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.NEXT_OBS,
                         SB.TERMINATEDS))
            self._rng, key = jax.random.split(self._rng)
            self.state, metrics = self._update(self.state, device_batch, key)

        self.workers.sync_weights(self.get_weights())
        info.update({k: float(v) for k, v in metrics.items()})
        return {"info": info}
