"""ES + ARS: black-box evolution-strategy policy search.

Analog of /root/reference/rllib/algorithms/es/es.py (OpenAI-ES: antithetic
Gaussian perturbations, centered-rank fitness shaping, shared-noise-style
seeded sampling) and ars/ars.py (Augmented Random Search: top-k direction
selection, reward-std scaling). Embarrassingly parallel by construction —
each rollout actor evaluates a (theta + sigma*eps) candidate; the "learner"
is a numpy vector update on the driver, no device mesh needed. The noise
table is reproduced from seeds on the driver rather than shipped (the
shared-noise-table trick without shared memory).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig


def centered_ranks(x: np.ndarray) -> np.ndarray:
    """Fitness shaping: map rewards to ranks in [-0.5, 0.5] (es.py
    compute_centered_ranks)."""
    ranks = np.empty(len(x), dtype=np.float32)
    ranks[x.argsort()] = np.arange(len(x), dtype=np.float32)
    return ranks / max(len(x) - 1, 1) - 0.5


class ESConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = ES
        self.noise_stdev = 0.05
        self.step_size = 0.02           # SGD step on the ES gradient
        self.episodes_per_candidate = 1
        self.candidates_per_iteration = 16   # antithetic pairs = n/2
        self.l2_coeff = 0.005


class ARSConfig(ESConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = ARS
        self.top_k = 8                  # directions kept per update


class ES(Algorithm):
    """Driver holds theta as a flat vector; workers score perturbations."""

    def setup_learner(self) -> None:
        import jax
        from jax.flatten_util import ravel_pytree
        from ray_tpu.rl.env import make_env
        from ray_tpu.rl.policy import JaxPolicy

        cfg: ESConfig = self.config
        probe = make_env(cfg.env_spec)
        pol = JaxPolicy(probe.observation_space, probe.action_space,
                        hidden=tuple(cfg.hidden), seed=cfg.seed or 0)
        probe.close()
        flat, unravel = ravel_pytree(pol.get_weights())
        self.theta = np.asarray(flat, np.float32)
        self._unravel = lambda v: jax.tree.map(
            np.asarray, unravel(np.asarray(v, np.float32)))
        self._np_rng = np.random.default_rng(cfg.seed or 0)

    def get_weights(self) -> Any:
        return self._unravel(self.theta)

    def set_weights(self, weights: Any) -> None:
        from jax.flatten_util import ravel_pytree
        flat, _ = ravel_pytree(weights)
        self.theta = np.asarray(flat, np.float32)

    def _perturbations(self, n_pairs: int) -> np.ndarray:
        return self._np_rng.standard_normal(
            (n_pairs, self.theta.size)).astype(np.float32)

    def _evaluate(self, candidates: List[np.ndarray]) -> np.ndarray:
        """Round-robin candidates over the worker set; mean return each.
        Also accumulates real env steps into _timesteps_total."""
        import ray_tpu
        cfg: ESConfig = self.config
        workers = self.workers.workers
        n_workers = len(workers)
        refs = []
        for i, cand in enumerate(candidates):
            w = workers[i % n_workers]
            refs.append(w.evaluate_rollout.remote(
                self._unravel(cand),
                n_episodes=cfg.episodes_per_candidate))
        rewards = np.zeros(len(candidates), np.float32)
        restarted = set()
        for i, ref in enumerate(refs):
            try:
                out = ray_tpu.get(ref, timeout=120.0)
                rewards[i] = float(np.mean(out["returns"]))
                self._timesteps_total += int(out["steps"])
            except Exception:
                idx = i % n_workers
                # a dead worker fails every ref it holds: restart once
                if idx not in restarted:
                    self.workers.restart_worker(idx)
                    restarted.add(idx)
                rewards[i] = np.nan
        # failed evaluations contribute the mean (no gradient pull); if
        # every evaluation failed this round, zero out so the rank update
        # is a no-op instead of poisoning theta with NaN
        if np.isnan(rewards).all():
            rewards = np.zeros_like(rewards)
        elif np.isnan(rewards).any():
            rewards = np.where(np.isnan(rewards),
                               np.nanmean(rewards), rewards)
        return rewards

    def training_step(self) -> Dict[str, Any]:
        cfg: ESConfig = self.config
        n_pairs = max(cfg.candidates_per_iteration // 2, 1)
        eps = self._perturbations(n_pairs)
        candidates = []
        for e in eps:
            candidates.append(self.theta + cfg.noise_stdev * e)
            candidates.append(self.theta - cfg.noise_stdev * e)
        rewards = self._evaluate(candidates)
        r_pos, r_neg = rewards[0::2], rewards[1::2]
        shaped = centered_ranks(rewards)
        s_pos, s_neg = shaped[0::2], shaped[1::2]
        grad = ((s_pos - s_neg)[:, None] * eps).sum(0) / (
            n_pairs * cfg.noise_stdev)
        self.theta = ((1.0 - cfg.l2_coeff) * self.theta
                      + cfg.step_size * grad)
        # keep the workers' default policy on the new mean for get_metrics
        self.workers.sync_weights(self.get_weights())
        return {"info": {
            "reward_mean_candidates": float(rewards.mean()),
            "reward_best_candidate": float(rewards.max()),
            "grad_norm": float(np.linalg.norm(grad)),
            "theta_norm": float(np.linalg.norm(self.theta))},
            "episode_reward_mean_candidates": float(
                np.maximum(r_pos, r_neg).mean())}

    def _collect_episode_metrics(self) -> Dict[str, Any]:
        """ES rollouts happen via evaluate_rollout (no persistent episode
        stats on workers) — score the current mean instead."""
        import ray_tpu
        try:
            out = ray_tpu.get(
                self.workers.workers[0].evaluate_rollout.remote(
                    self.get_weights(), n_episodes=2), timeout=120.0)
            rewards = out["returns"]
        except Exception:
            return {"episode_reward_mean": float("nan"),
                    "episode_len_mean": float("nan"), "episodes_total": 0}
        return {"episode_reward_mean": float(np.mean(rewards)),
                "episode_reward_max": float(np.max(rewards)),
                "episode_reward_min": float(np.min(rewards)),
                "episode_len_mean": float(out["steps"] / len(rewards)),
                "episodes_total": len(rewards)}


class ARS(ES):
    def training_step(self) -> Dict[str, Any]:
        cfg: ARSConfig = self.config
        n_pairs = max(cfg.candidates_per_iteration // 2, 1)
        eps = self._perturbations(n_pairs)
        candidates = []
        for e in eps:
            candidates.append(self.theta + cfg.noise_stdev * e)
            candidates.append(self.theta - cfg.noise_stdev * e)
        rewards = self._evaluate(candidates)
        r_pos, r_neg = rewards[0::2], rewards[1::2]
        # top-k directions by best-of-pair (ars.py)
        k = min(cfg.top_k, n_pairs)
        order = np.argsort(-np.maximum(r_pos, r_neg))[:k]
        sel = np.concatenate([r_pos[order], r_neg[order]])
        sigma_r = max(float(sel.std()), 1e-6)
        grad = ((r_pos[order] - r_neg[order])[:, None]
                * eps[order]).sum(0) / (k * sigma_r)
        self.theta = self.theta + cfg.step_size * grad
        self.workers.sync_weights(self.get_weights())
        return {"info": {
            "reward_mean_candidates": float(rewards.mean()),
            "reward_best_candidate": float(rewards.max()),
            "sigma_r": sigma_r,
            "grad_norm": float(np.linalg.norm(grad))},
            "episode_reward_mean_candidates": float(
                np.maximum(r_pos, r_neg).mean())}
