"""JaxPolicy: action computation on rollout CPUs, shared param tree.

Analog of the reference Policy abstraction
(/root/reference/rllib/policy/policy.py + torch_policy_v2.py): the policy
owns params + distribution fns; rollout workers call compute_actions on
host CPU (jitted, tiny batches), the learner updates the same tree on the
device mesh and broadcasts numpy weights back.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models as M
from ray_tpu.rl.env import Box, Discrete


def _epsilon_greedy(rng, greedy: np.ndarray, n_actions: int,
                    epsilon: float):
    """Shared epsilon-greedy mix-in: returns (actions, next_rng)."""
    rng, key = jax.random.split(rng)
    n = greedy.shape[0]
    k1, k2 = jax.random.split(key)
    randoms = np.asarray(jax.random.randint(k1, (n,), 0, n_actions))
    flip = np.asarray(jax.random.uniform(k2, (n,))) < epsilon
    return np.where(flip, randoms, greedy), rng


class JaxPolicy:
    def __init__(self, observation_space, action_space,
                 hidden=(256, 256), seed: int = 0):
        self.observation_space = observation_space
        self.action_space = action_space
        self.continuous = isinstance(action_space, Box)
        if self.continuous:
            act_dim = int(np.prod(action_space.shape))
        else:
            act_dim = action_space.n
        self.model = M.ActorCritic(action_dim=act_dim, hidden=tuple(hidden),
                                   continuous=self.continuous)
        obs_dim = int(np.prod(observation_space.shape))
        self._rng = jax.random.PRNGKey(seed)
        self.params = self.model.init(
            self._rng, jnp.zeros((1, obs_dim)))["params"]

        if self.continuous:
            sample_fn, logp_fn = M.diag_gaussian_sample, M.diag_gaussian_logp
        else:
            sample_fn, logp_fn = M.categorical_sample, M.categorical_logp

        @jax.jit
        def _compute(params, rng, obs):
            logits, value = self.model.apply({"params": params}, obs)
            actions = sample_fn(rng, logits)
            logp = logp_fn(logits, actions)
            return actions, logp, value

        @jax.jit
        def _deterministic(params, obs):
            logits, value = self.model.apply({"params": params}, obs)
            if self.continuous:
                mean, _ = jnp.split(logits, 2, axis=-1)
                return mean, value
            return jnp.argmax(logits, axis=-1), value

        self._compute = _compute
        self._deterministic = _deterministic

    def compute_actions(self, obs: np.ndarray, *, explore: bool = True
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """returns (actions, logp, vf_preds) as numpy."""
        obs = jnp.asarray(obs)
        if explore:
            self._rng, key = jax.random.split(self._rng)
            a, logp, v = self._compute(self.params, key, obs)
        else:
            a, v = self._deterministic(self.params, obs)
            logp = jnp.zeros(a.shape[0])
        return np.asarray(a), np.asarray(logp), np.asarray(v)

    def get_weights(self) -> Any:
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = jax.tree.map(jnp.asarray, weights)


class QPolicy:
    """Epsilon-greedy policy over a QNetwork (DQN-family rollouts).

    Exposes the same ``compute_actions`` triple as JaxPolicy so
    RolloutWorker can drive either; logp is zeros (no likelihoods) and the
    value column carries max-Q (useful for metrics only).
    """

    def __init__(self, observation_space, action_space,
                 hidden=(256, 256), seed: int = 0, epsilon: float = 1.0,
                 dueling: bool = True):
        if isinstance(action_space, Box):
            raise ValueError("QPolicy requires a discrete action space")
        self.observation_space = observation_space
        self.action_space = action_space
        self.epsilon = epsilon
        # dueling must match the learner's QNetwork or weight sync breaks
        self.model = M.QNetwork(action_dim=action_space.n,
                                hidden=tuple(hidden), dueling=dueling)
        obs_dim = int(np.prod(observation_space.shape))
        self._rng = jax.random.PRNGKey(seed)
        self.params = self.model.init(
            self._rng, jnp.zeros((1, obs_dim)))["params"]

        @jax.jit
        def _greedy(params, obs):
            q = self.model.apply({"params": params}, obs)
            return jnp.argmax(q, axis=-1), jnp.max(q, axis=-1)

        self._greedy = _greedy

    def set_epsilon(self, epsilon: float) -> None:
        self.epsilon = float(epsilon)

    def compute_actions(self, obs: np.ndarray, *, explore: bool = True
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        obs = jnp.asarray(obs)
        greedy, maxq = self._greedy(self.params, obs)
        greedy = np.asarray(greedy)
        if explore and self.epsilon > 0.0:
            actions, self._rng = _epsilon_greedy(
                self._rng, greedy, self.action_space.n, self.epsilon)
        else:
            actions = greedy
        return actions, np.zeros(actions.shape[0]), np.asarray(maxq)

    def get_weights(self) -> Any:
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = jax.tree.map(jnp.asarray, weights)


class R2D2Policy:
    """Recurrent epsilon-greedy policy (R2D2 rollouts): an LSTM carry per
    env, stepped one timestep at a time; carries reset at episode ends.
    Same compute_actions triple as QPolicy plus carry management.
    """

    def __init__(self, observation_space, action_space,
                 hidden=(64,), seed: int = 0, epsilon: float = 1.0,
                 lstm_size: int = 64, num_envs: int = 1):
        if isinstance(action_space, Box):
            raise ValueError("R2D2Policy requires a discrete action space")
        self.observation_space = observation_space
        self.action_space = action_space
        self.epsilon = epsilon
        self.num_envs = num_envs
        self.model = M.RecurrentQNetwork(action_dim=action_space.n,
                                         hidden=tuple(hidden),
                                         lstm_size=lstm_size)
        obs_dim = int(np.prod(observation_space.shape))
        self._rng = jax.random.PRNGKey(seed)
        self.carry = self.model.initial_state(num_envs)
        self.params = self.model.init(
            self._rng, jnp.zeros((num_envs, 1, obs_dim)),
            self.carry)["params"]

        @jax.jit
        def _step(params, carry, obs):
            q, carry = self.model.apply({"params": params},
                                        obs[:, None, :], carry)
            return q[:, 0], carry

        self._step = _step

    def set_epsilon(self, epsilon: float) -> None:
        self.epsilon = float(epsilon)

    def reset_carry(self, done_mask: np.ndarray) -> None:
        """Zero the carry for envs whose episode just ended."""
        keep = 1.0 - np.asarray(done_mask, np.float32)[:, None]
        self.carry = tuple(c * keep for c in self.carry)

    def compute_actions(self, obs: np.ndarray, *, explore: bool = True
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        q, self.carry = self._step(self.params, self.carry,
                                   jnp.asarray(obs, jnp.float32))
        greedy = np.asarray(jnp.argmax(q, axis=-1))
        if explore and self.epsilon > 0.0:
            actions, self._rng = _epsilon_greedy(
                self._rng, greedy, self.action_space.n, self.epsilon)
        else:
            actions = greedy
        return actions, np.zeros(actions.shape[0]), \
            np.asarray(jnp.max(q, axis=-1))

    def get_weights(self) -> Any:
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = jax.tree.map(jnp.asarray, weights)


class DDPGPolicy:
    """Deterministic policy + additive Gaussian exploration noise for
    DDPG/TD3 rollouts (cf. reference
    rllib/algorithms/ddpg/ddpg_torch_policy.py exploration:
    ornstein-uhlenbeck/gaussian; we use the TD3 default of plain Gaussian
    scaled to the action range). Same compute_actions triple as JaxPolicy.
    """

    def __init__(self, observation_space, action_space,
                 hidden=(256, 256), seed: int = 0,
                 exploration_noise: float = 0.1):
        if not isinstance(action_space, Box):
            raise ValueError("DDPGPolicy requires a continuous action space")
        self.observation_space = observation_space
        self.action_space = action_space
        self.noise_scale = float(exploration_noise)
        act_dim = int(np.prod(action_space.shape))
        self.model = M.DeterministicActor(action_dim=act_dim,
                                          hidden=tuple(hidden))
        obs_dim = int(np.prod(observation_space.shape))
        self._rng = jax.random.PRNGKey(seed)
        self.params = self.model.init(
            self._rng, jnp.zeros((1, obs_dim)))["params"]
        self._low = np.asarray(action_space.low, np.float32).reshape(-1)
        self._high = np.asarray(action_space.high, np.float32).reshape(-1)

        @jax.jit
        def _act(params, obs):
            return self.model.apply({"params": params}, obs)

        self._act = _act

    def set_noise_scale(self, scale: float) -> None:
        self.noise_scale = float(scale)

    def compute_actions(self, obs: np.ndarray, *, explore: bool = True
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        obs = jnp.asarray(obs)
        act = np.asarray(self._act(self.params, obs))
        if explore and self.noise_scale > 0.0:
            self._rng, key = jax.random.split(self._rng)
            act = act + self.noise_scale * np.asarray(
                jax.random.normal(key, act.shape))
            act = np.clip(act, -1.0, 1.0)
        scaled = self._low + (act + 1.0) * 0.5 * (self._high - self._low)
        return scaled, np.zeros(act.shape[0], np.float32), \
            np.zeros(act.shape[0], np.float32)

    def get_weights(self) -> Any:
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = jax.tree.map(jnp.asarray, weights)


class SACPolicy:
    """Stochastic squashed-Gaussian policy for SAC rollouts (CPU side).

    Actions are sampled from tanh(N(mean, std)) and rescaled to the Box
    bounds; same compute_actions triple as JaxPolicy (logp of the squashed
    sample; value column is zeros — SAC's critics live on the learner).
    """

    def __init__(self, observation_space, action_space,
                 hidden=(256, 256), seed: int = 0):
        if not isinstance(action_space, Box):
            raise ValueError("SACPolicy requires a continuous action space")
        self.observation_space = observation_space
        self.action_space = action_space
        act_dim = int(np.prod(action_space.shape))
        self.model = M.SquashedGaussianActor(action_dim=act_dim,
                                             hidden=tuple(hidden))
        obs_dim = int(np.prod(observation_space.shape))
        self._rng = jax.random.PRNGKey(seed)
        self.params = self.model.init(
            self._rng, jnp.zeros((1, obs_dim)))["params"]
        # actions are flattened to (B, prod(shape)); bounds follow suit
        self._low = np.asarray(action_space.low, np.float32).reshape(-1)
        self._high = np.asarray(action_space.high, np.float32).reshape(-1)

        @jax.jit
        def _sample(params, rng, obs):
            mean, log_std = self.model.apply({"params": params}, obs)
            act, logp = M.squashed_sample_logp(rng, mean, log_std)
            return act, logp

        @jax.jit
        def _deterministic(params, obs):
            mean, _ = self.model.apply({"params": params}, obs)
            return jnp.tanh(mean)

        self._sample = _sample
        self._deterministic = _deterministic

    def _rescale(self, act: np.ndarray) -> np.ndarray:
        return self._low + (np.asarray(act) + 1.0) * 0.5 * \
            (self._high - self._low)

    def compute_actions(self, obs: np.ndarray, *, explore: bool = True
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        obs = jnp.asarray(obs)
        if explore:
            self._rng, key = jax.random.split(self._rng)
            act, logp = self._sample(self.params, key, obs)
        else:
            act = self._deterministic(self.params, obs)
            logp = jnp.zeros(act.shape[0])
        act = np.asarray(act)
        return self._rescale(act), np.asarray(logp), \
            np.zeros(act.shape[0], np.float32)

    def get_weights(self) -> Any:
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = jax.tree.map(jnp.asarray, weights)
