"""CQL: conservative Q-learning for offline continuous control.

Analog of /root/reference/rllib/algorithms/cql/cql.py (+
cql_torch_policy.py): SAC's twin-critic max-entropy update plus the
conservative regularizer  E_s[logsumexp_a Q(s,a)] - E_(s,a)~D[Q(s,a)],
estimated with `num_actions` sampled random + policy actions. Trains from
a JsonReader dataset (no rollout workers); one jitted update per
minibatch on the mesh's data axis.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.env import Box, make_env
from ray_tpu.rl.offline import JsonReader, MARWILConfig
from ray_tpu.rl.sample_batch import SampleBatch


class CQLConfig(MARWILConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = CQL
        self.lr = 3e-4
        self.cql_alpha = 1.0            # weight of the conservative term
        self.num_actions = 4            # sampled actions for logsumexp
        self.tau = 0.005
        self.initial_alpha = 1.0
        self.train_batch_size = 256
        self.num_sgd_iter = 64          # updates per train() call


class CQL:
    def __init__(self, config: CQLConfig):
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.rl import models as M

        self.config = config
        if config.input_path is None:
            raise ValueError("config.offline_data(input_path=...) required")
        self.dataset = JsonReader(config.input_path).read_all()
        self.iteration = 0
        self._timesteps_total = 0

        probe = make_env(config.env_spec)
        if not isinstance(probe.action_space, Box):
            raise ValueError("CQL requires a continuous action space")
        act_dim = int(np.prod(probe.action_space.shape))
        obs_dim = int(np.prod(probe.observation_space.shape))
        low = np.asarray(probe.action_space.low, np.float32).reshape(-1)
        high = np.asarray(probe.action_space.high, np.float32).reshape(-1)
        probe.close()
        self.continuous = True

        # dataset actions must live in tanh space for the critic
        self._low, self._high = low, high
        scale, shift = (high - low) / 2.0, (high + low) / 2.0

        self.actor = M.SquashedGaussianActor(action_dim=act_dim,
                                             hidden=tuple(config.hidden))
        self.critic = M.TwinQ(hidden=tuple(config.hidden))
        rng = jax.random.PRNGKey(config.seed or 0)
        r1, r2 = jax.random.split(rng)
        actor_params = self.actor.init(r1, jnp.zeros((1, obs_dim)))["params"]
        critic_params = self.critic.init(
            r2, jnp.zeros((1, obs_dim)), jnp.zeros((1, act_dim)))["params"]
        self.actor_tx = optax.adam(config.lr)
        self.critic_tx = optax.adam(config.lr)
        self.state = {
            "actor": actor_params,
            "critic": critic_params,
            "target_critic": jax.tree.map(jnp.copy, critic_params),
            "actor_opt": self.actor_tx.init(actor_params),
            "critic_opt": self.critic_tx.init(critic_params),
        }

        actor, critic = self.actor, self.critic
        actor_tx, critic_tx = self.actor_tx, self.critic_tx
        gamma, tau = config.gamma, config.tau
        alpha_ent = 0.1                  # fixed entropy weight (offline)
        cql_alpha = config.cql_alpha
        n_act = config.num_actions

        def rescale(a_tanh):
            return a_tanh * scale + shift

        def update(state, batch, rng):
            r_next, r_pi, r_rand, r_cql = jax.random.split(rng, 4)
            B = batch[SB.REWARDS].shape[0]

            # -- soft Bellman target --------------------------------------
            mean_n, log_std_n = actor.apply({"params": state["actor"]},
                                            batch[SB.NEXT_OBS])
            a_next, logp_next = M.squashed_sample_logp(r_next, mean_n,
                                                       log_std_n)
            q1_t, q2_t = critic.apply({"params": state["target_critic"]},
                                      batch[SB.NEXT_OBS], rescale(a_next))
            q_next = jnp.minimum(q1_t, q2_t) - alpha_ent * logp_next
            not_done = 1.0 - batch[SB.TERMINATEDS].astype(jnp.float32)
            target = jax.lax.stop_gradient(
                batch[SB.REWARDS] + gamma * not_done * q_next)

            def critic_loss(p):
                q1, q2 = critic.apply({"params": p}, batch[SB.OBS],
                                      batch[SB.ACTIONS])
                bellman = (jnp.square(q1 - target)
                           + jnp.square(q2 - target)).mean() * 0.5
                # conservative term: logsumexp over random + policy actions
                rand_a = jax.random.uniform(
                    r_rand, (n_act, B, act_dim), minval=-1.0, maxval=1.0)
                mean_c, log_std_c = actor.apply(
                    {"params": state["actor"]}, batch[SB.OBS])
                keys = jax.random.split(r_cql, n_act)
                pol_a = jnp.stack([
                    M.squashed_sample_logp(k, mean_c, log_std_c)[0]
                    for k in keys])                       # [n_act, B, A]
                all_a = jnp.concatenate([rand_a, pol_a], axis=0)

                def q_of(a):
                    q1s, q2s = critic.apply({"params": p}, batch[SB.OBS],
                                            rescale(a))
                    return q1s, q2s

                q1_all, q2_all = jax.vmap(q_of)(all_a)    # [2n, B]
                lse1 = jax.scipy.special.logsumexp(q1_all, axis=0)
                lse2 = jax.scipy.special.logsumexp(q2_all, axis=0)
                conservative = ((lse1 - q1) + (lse2 - q2)).mean() * 0.5
                return bellman + cql_alpha * conservative, \
                    (bellman, conservative, q1.mean())

            (c_loss, (bellman, conservative, mean_q)), c_grads = \
                jax.value_and_grad(critic_loss, has_aux=True)(
                    state["critic"])
            c_updates, critic_opt = critic_tx.update(
                c_grads, state["critic_opt"], state["critic"])
            critic_params = optax.apply_updates(state["critic"], c_updates)

            # -- actor (SAC objective) ------------------------------------
            def actor_loss(p):
                mean, log_std = actor.apply({"params": p}, batch[SB.OBS])
                a, logp = M.squashed_sample_logp(r_pi, mean, log_std)
                q1, q2 = critic.apply({"params": critic_params},
                                      batch[SB.OBS], rescale(a))
                return (alpha_ent * logp - jnp.minimum(q1, q2)).mean()

            a_loss, a_grads = jax.value_and_grad(actor_loss)(state["actor"])
            a_updates, actor_opt = actor_tx.update(
                a_grads, state["actor_opt"], state["actor"])
            actor_params = optax.apply_updates(state["actor"], a_updates)

            target_critic = jax.tree.map(
                lambda t, o: t * (1.0 - tau) + o * tau,
                state["target_critic"], critic_params)
            new_state = {
                "actor": actor_params, "critic": critic_params,
                "target_critic": target_critic,
                "actor_opt": actor_opt, "critic_opt": critic_opt,
            }
            return new_state, {"critic_loss": c_loss,
                               "bellman_loss": bellman,
                               "cql_loss": conservative,
                               "actor_loss": a_loss, "mean_q": mean_q}

        import jax as _jax
        self._update = _jax.jit(update, donate_argnums=(0,))
        self._rng = _jax.random.PRNGKey((config.seed or 0) + 31)
        self._jnp = jnp
        self._jax = jax

        if SB.NEXT_OBS not in self.dataset:
            raise ValueError("CQL needs next_obs in the offline dataset "
                             "(collect with collect_dataset)")

    def get_weights(self) -> Any:
        import jax
        return jax.tree.map(np.asarray, self.state["actor"])

    def set_weights(self, weights: Any) -> None:
        self.state["actor"] = self._jax.tree.map(self._jnp.asarray, weights)

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        jnp = self._jnp
        metrics: Dict[str, Any] = {}
        rng = np.random.default_rng(
            (cfg.seed or 0) + self.iteration * 1000)
        n = self.dataset.count
        keep = (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.NEXT_OBS, SB.TERMINATEDS)
        for i in range(cfg.num_sgd_iter):
            # index-gather a minibatch; no full-dataset shuffle copies
            idx = rng.choice(n, size=min(cfg.train_batch_size, n),
                             replace=False)
            mb = SampleBatch({k: np.asarray(self.dataset[k])[idx]
                              for k in keep if k in self.dataset})
            device_batch = {k: jnp.asarray(v) for k, v in mb.items()}
            self._rng, key = self._jax.random.split(self._rng)
            self.state, metrics = self._update(self.state, device_batch, key)
            self._timesteps_total += mb.count
        self.iteration += 1
        info = {k: float(v) for k, v in metrics.items()}
        return {"info": info, "training_iteration": self.iteration,
                "timesteps_total": self._timesteps_total}

    def save(self) -> Checkpoint:
        from ray_tpu.rl.algorithm import full_training_state
        return Checkpoint.from_dict({
            "state": full_training_state(self),
            "iteration": self.iteration})

    def restore(self, checkpoint: Checkpoint) -> None:
        from ray_tpu.rl.algorithm import apply_full_training_state
        d = checkpoint.to_dict()
        if d.get("state") is not None:
            # full training state: actor + critics + targets + optimizers
            apply_full_training_state(self, d["state"])
        else:  # legacy actor-only checkpoint
            self.set_weights(d["weights"])
        self.iteration = d.get("iteration", 0)

    def stop(self) -> None:
        pass
