"""AlphaStar-style league training: populations + prioritized fictitious
self-play.

Analog of /root/reference/rllib/algorithms/alpha_star (Vinyals et al.
2019's league, scoped to the repo's board env): a population of learners —
**main agents** (the product), **main exploiters** (attack the current
mains), and **league exploiters** (attack the whole league) — trains by
playing matchups drawn with prioritized fictitious self-play (PFSP):
opponents are sampled by a weighting of the historical win-rate, so
learners spend their games where they are weakest. Learners are
periodically frozen into the league as past players (exploiters reset
after snapshotting, per the paper), and a payoff matrix of running
win-rates drives both matchmaking and snapshot gating.

TPU shape: one jitted masked-softmax policy-gradient update shared by all
learners (REINFORCE + value baseline + entropy); games are cheap CPU
board rollouts, the league bookkeeping is plain Python.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rl.algorithm import AlgorithmConfig
from ray_tpu.rl.alpha_zero import TicTacToe


class AlphaStarConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = AlphaStar
        self.num_main_agents = 1
        self.num_main_exploiters = 1
        self.num_league_exploiters = 1
        self.games_per_iter = 64        # per learner, per iteration
        self.snapshot_interval = 5      # iterations between league freezes
        self.pfsp_weighting = "variance"  # p(1-p); or "hard": (1-p)^2
        self.lr = 3e-3
        self.entropy_coef = 0.01
        self.value_coef = 0.5
        self.hidden = (64, 64)
        self.self_play_prob = 0.5       # mains: self-play vs PFSP split

    def environment(self, env=None, **kwargs):
        return super().environment(env or TicTacToe, **kwargs)


class AlphaStar:
    def __init__(self, config: AlphaStarConfig):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp
        import optax

        self.config = config
        self.iteration = 0
        self._timesteps_total = 0
        cfg = config
        if cfg.env_spec not in (None, TicTacToe):
            # the lockstep board mechanics are TicTacToe-specific; fail
            # loudly rather than silently training on the wrong game
            raise ValueError(
                "AlphaStar league play currently supports only the "
                f"TicTacToe board env, got {cfg.env_spec!r}")
        env = TicTacToe()
        obs_dim = int(np.prod(env.obs_shape))
        n_actions = env.n_actions
        self._obs_dim = obs_dim

        class PVNet(nn.Module):
            @nn.compact
            def __call__(self, x):
                for h in cfg.hidden:
                    x = nn.relu(nn.Dense(h)(x))
                return nn.Dense(n_actions)(x), nn.Dense(1)(x)[..., 0]

        self.net = PVNet()
        self.tx = optax.adam(cfg.lr)
        rng = jax.random.PRNGKey(cfg.seed or 0)

        def init_params(key):
            return self.net.init(key, jnp.zeros((1, obs_dim)))["params"]

        # learners: name -> {"params", "opt"}; league: name -> params
        self.learners: Dict[str, Dict[str, Any]] = {}
        names = ([f"main_{i}" for i in range(cfg.num_main_agents)]
                 + [f"main_exploiter_{i}"
                    for i in range(cfg.num_main_exploiters)]
                 + [f"league_exploiter_{i}"
                    for i in range(cfg.num_league_exploiters)])
        keys = jax.random.split(rng, len(names) + 1)
        for name, key in zip(names, keys[:-1]):
            params = init_params(key)
            self.learners[name] = {"params": params,
                                   "opt": self.tx.init(params)}
        self._init_key = keys[-1]
        self.league: Dict[str, Any] = {}      # frozen past players
        # payoff[(a, b)] = (wins_a, games) running counts, a vs b
        self.payoff: Dict[Tuple[str, str], Tuple[float, int]] = {}

        def pg_update(params, opt, obs, mask, actions, returns):
            def loss_fn(p):
                logits, values = self.net.apply({"params": p}, obs)
                logits = jnp.where(mask > 0, logits, -1e9)
                logp = jax.nn.log_softmax(logits)
                lp_a = jnp.take_along_axis(
                    logp, actions[:, None], axis=1)[:, 0]
                adv = returns - jax.lax.stop_gradient(values)
                pg = -(lp_a * adv).mean()
                v_loss = jnp.square(values - returns).mean()
                probs = jax.nn.softmax(logits)
                entropy = -(probs * jnp.where(mask > 0, logp, 0.0)
                            ).sum(-1).mean()
                return (pg + cfg.value_coef * v_loss
                        - cfg.entropy_coef * entropy), entropy

            (loss, ent), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt = self.tx.update(grads, opt, params)
            return optax.apply_updates(params, updates), opt, loss, ent

        self._pg_update = jax.jit(pg_update, donate_argnums=(0, 1))
        # per-move forward must be one compiled call, not op-by-op dispatch
        self._policy_logits = jax.jit(
            lambda p, o: self.net.apply({"params": p}, o)[0])
        self._jax, self._jnp = jax, jnp
        self._np_rng = np.random.default_rng((cfg.seed or 0) + 5)

    # ---------------------------------------------------------- matchmaking
    def _win_rate(self, a: str, b: str) -> float:
        wins, games = self.payoff.get((a, b), (0.0, 0))
        return 0.5 if games == 0 else wins / games

    def _pfsp_pick(self, learner: str, pool: List[str]) -> Optional[str]:
        """Prioritized fictitious self-play: sample an opponent weighted
        toward the ones this learner beats least (AlphaStar's f_hard /
        variance weightings)."""
        if not pool:
            return None
        ps = np.array([self._win_rate(learner, o) for o in pool])
        if self.config.pfsp_weighting == "hard":
            w = np.square(1.0 - ps)
        else:
            w = ps * (1.0 - ps) + 1e-3  # variance weighting
        w = w / w.sum()
        return pool[int(self._np_rng.choice(len(pool), p=w))]

    def _pick_opponent(self, name: str) -> Tuple[str, Any]:
        """Returns (opponent_name, opponent_params) per league role."""
        mains = [n for n in self.learners if n.startswith("main_")
                 and "exploiter" not in n]
        if name.startswith("main_exploiter"):
            # attacks current main agents only
            opp = mains[int(self._np_rng.integers(len(mains)))]
            return opp, self.learners[opp]["params"]
        if name.startswith("league_exploiter"):
            pool = list(self.league)
            opp = self._pfsp_pick(name, pool)
            if opp is not None:
                return opp, self.league[opp]
            opp = mains[int(self._np_rng.integers(len(mains)))]
            return opp, self.learners[opp]["params"]
        # main agent: self-play or PFSP vs league snapshots
        if self.league and \
                self._np_rng.random() > self.config.self_play_prob:
            opp = self._pfsp_pick(name, list(self.league))
            return opp, self.league[opp]
        opp = mains[int(self._np_rng.integers(len(mains)))]
        return opp, self.learners[opp]["params"]

    # ---------------------------------------------------------------- games
    _LINES = np.array([[0, 1, 2], [3, 4, 5], [6, 7, 8], [0, 3, 6],
                       [1, 4, 7], [2, 5, 8], [0, 4, 8], [2, 4, 6]])

    @staticmethod
    def _vec_obs(boards: np.ndarray, player: np.ndarray) -> np.ndarray:
        mine = (boards == player[:, None]).astype(np.float32)
        theirs = (boards == -player[:, None]).astype(np.float32)
        return np.concatenate([mine, theirs], 1)

    def _apply_moves(self, boards, player, side, active, z,
                     idxs, acts) -> None:
        """Apply one move per game in ``idxs`` (in place), resolve games
        that just finished (z from the learner/eval side's perspective:
        1 win / 0.5 draw / 0 loss), and flip whose turn it is."""
        boards[idxs, acts] = player[idxs]
        sums = boards[idxs][:, self._LINES].sum(2)
        won = (sums * player[idxs, None] == 3).any(1)
        full = (boards[idxs] != 0).all(1)
        done = won | full
        if done.any():
            d = idxs[done]
            z[d] = np.where(won[done],
                            (player[d] == side[d]).astype(np.float64), 0.5)
            active[d] = False
        player[idxs] = -player[idxs]

    def _batch_sample(self, params, boards, player,
                      greedy: bool = False) -> np.ndarray:
        """One batched policy call for a set of same-params games; masked
        Gumbel sampling keeps the draw fully vectorized."""
        jnp = self._jnp
        obs = self._vec_obs(boards, player)
        # pad to power-of-two buckets: group sizes vary per ply, and each
        # distinct batch shape would otherwise recompile the jitted call
        n = len(obs)
        bucket = 1 << max(0, (n - 1).bit_length())
        if bucket != n:
            obs = np.concatenate(
                [obs, np.zeros((bucket - n, obs.shape[1]), np.float32)])
        logits = np.asarray(self._policy_logits(
            params, jnp.asarray(obs, jnp.float32)))[:n]
        masked = np.where(boards == 0, logits, -np.inf)
        if greedy:
            return masked.argmax(1)
        gumbel = -np.log(-np.log(
            self._np_rng.random(masked.shape) + 1e-12) + 1e-12)
        return (masked + gumbel).argmax(1)

    def _play_matches(self, learner_params, matches
                      ) -> Tuple[List, List, List, List[Tuple[str, float]]]:
        """Play every game of this iteration in lockstep: at each ply one
        batched policy call per distinct parameter set (the learner plus
        each sampled opponent) instead of one per move — the difference
        between thousands of device round-trips and ~9*(1+K).

        ``matches``: list of (opp_name, opp_params, n_games). Returns the
        learner's (obs, masks, actions) across all games and a per-game
        (opp_name, z) outcome list."""
        opp_of_game: List[int] = []
        for i, (_name, _params, n) in enumerate(matches):
            opp_of_game += [i] * n
        opp_of_game = np.asarray(opp_of_game)
        n_games = len(opp_of_game)
        boards = np.zeros((n_games, 9), np.int8)
        player = np.ones(n_games, np.int8)
        learner_side = np.where(self._np_rng.random(n_games) < 0.5,
                                1, -1).astype(np.int8)
        active = np.ones(n_games, bool)
        z = np.full(n_games, 0.5)
        obs_l: List[np.ndarray] = []
        mask_l: List[np.ndarray] = []
        act_l: List[np.ndarray] = []
        ret_game: List[np.ndarray] = []  # game index of each learner move
        for _ply in range(9):
            if not active.any():
                break
            turn_learner = np.flatnonzero(
                active & (player == learner_side))
            groups = [(learner_params, turn_learner, True)]
            for i, (_n, opp_params, _c) in enumerate(matches):
                idxs = np.flatnonzero(active & (player != learner_side)
                                      & (opp_of_game == i))
                if len(idxs):
                    groups.append((opp_params, idxs, False))
            for params, idxs, is_learner in groups:
                if len(idxs) == 0:
                    continue
                acts = self._batch_sample(params, boards[idxs],
                                          player[idxs])
                if is_learner:
                    obs_l.append(self._vec_obs(boards[idxs], player[idxs]))
                    mask_l.append((boards[idxs] == 0).astype(np.float32))
                    act_l.append(acts)
                    ret_game.append(idxs)
                self._timesteps_total += len(idxs)
                self._apply_moves(boards, player, learner_side, active, z,
                                  idxs, acts)
        outcomes = [(matches[opp_of_game[g]][0], z[g])
                    for g in range(n_games)]
        obs = np.concatenate(obs_l) if obs_l else np.zeros((0, 18),
                                                           np.float32)
        masks = np.concatenate(mask_l) if mask_l else np.zeros(
            (0, 9), np.float32)
        acts = np.concatenate(act_l) if act_l else np.zeros(0, np.int64)
        game_of_move = np.concatenate(ret_game) if ret_game else \
            np.zeros(0, np.int64)
        returns = 2.0 * z[game_of_move] - 1.0
        return (obs, masks, acts), returns, outcomes

    def _record(self, a: str, b: str, z: float) -> None:
        wins, games = self.payoff.get((a, b), (0.0, 0))
        self.payoff[(a, b)] = (wins + z, games + 1)
        wins_b, games_b = self.payoff.get((b, a), (0.0, 0))
        self.payoff[(b, a)] = (wins_b + (1.0 - z), games_b + 1)

    # ---------------------------------------------------------------- train
    def train(self) -> Dict[str, Any]:
        cfg = self.config
        jnp = self._jnp
        info: Dict[str, Any] = {}
        for name, learner in self.learners.items():
            # sample an opponent per game, then group identical opponents
            # so lockstep play needs one policy batch per distinct foe
            draws: Dict[str, Tuple[Any, int]] = {}
            for _ in range(cfg.games_per_iter):
                opp_name, opp_params = self._pick_opponent(name)
                params, count = draws.get(opp_name, (opp_params, 0))
                draws[opp_name] = (params, count + 1)
            matches = [(n, p, c) for n, (p, c) in draws.items()]
            (obs, masks, acts), rets, outcomes = self._play_matches(
                learner["params"], matches)
            for opp_name, z in outcomes:
                self._record(name, opp_name, z)
            batch = (jnp.asarray(obs, jnp.float32),
                     jnp.asarray(masks, jnp.float32),
                     jnp.asarray(acts.astype(np.int32)),
                     jnp.asarray(rets.astype(np.float32)))
            learner["params"], learner["opt"], loss, ent = \
                self._pg_update(learner["params"], learner["opt"], *batch)
            info[f"{name}_win_rate"] = float(
                np.mean([z for _, z in outcomes]))
            info[f"{name}_loss"] = float(loss)
        self.iteration += 1
        # periodic league freeze: snapshot every learner; exploiters
        # restart from a fresh init after snapshotting (the paper's reset)
        if self.iteration % cfg.snapshot_interval == 0:
            for name, learner in list(self.learners.items()):
                snap = f"{name}@{self.iteration}"
                self.league[snap] = self._jax.tree.map(
                    np.asarray, learner["params"])
                if "exploiter" in name:
                    self._init_key, key = self._jax.random.split(
                        self._init_key)
                    params = self.net.init(
                        key, jnp.zeros((1, self._obs_dim)))["params"]
                    learner["params"] = params
                    learner["opt"] = self.tx.init(params)
            info["league_size"] = len(self.league)
        return {"training_iteration": self.iteration,
                "timesteps_total": self._timesteps_total,
                "info": info}

    # ------------------------------------------------------------ eval utils
    def eval_vs_random(self, name: str = "main_0",
                       n_games: int = 50) -> float:
        """Win-rate (draws = 0.5) of a learner against a uniform-random
        player — the standard sanity ladder rung.  Lockstep-batched."""
        params = self.learners[name]["params"]
        boards = np.zeros((n_games, 9), np.int8)
        player = np.ones(n_games, np.int8)
        side = np.where(self._np_rng.random(n_games) < 0.5,
                        1, -1).astype(np.int8)
        active = np.ones(n_games, bool)
        z = np.full(n_games, 0.5)
        for _ply in range(9):
            if not active.any():
                break
            for is_learner in (True, False):
                idxs = np.flatnonzero(
                    active & ((player == side) == is_learner))
                if len(idxs) == 0:
                    continue
                if is_learner:
                    acts = self._batch_sample(params, boards[idxs],
                                              player[idxs], greedy=True)
                else:
                    gumbel = self._np_rng.random((len(idxs), 9))
                    acts = np.where(boards[idxs] == 0, gumbel,
                                    -1.0).argmax(1)
                self._apply_moves(boards, player, side, active, z,
                                  idxs, acts)
        return float(z.mean())

    # ----------------------------------------------------------- checkpoint
    def get_weights(self) -> Any:
        return self._jax.tree.map(np.asarray,
                                  self.learners["main_0"]["params"])

    def set_weights(self, weights: Any) -> None:
        self.learners["main_0"]["params"] = self._jax.tree.map(
            self._jnp.asarray, weights)

    def save(self) -> Checkpoint:
        import cloudpickle
        blob = cloudpickle.dumps({
            "learners": self._jax.tree.map(
                np.asarray, {n: l["params"]
                             for n, l in self.learners.items()}),
            "opts": self._jax.tree.map(
                np.asarray, {n: l["opt"]
                             for n, l in self.learners.items()}),
            "league": self.league,
            "payoff": self.payoff,
        })
        return Checkpoint.from_dict({"league_blob": blob,
                                     "iteration": self.iteration})

    def restore(self, checkpoint: Checkpoint) -> None:
        import cloudpickle
        d = checkpoint.to_dict()
        state = cloudpickle.loads(d["league_blob"])
        for n, p in state["learners"].items():
            self.learners[n] = {
                "params": self._jax.tree.map(self._jnp.asarray, p),
                "opt": self._jax.tree.map(self._jnp.asarray,
                                          state["opts"][n]),
            }
        self.league = state["league"]
        self.payoff = state["payoff"]
        self.iteration = d.get("iteration", 0)

    def stop(self) -> None:
        pass
