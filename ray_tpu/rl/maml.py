"""MAML: model-agnostic meta-learning.

Analog of /root/reference/rllib/algorithms/maml/maml.py (Finn et al.):
meta-train initial parameters such that one (or a few) inner gradient
steps on a new task's support set give good performance on that task.
TPU-native shape: the inner adaptation loop is differentiated through
directly — ``jax.grad`` of a function that itself applies ``jax.grad``
— and tasks are vmapped into one jitted meta-step, so the whole
second-order computation is a single XLA program. Ships the canonical
sinusoid-regression task distribution (Finn et al. §5.1).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rl.algorithm import AlgorithmConfig


class SinusoidTasks:
    """Task distribution: y = A sin(x + phi), A ~ U[0.1, 5], phi ~
    U[0, pi]; support/query sets sampled per task."""

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)

    def sample(self, n_tasks: int, k_shot: int, k_query: int
               ) -> Dict[str, np.ndarray]:
        amp = self._rng.uniform(0.1, 5.0, n_tasks)
        phase = self._rng.uniform(0.0, np.pi, n_tasks)
        xs = self._rng.uniform(-5.0, 5.0, (n_tasks, k_shot + k_query, 1))
        ys = amp[:, None, None] * np.sin(xs + phase[:, None, None])
        return {
            "x_support": xs[:, :k_shot].astype(np.float32),
            "y_support": ys[:, :k_shot].astype(np.float32),
            "x_query": xs[:, k_shot:].astype(np.float32),
            "y_query": ys[:, k_shot:].astype(np.float32),
        }


class MAMLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = MAML
        self.inner_lr = 0.01
        self.inner_steps = 1
        self.meta_lr = 1e-3
        self.meta_batch_size = 16       # tasks per meta-update
        self.k_shot = 10
        self.k_query = 10
        self.meta_updates_per_iter = 50
        self.first_order = False        # FOMAML when True
        self.hidden = (64, 64)

    def environment(self, env=None, **kwargs):
        return super().environment(env or SinusoidTasks, **kwargs)


class MAML:
    """Meta-learner over a task distribution with .sample(n, k, q)."""

    def __init__(self, config: MAMLConfig):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp
        import optax

        self.config = config
        ctor = config.env_spec or SinusoidTasks

        def build(seed_offset: int):
            if not callable(ctor):
                return ctor
            import inspect
            try:
                params = inspect.signature(ctor).parameters
                takes_seed = "seed" in params or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params.values())
            except (TypeError, ValueError):
                takes_seed = False
            # the contract only requires .sample(n, k, q); seed is
            # threaded through when the ctor advertises it
            return ctor(seed=(config.seed or 0) + seed_offset) \
                if takes_seed else ctor()

        self.tasks = build(0)
        # held-out distribution: evaluate() must not consume (or even
        # share) the training task stream's RNG
        self._eval_tasks = build(10_000) if callable(ctor) else self.tasks

        class RegNet(nn.Module):
            hidden_: Tuple[int, ...]

            @nn.compact
            def __call__(self, x):
                for i, h in enumerate(self.hidden_):
                    x = nn.relu(nn.Dense(h, name=f"fc_{i}")(x))
                return nn.Dense(1, name="out")(x)

        self.model = RegNet(hidden_=tuple(config.hidden))
        self.params = self.model.init(
            jax.random.PRNGKey(config.seed or 0),
            jnp.zeros((1, 1)))["params"]
        self.tx = optax.adam(config.meta_lr)
        self.opt_state = self.tx.init(self.params)

        model = self.model
        inner_lr = config.inner_lr
        inner_steps = config.inner_steps
        first_order = config.first_order

        def mse(params, x, y):
            pred = model.apply({"params": params}, x)
            return jnp.mean(jnp.square(pred - y))

        def adapt(params, x_s, y_s):
            """Inner loop: a few SGD steps on the support set. The outer
            grad flows through these updates (second-order MAML) unless
            first_order stops the gradient at the inner grads."""
            def one_step(p, _):
                g = jax.grad(mse)(p, x_s, y_s)
                if first_order:
                    g = jax.lax.stop_gradient(g)
                p = jax.tree.map(lambda w, gw: w - inner_lr * gw, p, g)
                return p, None
            params, _ = jax.lax.scan(one_step, params, None,
                                     length=inner_steps)
            return params

        def task_loss(params, task):
            adapted = adapt(params, task["x_support"], task["y_support"])
            return mse(adapted, task["x_query"], task["y_query"])

        def meta_loss(params, batch):
            # vmap the whole inner-adapt + query evaluation over tasks
            losses = jax.vmap(lambda t: task_loss(params, t))(batch)
            return losses.mean()

        @jax.jit
        def meta_step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(meta_loss)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        @jax.jit
        def eval_task(params, task):
            pre = mse(params, task["x_query"], task["y_query"])
            adapted = adapt(params, task["x_support"], task["y_support"])
            post = mse(adapted, task["x_query"], task["y_query"])
            return pre, post

        self._meta_step = meta_step
        self._eval_task = eval_task
        self._jnp = jnp
        self._jax = jax
        self.iteration = 0
        self._timesteps_total = 0

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        jnp = self._jnp
        loss = 0.0
        for _ in range(cfg.meta_updates_per_iter):
            batch = {k: jnp.asarray(v) for k, v in self.tasks.sample(
                cfg.meta_batch_size, cfg.k_shot, cfg.k_query).items()}
            self.params, self.opt_state, loss = self._meta_step(
                self.params, self.opt_state, batch)
            self._timesteps_total += cfg.meta_batch_size * (
                cfg.k_shot + cfg.k_query)
        self.iteration += 1
        result = {"info": {"meta_loss": float(loss)},
                  "training_iteration": self.iteration,
                  "timesteps_total": self._timesteps_total}
        result.update(self.evaluate())
        return result

    def evaluate(self, n_tasks: int = 32) -> Dict[str, float]:
        """Pre- vs post-adaptation query MSE on held-out tasks — the
        meta-learning signal is the adaptation gain."""
        jnp = self._jnp
        batch = {k: jnp.asarray(v) for k, v in self._eval_tasks.sample(
            n_tasks, self.config.k_shot, self.config.k_query).items()}
        pre, post = self._jax.vmap(
            lambda t: self._eval_task(self.params, t))(batch)
        return {"pre_adapt_mse": float(jnp.mean(pre)),
                "post_adapt_mse": float(jnp.mean(post))}

    def get_weights(self) -> Any:
        return self._jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = self._jax.tree.map(self._jnp.asarray, weights)

    def save(self) -> Checkpoint:
        return Checkpoint.from_dict({
            "weights": self.get_weights(), "iteration": self.iteration,
            "timesteps_total": self._timesteps_total})

    def restore(self, checkpoint: Checkpoint) -> None:
        d = checkpoint.to_dict()
        self.set_weights(d["weights"])
        self.iteration = d.get("iteration", 0)
        self._timesteps_total = d.get("timesteps_total", 0)

    def stop(self) -> None:
        pass
