"""Algorithm registry: name → (Algorithm, AlgorithmConfig).

Analog of /root/reference/rllib/algorithms/registry.py (get_algorithm_class)
— the string lookup used by the CLI, Tune experiment specs, and tests.
"""

from __future__ import annotations

from typing import Tuple, Type


def get_algorithm_class(name: str, return_config: bool = False):
    """Look up an algorithm by its registry name (case-insensitive)."""
    key = name.lower().replace("-", "").replace("_", "")
    try:
        algo_cls, cfg_cls = _REGISTRY[key]()
    except KeyError:
        raise ValueError(
            f"unknown algorithm {name!r}; known: {sorted(_REGISTRY)}")
    if return_config:
        return algo_cls, cfg_cls
    return algo_cls


def _ppo():
    from ray_tpu.rl.ppo import PPO, PPOConfig
    return PPO, PPOConfig


def _impala():
    from ray_tpu.rl.impala import Impala, ImpalaConfig
    return Impala, ImpalaConfig


def _appo():
    from ray_tpu.rl.appo import APPO, APPOConfig
    return APPO, APPOConfig


def _dqn():
    from ray_tpu.rl.dqn import DQN, DQNConfig
    return DQN, DQNConfig


def _simple_q():
    from ray_tpu.rl.simple_q import SimpleQ, SimpleQConfig
    return SimpleQ, SimpleQConfig


def _sac():
    from ray_tpu.rl.sac import SAC, SACConfig
    return SAC, SACConfig


def _ddpg():
    from ray_tpu.rl.ddpg import DDPG, DDPGConfig
    return DDPG, DDPGConfig


def _td3():
    from ray_tpu.rl.ddpg import TD3, TD3Config
    return TD3, TD3Config


def _pg():
    from ray_tpu.rl.pg import PG, PGConfig
    return PG, PGConfig


def _a2c():
    from ray_tpu.rl.a2c import A2C, A2CConfig
    return A2C, A2CConfig


def _a3c():
    from ray_tpu.rl.a2c import A3C, A3CConfig
    return A3C, A3CConfig


def _bc():
    from ray_tpu.rl.offline import BC, BCConfig
    return BC, BCConfig


def _marwil():
    from ray_tpu.rl.offline import MARWIL, MARWILConfig
    return MARWIL, MARWILConfig


def _cql():
    from ray_tpu.rl.cql import CQL, CQLConfig
    return CQL, CQLConfig


def _apex_dqn():
    from ray_tpu.rl.apex_dqn import ApexDQN, ApexDQNConfig
    return ApexDQN, ApexDQNConfig


def _crr():
    from ray_tpu.rl.crr import CRR, CRRConfig
    return CRR, CRRConfig


def _dt():
    from ray_tpu.rl.dt import DT, DTConfig
    return DT, DTConfig


def _bandit_linucb():
    from ray_tpu.rl.bandit import BanditConfig, BanditLinUCB
    return BanditLinUCB, BanditConfig


def _bandit_lints():
    from ray_tpu.rl.bandit import BanditLinTS, BanditLinTSConfig
    return BanditLinTS, BanditLinTSConfig


def _alpha_zero():
    from ray_tpu.rl.alpha_zero import AlphaZero, AlphaZeroConfig
    return AlphaZero, AlphaZeroConfig


def _dreamer():
    from ray_tpu.rl.dreamer import Dreamer, DreamerConfig
    return Dreamer, DreamerConfig


def _slateq():
    from ray_tpu.rl.slateq import SlateQ, SlateQConfig
    return SlateQ, SlateQConfig


def _maml():
    from ray_tpu.rl.maml import MAML, MAMLConfig
    return MAML, MAMLConfig


def _maddpg():
    from ray_tpu.rl.maddpg import MADDPG, MADDPGConfig
    return MADDPG, MADDPGConfig


def _qmix():
    from ray_tpu.rl.qmix import QMix, QMixConfig
    return QMix, QMixConfig


def _r2d2():
    from ray_tpu.rl.r2d2 import R2D2, R2D2Config
    return R2D2, R2D2Config


def _es():
    from ray_tpu.rl.es import ES, ESConfig
    return ES, ESConfig


def _ars():
    from ray_tpu.rl.es import ARS, ARSConfig
    return ARS, ARSConfig


def _alpha_star():
    from ray_tpu.rl.alpha_star import AlphaStar, AlphaStarConfig
    return AlphaStar, AlphaStarConfig


def _mbmpo():
    from ray_tpu.rl.mbmpo import MBMPO, MBMPOConfig
    return MBMPO, MBMPOConfig


_REGISTRY = {
    "ppo": _ppo,
    "impala": _impala,
    "appo": _appo,
    "dqn": _dqn,
    "simpleq": _simple_q,
    "sac": _sac,
    "ddpg": _ddpg,
    "td3": _td3,
    "pg": _pg,
    "a2c": _a2c,
    "a3c": _a3c,
    "bc": _bc,
    "marwil": _marwil,
    "cql": _cql,
    "es": _es,
    "r2d2": _r2d2,
    "qmix": _qmix,
    "alphazero": _alpha_zero,
    "maddpg": _maddpg,
    "maml": _maml,
    "slateq": _slateq,
    "dreamer": _dreamer,
    "mbmpo": _mbmpo,
    "alphastar": _alpha_star,
    "apexdqn": _apex_dqn,
    "crr": _crr,
    "dt": _dt,
    "banditlinucb": _bandit_linucb,
    "banditlints": _bandit_lints,
    "ars": _ars,
}

POLICIES: Tuple[str, ...] = tuple(sorted(_REGISTRY))
