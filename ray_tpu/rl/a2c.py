"""A2C / A3C: (a)synchronous advantage actor-critic.

Analog of /root/reference/rllib/algorithms/a2c/a2c.py and a3c/a3c.py
(a3c_torch_policy.py loss: pg + 0.5*vf - entropy, single pass per batch).
A2C is the synchronous variant: gather one on-policy batch from all
workers, one fused update. A3C keeps RLlib's semantics the TPU-native
way: instead of lock-free HogWild gradient application (a poor fit for a
jitted learner), each worker's fragment is applied the moment it arrives
— same staleness profile, deterministic learner.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.sample_batch import SampleBatch


class A2CConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = A2C
        self.lr = 1e-3
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.train_batch_size = 500
        self.rollout_fragment_length = 50


class A2C(Algorithm):
    def setup_learner(self) -> None:
        cfg: A2CConfig = self.config
        self.model, params, _, logp_fn, ent_fn = self.init_actor_critic()
        self.tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                              optax.rmsprop(cfg.lr, decay=0.99))
        self.build_learner_mesh()
        self.params = jax.device_put(params, self.repl_sharding)
        self.opt_state = jax.device_put(self.tx.init(params),
                                        self.repl_sharding)
        model, tx = self.model, self.tx
        vf_coeff, ent_coeff = cfg.vf_loss_coeff, cfg.entropy_coeff

        def loss_fn(params, batch):
            logits, values = model.apply({"params": params}, batch[SB.OBS])
            logp = logp_fn(logits, batch[SB.ACTIONS])
            pg_loss = -(logp * batch[SB.ADVANTAGES]).mean()
            vf_loss = 0.5 * jnp.square(
                values - batch[SB.VALUE_TARGETS]).mean()
            entropy = ent_fn(logits).mean()
            total = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        @jax.jit
        def sgd_step(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = loss
            aux["grad_norm"] = optax.global_norm(grads)
            return params, opt_state, aux

        self._sgd_step = sgd_step

    def get_weights(self) -> Any:
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = jax.device_put(
            jax.tree.map(jnp.asarray, weights), self.repl_sharding)

    def _apply_batch(self, batch: SampleBatch) -> Dict[str, Any]:
        n = self.round_minibatch(batch.count)
        device_batch = self.stage_batch(
            batch.slice(0, n),
            (SB.OBS, SB.ACTIONS, SB.ADVANTAGES, SB.VALUE_TARGETS))
        self.params, self.opt_state, aux = self._sgd_step(
            self.params, self.opt_state, device_batch)
        return aux

    def training_step(self) -> Dict[str, Any]:
        cfg: A2CConfig = self.config
        train_batch = self.gather_on_policy_batch(cfg.train_batch_size)
        aux = self._apply_batch(train_batch)
        self.workers.sync_weights(self.get_weights())
        info = {k: float(v) for k, v in aux.items()}
        info["train_batch_size"] = train_batch.count
        return {"info": info}


class A3CConfig(A2CConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = A3C
        self.batches_per_step = 8


class A3C(A2C):
    """Async variant: per-worker fragments applied as they arrive, fresh
    weights pushed back to the producing worker only (no global barrier) —
    the async-update semantics of a3c.py without HogWild races."""

    def setup_learner(self) -> None:
        super().setup_learner()
        self._inflight: Dict[Any, int] = {}

    def _submit(self, idx: int) -> None:
        ref = self.workers.workers[idx].sample.remote()
        self._inflight[ref] = idx

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu
        cfg: A3CConfig = self.config
        live = set(self._inflight.values())
        for i in range(len(self.workers.workers)):
            if i not in live:
                self._submit(i)
        aux_last: Dict[str, Any] = {}
        processed = 0
        while processed < cfg.batches_per_step:
            ready, _ = ray_tpu.wait(list(self._inflight.keys()),
                                    num_returns=1, timeout=60.0)
            if not ready:
                break
            ref = ready[0]
            idx = self._inflight.pop(ref)
            try:
                fragment = ray_tpu.get(ref, timeout=30.0)
            except Exception:
                # push current weights to the replacement before it samples
                # (A3C has no importance correction for off-policy data)
                self.workers.restart_worker(idx, self.get_weights())
                self._submit(idx)
                continue
            aux_last = self._apply_batch(fragment)
            self._timesteps_total += fragment.count
            processed += 1
            try:
                self.workers.workers[idx].set_weights.remote(
                    self.get_weights())
            except Exception:
                pass
            self._submit(idx)
        info = {k: float(v) for k, v in aux_last.items()}
        info["batches_processed"] = processed
        return {"info": info}

    def stop(self) -> None:
        self._inflight.clear()
        super().stop()
