"""DQN: off-policy Q-learning with replay, target network, double-Q, dueling.

Analog of /root/reference/rllib/algorithms/dqn/dqn.py (training_step:
sample → store → replay → TD update → periodic target sync) with the loss
of dqn_torch_policy.py (Huber TD error, double-Q action selection).
TPU-native: the TD step is one jitted function over the mesh's data axis;
rollout actors run the epsilon-greedy QPolicy on CPU.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import models as M
from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import Box, make_env
from ray_tpu.rl.replay_buffer import (PrioritizedReplayBuffer, ReplayBuffer,
                                      SampleBatch)


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = DQN
        self.lr = 5e-4
        self.train_batch_size = 32
        self.buffer_size = 50_000
        self.learning_starts = 1000
        self.target_update_freq = 500        # in sampled env steps
        self.n_updates_per_iter = 32         # TD steps per training_step
        self.double_q = True
        self.dueling = True
        self.prioritized_replay = False
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.02
        self.epsilon_timesteps = 10_000
        self.rollout_fragment_length = 32
        self.num_sgd_iter = 1                # unused; kept for config parity


class DQN(Algorithm):
    @classmethod
    def extra_worker_kwargs(cls, config: AlgorithmConfig) -> Dict[str, Any]:
        return {"policy": "q",
                "policy_kwargs": {"dueling": getattr(config, "dueling",
                                                     True)}}

    def setup_learner(self) -> None:
        cfg: DQNConfig = self.config
        probe = make_env(cfg.env_spec)
        if isinstance(probe.action_space, Box):
            raise ValueError("DQN requires a discrete action space")
        act_dim = probe.action_space.n
        obs_dim = int(np.prod(probe.observation_space.shape))
        probe.close()

        self.model = M.QNetwork(action_dim=act_dim, hidden=tuple(cfg.hidden),
                                dueling=cfg.dueling)
        params = self.model.init(jax.random.PRNGKey(cfg.seed or 0),
                                 jnp.zeros((1, obs_dim)))["params"]
        self.tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                              optax.adam(cfg.lr))

        self.build_learner_mesh()
        repl = self.repl_sharding
        self.params = jax.device_put(params, repl)
        self.target_params = jax.device_put(params, repl)
        self.opt_state = jax.device_put(self.tx.init(self.params), repl)

        buffer_cls = PrioritizedReplayBuffer if cfg.prioritized_replay \
            else ReplayBuffer
        self.buffer = buffer_cls(cfg.buffer_size, seed=cfg.seed)
        self._steps_since_target_sync = 0

        model, tx = self.model, self.tx
        gamma, double_q = cfg.gamma, cfg.double_q

        def loss_fn(params, target_params, batch):
            q = model.apply({"params": params}, batch[SB.OBS])
            q_taken = jnp.take_along_axis(
                q, batch[SB.ACTIONS][:, None].astype(jnp.int32), axis=-1)[:, 0]
            q_next_target = model.apply({"params": target_params},
                                        batch[SB.NEXT_OBS])
            if double_q:
                # online net picks the action, target net evaluates it
                q_next_online = model.apply({"params": params},
                                            batch[SB.NEXT_OBS])
                next_a = jnp.argmax(q_next_online, axis=-1)
                q_next = jnp.take_along_axis(
                    q_next_target, next_a[:, None], axis=-1)[:, 0]
            else:
                q_next = jnp.max(q_next_target, axis=-1)
            not_done = 1.0 - batch[SB.TERMINATEDS].astype(jnp.float32)
            target = batch[SB.REWARDS] + gamma * not_done * \
                jax.lax.stop_gradient(q_next)
            td = q_taken - target
            weights = batch.get("weights")
            huber = optax.huber_loss(q_taken, target, delta=1.0)
            loss = jnp.mean(huber * weights) if weights is not None \
                else jnp.mean(huber)
            return loss, {"mean_q": q_taken.mean(), "td_error": td}

        @jax.jit
        def td_step(params, target_params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["loss"] = loss
            aux["grad_norm"] = optax.global_norm(grads)
            return params, opt_state, aux

        self._td_step = td_step

    def get_weights(self) -> Any:
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = jax.device_put(jax.tree.map(jnp.asarray, weights),
                                     self.repl_sharding)
        self.target_params = self.params

    def _epsilon(self) -> float:
        cfg: DQNConfig = self.config
        frac = min(self._timesteps_total / max(cfg.epsilon_timesteps, 1), 1.0)
        return cfg.epsilon_initial + frac * (
            cfg.epsilon_final - cfg.epsilon_initial)

    def training_step(self) -> Dict[str, Any]:
        cfg: DQNConfig = self.config
        # 1. sample transitions with the current epsilon
        self.workers.foreach_worker("set_epsilon", self._epsilon())
        batches = self.workers.foreach_worker("sample_transitions")
        for b in batches:
            self.buffer.add(b)
            self._timesteps_total += b.count
            self._steps_since_target_sync += b.count

        info: Dict[str, Any] = {"epsilon": self._epsilon(),
                                "buffer_size": len(self.buffer)}
        if len(self.buffer) < cfg.learning_starts:
            return {"info": info}

        # 2. replayed TD updates on the mesh
        mb = self.round_minibatch(cfg.train_batch_size)
        prioritized = isinstance(self.buffer, PrioritizedReplayBuffer)
        aux_last: Dict[str, Any] = {}
        for _ in range(cfg.n_updates_per_iter):
            sample = self.buffer.sample(mb)
            device_batch = self.stage_batch(
                sample, (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.NEXT_OBS,
                         SB.TERMINATEDS, "weights"))
            self.params, self.opt_state, aux = self._td_step(
                self.params, self.target_params, self.opt_state, device_batch)
            if prioritized and "batch_indexes" in sample:
                self.buffer.update_priorities(
                    sample["batch_indexes"],
                    np.abs(np.asarray(aux["td_error"])) + 1e-6)
            aux_last = aux

        # 3. periodic hard target sync (dqn.py target_network_update_freq)
        if self._steps_since_target_sync >= cfg.target_update_freq:
            self.target_params = self.params
            self._steps_since_target_sync = 0
            info["target_synced"] = True

        # 4. fresh online weights to the epsilon-greedy rollouts
        self.workers.sync_weights(self.get_weights())
        info.update({k: float(np.mean(np.asarray(v)))
                     for k, v in aux_last.items() if k != "td_error"})
        return {"info": info}
