"""AlphaZero: MCTS self-play + policy/value network.

Analog of /root/reference/rllib/algorithms/alpha_zero/ (alpha_zero.py,
mcts.py): PUCT tree search guided by a policy/value net, self-play
generating (state, visit-count policy, outcome) targets, replayed network
updates. Ships a TicTacToe board env (the reference's open_spiel cartpole
stand-in is replaced by a real two-player zero-sum game). Search runs
driver-local on numpy (trees are irregular — poor XLA fit); the network
update is the jitted compute path.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rl.algorithm import AlgorithmConfig


class TicTacToe:
    """3x3 two-player zero-sum board. State: 2 planes (mine, theirs) from
    the current player's perspective; action: cell 0..8."""

    n_actions = 9
    obs_shape = (18,)

    def __init__(self):
        self.reset()

    def reset(self):
        self.board = np.zeros(9, np.int8)   # +1 / -1 / 0
        self.player = 1
        return self.observation()

    def observation(self) -> np.ndarray:
        mine = (self.board == self.player).astype(np.float32)
        theirs = (self.board == -self.player).astype(np.float32)
        return np.concatenate([mine, theirs])

    def legal_actions(self) -> np.ndarray:
        return np.flatnonzero(self.board == 0)

    _LINES = [(0, 1, 2), (3, 4, 5), (6, 7, 8), (0, 3, 6), (1, 4, 7),
              (2, 5, 8), (0, 4, 8), (2, 4, 6)]

    def winner(self) -> Optional[int]:
        for a, b, c in self._LINES:
            s = self.board[a] + self.board[b] + self.board[c]
            if s == 3:
                return 1
            if s == -3:
                return -1
        if not (self.board == 0).any():
            return 0
        return None

    def step(self, action: int) -> Tuple[Optional[int], bool]:
        """Returns (winner from +1's view or None, done)."""
        assert self.board[action] == 0, "illegal move"
        self.board[action] = self.player
        w = self.winner()
        self.player = -self.player
        return w, w is not None

    def clone(self) -> "TicTacToe":
        e = TicTacToe.__new__(TicTacToe)
        e.board = self.board.copy()
        e.player = self.player
        return e


class _Node:
    __slots__ = ("prior", "visits", "value_sum", "children")

    def __init__(self, prior: float):
        self.prior = prior
        self.visits = 0
        self.value_sum = 0.0
        self.children: Dict[int, "_Node"] = {}

    @property
    def value(self) -> float:
        return self.value_sum / self.visits if self.visits else 0.0


class MCTS:
    """PUCT search (cf. reference rllib/algorithms/alpha_zero/mcts.py):
    expand with network priors, select argmax Q + c * P * sqrt(N)/(1+n),
    back up negamax values."""

    def __init__(self, predict, *, num_simulations: int = 50,
                 c_puct: float = 1.5, dirichlet_alpha: float = 0.3,
                 exploration_fraction: float = 0.25,
                 rng: Optional[np.random.Generator] = None):
        self.predict = predict          # obs -> (priors [A], value scalar)
        self.num_simulations = num_simulations
        self.c_puct = c_puct
        self.alpha = dirichlet_alpha
        self.frac = exploration_fraction
        self.rng = rng or np.random.default_rng(0)

    def run(self, env: TicTacToe, add_noise: bool = True) -> np.ndarray:
        root = _Node(0.0)
        self._expand(root, env)
        if add_noise and root.children:
            acts = list(root.children)
            noise = self.rng.dirichlet([self.alpha] * len(acts))
            for a, n in zip(acts, noise):
                root.children[a].prior = (
                    (1 - self.frac) * root.children[a].prior
                    + self.frac * n)
        for _ in range(self.num_simulations):
            node, sim = root, env.clone()
            path = [node]
            # select to a leaf
            while node.children:
                action, node = self._select(node)
                sim.step(action)
                path.append(node)
            w = sim.winner()
            if w is None:
                value = self._expand(node, sim)
            else:
                # terminal: value from the perspective of the player to
                # move at the leaf (who cannot move; they lost or drew)
                value = 0.0 if w == 0 else (1.0 if w == sim.player
                                            else -1.0)
            # negamax backup: parents alternate perspective
            for n in reversed(path):
                n.visits += 1
                n.value_sum += value
                value = -value
        counts = np.zeros(env.n_actions, np.float32)
        for a, child in root.children.items():
            counts[a] = child.visits
        return counts / max(counts.sum(), 1.0)

    def _select(self, node: _Node) -> Tuple[int, _Node]:
        sqrt_n = math.sqrt(node.visits)
        best, best_score = None, -np.inf
        for a, child in node.children.items():
            # child.value is from the child player's view: negate
            score = -child.value + self.c_puct * child.prior * \
                sqrt_n / (1 + child.visits)
            if score > best_score:
                best, best_score = a, score
        return best, node.children[best]

    def _expand(self, node: _Node, env: TicTacToe) -> float:
        priors, value = self.predict(env.observation())
        legal = env.legal_actions()
        p = np.asarray(priors)[legal]
        p = p / max(p.sum(), 1e-8)
        for a, pr in zip(legal, p):
            node.children[int(a)] = _Node(float(pr))
        return float(value)


class AlphaZeroConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = AlphaZero
        self.lr = 1e-3
        self.num_simulations = 50
        self.c_puct = 1.5
        self.episodes_per_iter = 16
        self.train_batch_size = 128
        self.num_sgd_iter = 8
        self.buffer_size = 4000
        self.temperature_moves = 4      # sample pi^1 for the first k moves
        self.hidden = (64, 64)

    def environment(self, env=None, **kwargs):
        # board games carry their own env; default TicTacToe
        return super().environment(env or TicTacToe, **kwargs)


class AlphaZero:
    def __init__(self, config: AlphaZeroConfig):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp
        import optax

        self.config = config
        env_ctor = config.env_spec or TicTacToe
        if isinstance(env_ctor, str):
            raise ValueError(
                "AlphaZero needs a board-env class/callable with the "
                "TicTacToe interface (legal_actions/winner/clone), not a "
                f"registered env name ({env_ctor!r})")
        self.env_ctor = env_ctor
        probe = env_ctor()
        self.n_actions = probe.n_actions
        obs_dim = int(np.prod(probe.obs_shape))

        class PVNet(nn.Module):
            n_actions_: int
            hidden_: Tuple[int, ...]

            @nn.compact
            def __call__(self, x):
                for i, h in enumerate(self.hidden_):
                    x = nn.relu(nn.Dense(h, name=f"fc_{i}")(x))
                logits = nn.Dense(self.n_actions_, name="pi")(x)
                value = nn.tanh(nn.Dense(1, name="v")(x))[..., 0]
                return logits, value

        self.model = PVNet(n_actions_=self.n_actions,
                           hidden_=tuple(config.hidden))
        self.params = self.model.init(
            jax.random.PRNGKey(config.seed or 0),
            jnp.zeros((1, obs_dim)))["params"]
        self.tx = optax.chain(optax.clip_by_global_norm(config.grad_clip),
                              optax.adam(config.lr))
        self.opt_state = self.tx.init(self.params)

        model, tx = self.model, self.tx

        def loss_fn(params, obs, pi_target, z):
            logits, v = model.apply({"params": params}, obs)
            logp = jax.nn.log_softmax(logits)
            pi_loss = -(pi_target * logp).sum(-1).mean()
            v_loss = jnp.square(v - z).mean()
            return pi_loss + v_loss, {"pi_loss": pi_loss, "v_loss": v_loss}

        @jax.jit
        def sgd_step(params, opt_state, obs, pi, z):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, obs, pi, z)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["loss"] = loss
            return params, opt_state, aux

        @jax.jit
        def forward(params, obs):
            logits, v = model.apply({"params": params}, obs[None])
            return jax.nn.softmax(logits)[0], v[0]

        self._sgd_step = sgd_step
        self._forward = forward
        self._jnp = jnp
        self._jax = jax
        self._np_rng = np.random.default_rng(config.seed or 0)
        self._buffer: List[Tuple[np.ndarray, np.ndarray, float]] = []
        self.iteration = 0
        self._timesteps_total = 0
        self._episodes_total = 0

    def _predict(self, obs: np.ndarray):
        p, v = self._forward(self.params, self._jnp.asarray(obs))
        return np.asarray(p), float(v)

    def _self_play(self) -> Tuple[int, int]:
        """One self-play game; appends (obs, pi, z) rows. Returns
        (winner, moves)."""
        cfg = self.config
        env = self.env_ctor()
        env.reset()
        mcts = MCTS(self._predict, num_simulations=cfg.num_simulations,
                    c_puct=cfg.c_puct, rng=self._np_rng)
        history: List[Tuple[np.ndarray, np.ndarray, int]] = []
        moves = 0
        while True:
            pi = mcts.run(env)
            history.append((env.observation(), pi, env.player))
            if moves < cfg.temperature_moves:
                action = int(self._np_rng.choice(len(pi), p=pi))
            else:
                action = int(np.argmax(pi))
            w, done = env.step(action)
            moves += 1
            if done:
                break
        for obs, pi, player in history:
            z = 0.0 if w == 0 else (1.0 if w == player else -1.0)
            self._buffer.append((obs, pi, z))
        if len(self._buffer) > cfg.buffer_size:
            self._buffer = self._buffer[-cfg.buffer_size:]
        return w, moves

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        jnp = self._jnp
        outcomes = []
        for _ in range(cfg.episodes_per_iter):
            w, moves = self._self_play()
            outcomes.append(w)
            self._timesteps_total += moves
            self._episodes_total += 1

        aux: Dict[str, Any] = {}
        if len(self._buffer) >= cfg.train_batch_size:
            for _ in range(cfg.num_sgd_iter):
                idx = self._np_rng.choice(len(self._buffer),
                                          size=cfg.train_batch_size,
                                          replace=False)
                obs = jnp.asarray(
                    np.stack([self._buffer[i][0] for i in idx]))
                pi = jnp.asarray(
                    np.stack([self._buffer[i][1] for i in idx]))
                z = jnp.asarray(
                    np.asarray([self._buffer[i][2] for i in idx],
                               np.float32))
                self.params, self.opt_state, aux = self._sgd_step(
                    self.params, self.opt_state, obs, pi, z)
        self.iteration += 1
        draws = sum(1 for w in outcomes if w == 0)
        return {"info": {**{k: float(v) for k, v in aux.items()},
                         "buffer_size": len(self._buffer),
                         "draw_fraction": draws / len(outcomes)},
                "training_iteration": self.iteration,
                "timesteps_total": self._timesteps_total,
                "episodes_total": self._episodes_total}

    def play_vs_random(self, games: int = 20,
                       use_search: bool = True) -> Dict[str, float]:
        """Greedy policy vs a uniform-random opponent. With
        ``use_search=False`` the raw network priors pick the move — the
        cleanest probe of what self-play distilled into the net (search
        alone already plays strong TicTacToe)."""
        wins = losses = draws = 0
        rng = np.random.default_rng(123)
        for g in range(games):
            env = self.env_ctor()
            env.reset()
            az_player = 1 if g % 2 == 0 else -1
            mcts = MCTS(self._predict,
                        num_simulations=self.config.num_simulations,
                        c_puct=self.config.c_puct, rng=self._np_rng)
            while True:
                if env.player == az_player:
                    if use_search:
                        pi = mcts.run(env, add_noise=False)
                        action = int(np.argmax(pi))
                    else:
                        priors, _ = self._predict(env.observation())
                        legal = env.legal_actions()
                        action = int(legal[np.argmax(priors[legal])])
                else:
                    action = int(rng.choice(env.legal_actions()))
                w, done = env.step(action)
                if done:
                    if w == 0:
                        draws += 1
                    elif w == az_player:
                        wins += 1
                    else:
                        losses += 1
                    break
        return {"win_rate": wins / games, "loss_rate": losses / games,
                "draw_rate": draws / games}

    def get_weights(self) -> Any:
        return self._jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = self._jax.tree.map(self._jnp.asarray, weights)

    def save(self) -> Checkpoint:
        return Checkpoint.from_dict({
            "weights": self.get_weights(), "iteration": self.iteration,
            "timesteps_total": self._timesteps_total,
            "episodes_total": self._episodes_total})

    def restore(self, checkpoint: Checkpoint) -> None:
        d = checkpoint.to_dict()
        self.set_weights(d["weights"])
        self.iteration = d.get("iteration", 0)
        self._timesteps_total = d.get("timesteps_total", 0)
        self._episodes_total = d.get("episodes_total", 0)

    def stop(self) -> None:
        pass
