"""SampleBatch: columnar rollout data.

Analog of /root/reference/rllib/policy/sample_batch.py — a dict of aligned
numpy arrays with the concat/slice/shuffle/minibatch machinery training
needs. Kept numpy-only on the rollout side; the learner device_puts once.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
TERMINATEDS = "terminateds"
TRUNCATEDS = "truncateds"
NEXT_OBS = "next_obs"
VF_PREDS = "vf_preds"
ACTION_LOGP = "action_logp"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"
EPS_ID = "eps_id"


class SampleBatch(dict):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            if not isinstance(v, np.ndarray):
                v = np.asarray(v)
            # columns must be C-contiguous: the serializer only ships
            # contiguous buffers out-of-band (pickle-5), so a strided
            # view (e.g. a [:, i] env slice) would silently fall back
            # to an in-band row-wise copy on every fragment hop
            if not v.flags.c_contiguous:
                v = np.ascontiguousarray(v)
            self[k] = v

    @property
    def count(self) -> int:
        if dict.__len__(self) == 0:
            return 0
        return len(next(iter(self.values())))

    def __len__(self) -> int:  # number of rows, not keys
        return self.count

    @staticmethod
    def concat_samples(batches: List["SampleBatch"]) -> "SampleBatch":
        batches = [b for b in batches if b and b.count]
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch({
            k: np.concatenate([b[k] for b in batches]) for k in keys})

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def shuffle(self, seed: Optional[int] = None) -> "SampleBatch":
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self.count)
        return SampleBatch({k: v[perm] for k, v in self.items()})

    def minibatches(self, size: int,
                    epochs: int = 1,
                    seed: Optional[int] = None) -> Iterator["SampleBatch"]:
        for ep in range(epochs):
            shuffled = self.shuffle(None if seed is None else seed + ep)
            for start in range(0, self.count - size + 1, size):
                yield shuffled.slice(start, start + size)

    def split_by_episode(self) -> List["SampleBatch"]:
        if EPS_ID not in self:
            return [self]
        out = []
        ids = self[EPS_ID]
        boundaries = np.where(ids[1:] != ids[:-1])[0] + 1
        start = 0
        for b in list(boundaries) + [len(ids)]:
            out.append(self.slice(start, b))
            start = b
        return out

    def to_device(self, sharding=None) -> Dict[str, "object"]:
        import jax
        arrs = {k: v for k, v in self.items()}
        if sharding is not None:
            return {k: jax.device_put(v, sharding) for k, v in arrs.items()}
        return {k: jax.device_put(v) for k, v in arrs.items()}


def compute_gae(batch: SampleBatch, *, gamma: float = 0.99,
                lam: float = 0.95,
                last_value: float = 0.0) -> SampleBatch:
    """Generalized advantage estimation over a (time-ordered) rollout
    fragment (cf. rllib/evaluation/postprocessing.py compute_advantages).
    ``terminateds`` cuts bootstrapping; truncation bootstraps from vf."""
    rewards = batch[REWARDS]
    values = batch[VF_PREDS]
    terms = batch[TERMINATEDS].astype(np.float32)
    n = len(rewards)
    adv = np.zeros(n, np.float32)
    next_value = last_value
    next_adv = 0.0
    for t in range(n - 1, -1, -1):
        nonterminal = 1.0 - terms[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        next_adv = delta + gamma * lam * nonterminal * next_adv
        adv[t] = next_adv
        next_value = values[t]
    batch[ADVANTAGES] = adv
    batch[VALUE_TARGETS] = adv + values
    return batch
