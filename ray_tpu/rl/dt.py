"""DT: Decision Transformer — RL as conditional sequence modeling.

Analog of /root/reference/rllib/algorithms/dt/ (dt.py, the
return-conditioned transformer of Chen et al. 2021): interleaved
(return-to-go, state, action) token triples through a causal transformer
(the repo's GPT block stack — RoPE provides the timestep geometry),
action predicted at each state token. Offline: trains from a JsonReader
dataset; evaluation rolls the env conditioned on a target return.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import flax.linen as nn
import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.algorithm import AlgorithmConfig
from ray_tpu.rl.env import Box, make_env
from ray_tpu.rl.offline import JsonReader


class DTConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = DT
        self.input_path: Optional[str] = None
        self.context_len = 20           # K timesteps of (R, s, a) context
        self.d_model = 128
        self.n_layers = 3
        self.n_heads = 4
        self.lr = 1e-4
        self.train_batch_size = 64
        self.num_sgd_iter = 50
        self.target_return: Optional[float] = None   # None -> dataset max

    def offline_data(self, *, input_path: Optional[str] = None,
                     **kwargs) -> "DTConfig":
        if input_path is not None:
            self.input_path = input_path
        self.extra.update(kwargs)
        return self


class _DTModel(nn.Module):
    """(rtg, obs, act) triples -> per-state-token action logits."""

    obs_dim: int
    act_dim: int
    d_model: int
    n_layers: int
    n_heads: int
    context_len: int

    @nn.compact
    def __call__(self, rtg, obs, act):
        import jax.numpy as jnp
        from ray_tpu.models.configs import TransformerConfig
        from ray_tpu.models.gpt import Block, RMSNorm, stack_layers
        from ray_tpu.ops.layers import rope_frequencies

        B, K = rtg.shape[:2]
        cfg = TransformerConfig(
            vocab_size=1, d_model=self.d_model, n_layers=self.n_layers,
            n_heads=self.n_heads, d_ff=4 * self.d_model,
            max_seq_len=3 * self.context_len,
            dtype=jnp.float32, remat=False, scan_layers=True)
        e_r = nn.Dense(self.d_model, name="embed_rtg")(rtg[..., None])
        e_s = nn.Dense(self.d_model, name="embed_obs")(obs)
        e_a = nn.Dense(self.d_model, name="embed_act")(act)
        # interleave [r_1, s_1, a_1, r_2, ...] -> [B, 3K, D]
        x = jnp.stack([e_r, e_s, e_a], axis=2).reshape(B, 3 * K,
                                                       self.d_model)
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len)
        x = stack_layers(Block, cfg, dict(mesh=None), x, (cos, sin, None))
        x = RMSNorm(name="final_norm")(x)
        # state tokens sit at positions 3t+1; predict a_t there
        state_tokens = x[:, 1::3]
        return nn.Dense(self.act_dim, name="action_head")(state_tokens)


class DT:
    def __init__(self, config: DTConfig):
        import jax
        import jax.numpy as jnp
        import optax

        self.config = config
        if config.input_path is None:
            raise ValueError("config.offline_data(input_path=...) required")
        probe = make_env(config.env_spec)
        if isinstance(probe.action_space, Box):
            raise ValueError("this DT implementation handles discrete "
                             "action spaces (reference dt targets d4rl; "
                             "the discrete path covers the in-repo envs)")
        self.act_dim = probe.action_space.n
        self.obs_dim = int(np.prod(probe.observation_space.shape))
        probe.close()

        self._episodes = self._load_episodes(config)
        self._ep_returns = [float(ep["rtg"][0]) for ep in self._episodes]
        self.target_return = (config.target_return
                              if config.target_return is not None
                              else max(self._ep_returns))

        K = config.context_len
        self.model = _DTModel(obs_dim=self.obs_dim, act_dim=self.act_dim,
                              d_model=config.d_model,
                              n_layers=config.n_layers,
                              n_heads=config.n_heads, context_len=K)
        rng = jax.random.PRNGKey(config.seed or 0)
        self.params = self.model.init(
            rng, jnp.zeros((1, K)), jnp.zeros((1, K, self.obs_dim)),
            jnp.zeros((1, K, self.act_dim)))["params"]
        self.tx = optax.chain(optax.clip_by_global_norm(1.0),
                              optax.adamw(config.lr, weight_decay=1e-4))
        self.opt_state = self.tx.init(self.params)
        self.iteration = 0
        self._timesteps_total = 0

        model, tx = self.model, self.tx

        def loss_fn(params, rtg, obs, act_onehot, act_labels, mask):
            logits = model.apply({"params": params}, rtg, obs, act_onehot)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(
                logp, act_labels[..., None], axis=-1)[..., 0]
            denom = jnp.maximum(mask.sum(), 1.0)
            loss = (nll * mask).sum() / denom
            acc = ((jnp.argmax(logits, -1) == act_labels)
                   * mask).sum() / denom
            return loss, acc

        @jax.jit
        def sgd_step(params, opt_state, rtg, obs, act_onehot, labels, mask):
            (loss, acc), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, rtg, obs, act_onehot,
                                       labels, mask)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, acc

        self._sgd_step = sgd_step
        self._np_rng = np.random.default_rng(config.seed or 0)
        self._jnp = jnp
        self._jax = jax

    @staticmethod
    def _load_episodes(config) -> List[Dict[str, np.ndarray]]:
        data = JsonReader(config.input_path).read_all()
        episodes = []
        for ep in data.split_by_episode():
            rew = np.asarray(ep[SB.REWARDS], np.float32)
            rtg = np.cumsum(rew[::-1])[::-1].copy()   # returns-to-go
            episodes.append({
                "obs": np.asarray(ep[SB.OBS], np.float32),
                "act": np.asarray(ep[SB.ACTIONS], np.int64),
                "rtg": rtg})
        return episodes

    def _sample_batch(self, batch_size: int):
        K = self.config.context_len
        rtg = np.zeros((batch_size, K), np.float32)
        obs = np.zeros((batch_size, K, self.obs_dim), np.float32)
        act = np.zeros((batch_size, K), np.int64)
        mask = np.zeros((batch_size, K), np.float32)
        for i in range(batch_size):
            ep = self._episodes[self._np_rng.integers(len(self._episodes))]
            T = len(ep["act"])
            start = int(self._np_rng.integers(max(T, 1)))
            seg = slice(start, min(start + K, T))
            n = seg.stop - seg.start
            rtg[i, :n] = ep["rtg"][seg]
            obs[i, :n] = ep["obs"][seg]
            act[i, :n] = ep["act"][seg]
            mask[i, :n] = 1.0
        onehot = np.eye(self.act_dim, dtype=np.float32)[act]
        # teacher forcing: the action token at t carries a_t; the
        # prediction at the state token sees only r<=t, s<=t, a<t (causal)
        return rtg, obs, onehot, act, mask

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        jnp = self._jnp
        loss = acc = 0.0
        for _ in range(cfg.num_sgd_iter):
            rtg, obs, onehot, labels, mask = self._sample_batch(
                cfg.train_batch_size)
            self.params, self.opt_state, loss, acc = self._sgd_step(
                self.params, self.opt_state, jnp.asarray(rtg),
                jnp.asarray(obs), jnp.asarray(onehot),
                jnp.asarray(labels), jnp.asarray(mask))
            self._timesteps_total += int(mask.sum())
        self.iteration += 1
        result = {"info": {"loss": float(loss),
                           "action_accuracy": float(acc),
                           "target_return": self.target_return},
                  "training_iteration": self.iteration,
                  "timesteps_total": self._timesteps_total}
        result.update(self.evaluate())
        return result

    def evaluate(self, episodes: int = 3,
                 max_steps: int = 500) -> Dict[str, Any]:
        """Return-conditioned rollout at the target return."""
        jnp = self._jnp
        K = self.config.context_len
        env = make_env(self.config.env_spec)
        totals = []
        for ep in range(episodes):
            ob, _ = env.reset(seed=2000 + ep)
            rtg_hist = [float(self.target_return)]
            obs_hist = [np.asarray(ob, np.float32)]
            act_hist: List[int] = []
            total, done, steps = 0.0, False, 0
            while not done and steps < max_steps:
                n = len(obs_hist)
                lo = max(n - K, 0)
                rtg = np.zeros((1, K), np.float32)
                obs = np.zeros((1, K, self.obs_dim), np.float32)
                act = np.zeros((1, K), np.int64)
                seg_n = n - lo
                rtg[0, :seg_n] = rtg_hist[lo:]
                obs[0, :seg_n] = np.stack(obs_hist[lo:])
                acts = act_hist[lo:]
                if acts:
                    act[0, :len(acts)] = acts
                onehot = np.eye(self.act_dim, dtype=np.float32)[act]
                logits = self.model.apply(
                    {"params": self.params}, jnp.asarray(rtg),
                    jnp.asarray(obs), jnp.asarray(onehot))
                a = int(np.argmax(np.asarray(logits)[0, seg_n - 1]))
                ob, r, term, trunc, _ = env.step(a)
                total += r
                act_hist.append(a)
                rtg_hist.append(rtg_hist[-1] - r)
                obs_hist.append(np.asarray(ob, np.float32))
                done = term or trunc
                steps += 1
            totals.append(total)
        env.close()
        return {"episode_reward_mean": float(np.mean(totals)),
                "episodes_total": episodes}

    def get_weights(self) -> Any:
        return self._jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = self._jax.tree.map(self._jnp.asarray, weights)

    def save(self) -> Checkpoint:
        return Checkpoint.from_dict({
            "weights": self.get_weights(), "iteration": self.iteration,
            "target_return": self.target_return})

    def restore(self, checkpoint: Checkpoint) -> None:
        d = checkpoint.to_dict()
        self.set_weights(d["weights"])
        self.iteration = d.get("iteration", 0)
        self.target_return = d.get("target_return", self.target_return)

    def stop(self) -> None:
        pass
