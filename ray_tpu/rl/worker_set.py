"""WorkerSet: fault-tolerant gang of rollout actors.

Analog of /root/reference/rllib/evaluation/worker_set.py:77 with the
restart behavior of FaultTolerantActorManager
(rllib/utils/actor_manager.py:187): dead rollout workers are replaced
in-place and the round continues with the survivors' samples.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ray_tpu.rl.rollout_worker import RolloutWorker


class WorkerSet:
    def __init__(self, env_spec, *, num_workers: int, worker_kwargs: dict,
                 recreate_failed_workers: bool = True):
        import ray_tpu
        self._env_spec = env_spec
        self._kwargs = dict(worker_kwargs)
        self._recreate = recreate_failed_workers
        self._cls = ray_tpu.remote(num_cpus=1)(RolloutWorker)
        self.workers = [
            self._make(i) for i in range(num_workers)]
        self.num_restarts = 0

    def _make(self, index: int):
        return self._cls.remote(self._env_spec, worker_index=index,
                                **self._kwargs)

    def foreach_worker(self, method: str, *args,
                       timeout: float = 120.0, **kwargs) -> List[Any]:
        """Call ``method`` on all workers; replace any that died (their
        result is dropped this round)."""
        import ray_tpu
        refs = [(i, getattr(w, method).remote(*args, **kwargs))
                for i, w in enumerate(self.workers)]
        out = []
        for i, ref in refs:
            try:
                out.append(ray_tpu.get(ref, timeout=timeout))
            except Exception:
                if not self._recreate:
                    raise
                self.workers[i] = self._make(i)
                self.num_restarts += 1
        return out

    def restart_worker(self, index: int, weights=None) -> bool:
        """Replace a dead worker in place (honors recreate_failed_workers;
        returns False and raises if recreation is disabled). Pushes
        ``weights`` to the replacement so its first fragment is on-policy.
        """
        if not self._recreate:
            raise RuntimeError(
                f"rollout worker {index} died and "
                "recreate_failed_workers=False")
        self.workers[index] = self._make(index)
        self.num_restarts += 1
        if weights is not None:
            try:
                self.workers[index].set_weights.remote(weights)
            except Exception:
                pass
        return True

    def sync_weights(self, weights) -> None:
        import ray_tpu
        wref = ray_tpu.put(weights)
        self.foreach_worker("set_weights", wref)

    def stop(self) -> None:
        import ray_tpu
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
