"""PG: vanilla policy gradient (REINFORCE with value-function baseline).

Analog of /root/reference/rllib/algorithms/pg/pg.py (+ pg_torch_policy.py:
loss = -logp * advantages, no clipping, single pass). The simplest
on-policy algorithm; kept for parity and as the reference point for the
actor-critic family. TPU-native like PPO: the update is one jitted step
over the mesh's data axis.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig


class PGConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = PG
        self.lr = 4e-4
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.num_sgd_iter = 1          # single pass: on-policy REINFORCE
        self.train_batch_size = 2000


class PG(Algorithm):
    def setup_learner(self) -> None:
        cfg: PGConfig = self.config
        self.model, params, _, logp_fn, ent_fn = self.init_actor_critic()
        self.tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                              optax.adam(cfg.lr))
        self.build_learner_mesh()
        self.params = jax.device_put(params, self.repl_sharding)
        self.opt_state = jax.device_put(self.tx.init(params),
                                        self.repl_sharding)
        model, tx = self.model, self.tx
        vf_coeff, ent_coeff = cfg.vf_loss_coeff, cfg.entropy_coeff

        def loss_fn(params, batch):
            logits, values = model.apply({"params": params}, batch[SB.OBS])
            logp = logp_fn(logits, batch[SB.ACTIONS])
            adv = batch[SB.ADVANTAGES]
            adv = (adv - adv.mean()) / jnp.maximum(adv.std(), 1e-4)
            pg_loss = -(logp * adv).mean()
            vf_loss = 0.5 * jnp.square(
                values - batch[SB.VALUE_TARGETS]).mean()
            entropy = ent_fn(logits).mean()
            total = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        @jax.jit
        def sgd_step(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = loss
            return params, opt_state, aux

        self._sgd_step = sgd_step

    def get_weights(self) -> Any:
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = jax.device_put(
            jax.tree.map(jnp.asarray, weights), self.repl_sharding)

    def training_step(self) -> Dict[str, Any]:
        cfg: PGConfig = self.config
        train_batch = self.gather_on_policy_batch(cfg.train_batch_size)
        n = self.round_minibatch(train_batch.count)
        device_batch = self.stage_batch(
            train_batch.slice(0, n),
            (SB.OBS, SB.ACTIONS, SB.ADVANTAGES, SB.VALUE_TARGETS))
        aux: Dict[str, Any] = {}
        for _ in range(cfg.num_sgd_iter):
            self.params, self.opt_state, aux = self._sgd_step(
                self.params, self.opt_state, device_batch)
        self.workers.sync_weights(self.get_weights())
        info = {k: float(v) for k, v in aux.items()}
        info["train_batch_size"] = train_batch.count
        return {"info": info}
