"""Ape-X DQN: distributed prioritized experience replay.

Analog of /root/reference/rllib/algorithms/apex_dqn/apex_dqn.py
(Horgan et al.): many rollout workers free-run with a per-worker epsilon
ladder (worker i explores at eps^(1 + i*alpha/(N-1))), transitions stream
asynchronously into a prioritized replay buffer, and the learner performs
TD updates continuously — no sampling/learning barrier. Reuses the DQN
learner (double-Q/dueling/PER) with IMPALA-style async collection.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rl import sample_batch as SB
from ray_tpu.rl.dqn import DQN, DQNConfig
from ray_tpu.rl.replay_buffer import PrioritizedReplayBuffer


class ApexDQNConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = ApexDQN
        self.prioritized_replay = True
        self.num_rollout_workers = 4
        self.epsilon_base = 0.4         # Ape-X ladder: eps^(1+i*alpha/(N-1))
        self.epsilon_alpha = 7.0
        self.n_updates_per_iter = 64
        self.learning_starts = 1000
        self.rollout_fragment_length = 50
        self.max_pending_per_worker = 1


class ApexDQN(DQN):
    def setup_learner(self) -> None:
        super().setup_learner()
        assert isinstance(self.buffer, PrioritizedReplayBuffer)
        self._inflight: Dict[Any, int] = {}
        # fixed per-worker epsilon ladder (Horgan et al. eq. 1)
        cfg: ApexDQNConfig = self.config
        n = max(len(self.workers.workers), 1)
        self._epsilons = [
            cfg.epsilon_base ** (1.0 + (i * cfg.epsilon_alpha) / max(n - 1, 1))
            for i in range(n)]

    def _submit(self, idx: int) -> None:
        ref = self.workers.workers[idx].sample_transitions.remote()
        self._inflight[ref] = idx

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu
        cfg: ApexDQNConfig = self.config

        # keep every worker busy at its ladder epsilon
        live = set(self._inflight.values())
        for i in range(len(self.workers.workers)):
            if i not in live:
                self.workers.workers[i].set_epsilon.remote(self._epsilons[i])
                self._submit(i)

        # drain whatever has landed (don't block on stragglers)
        ready, _ = ray_tpu.wait(list(self._inflight.keys()),
                                num_returns=len(self._inflight),
                                timeout=2.0)
        wref = ray_tpu.put(self.get_weights()) if ready else None
        for ref in ready:
            idx = self._inflight.pop(ref)
            try:
                batch = ray_tpu.get(ref, timeout=30.0)
            except Exception:
                # the replacement needs its ladder epsilon back, or it
                # would explore at QPolicy's default epsilon=1.0 forever
                self.workers.restart_worker(idx, self.get_weights())
                self.workers.workers[idx].set_epsilon.remote(
                    self._epsilons[idx])
                self._submit(idx)
                continue
            self.buffer.add(batch)
            self._timesteps_total += batch.count
            self._steps_since_target_sync += batch.count
            # push fresh weights only to the producer (async, no barrier);
            # one shared object-store put serves every ready worker
            try:
                self.workers.workers[idx].set_weights.remote(wref)
            except Exception:
                pass
            self._submit(idx)

        info: Dict[str, Any] = {"buffer_size": len(self.buffer),
                                "batches_received": len(ready),
                                "epsilons": self._epsilons}
        if len(self.buffer) < cfg.learning_starts:
            return {"info": info}

        mb = self.round_minibatch(cfg.train_batch_size)
        aux_last: Dict[str, Any] = {}
        for _ in range(cfg.n_updates_per_iter):
            sample = self.buffer.sample(mb)
            device_batch = self.stage_batch(
                sample, (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.NEXT_OBS,
                         SB.TERMINATEDS, "weights"))
            self.params, self.opt_state, aux = self._td_step(
                self.params, self.target_params, self.opt_state,
                device_batch)
            if "batch_indexes" in sample:
                self.buffer.update_priorities(
                    sample["batch_indexes"],
                    np.abs(np.asarray(aux["td_error"])) + 1e-6)
            aux_last = aux

        if self._steps_since_target_sync >= cfg.target_update_freq:
            self.target_params = self.params
            self._steps_since_target_sync = 0
            info["target_synced"] = True
        info.update({k: float(np.mean(np.asarray(v)))
                     for k, v in aux_last.items() if k != "td_error"})
        return {"info": info}

    def stop(self) -> None:
        self._inflight.clear()
        super().stop()
