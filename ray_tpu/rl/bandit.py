"""Contextual bandits: LinUCB and LinTS.

Analog of /root/reference/rllib/algorithms/bandit/ (bandit_torch_policy.py,
lin_ucb / lin_ts exploration): closed-form linear-Gaussian posteriors per
arm — A = I + sum x x^T, b = sum r x — with UCB or Thompson-sampling arm
selection. Pure numpy on the driver (the posteriors are tiny); the env
steps locally, no rollout actors needed.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rl.algorithm import AlgorithmConfig
from ray_tpu.rl.env import Box, Discrete, Env, make_env


class LinearDiscreteEnv(Env):
    """Contextual bandit test env: reward = context . theta_arm + noise
    (cf. reference rllib/env/wrappers/recsim... simplest linear testbed).
    """

    def __init__(self, n_arms: int = 5, dim: int = 8,
                 noise: float = 0.1, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.theta = rng.normal(size=(n_arms, dim)) / np.sqrt(dim)
        self.noise = noise
        self.observation_space = Box(low=-1.0, high=1.0, shape=(dim,))
        self.action_space = Discrete(n_arms)
        self._rng = rng
        self._ctx = None

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._ctx = self._rng.normal(size=self.theta.shape[1]).astype(
            np.float32)
        return self._ctx, {}

    def step(self, action):
        r = float(self.theta[int(action)] @ self._ctx
                  + self.noise * self._rng.normal())
        # bandit: every step is its own episode; next context arrives
        self._ctx = self._rng.normal(size=self.theta.shape[1]).astype(
            np.float32)
        return self._ctx, r, True, False, {}

    def best_reward(self, ctx) -> float:
        return float(np.max(self.theta @ ctx))


class BanditConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = BanditLinUCB
        self.alpha = 1.0               # UCB exploration width
        self.steps_per_iteration = 100


class BanditLinTSConfig(BanditConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = BanditLinTS


class BanditLinUCB:
    """Driver-local bandit: per-arm ridge posterior + UCB selection."""

    exploration = "ucb"

    def __init__(self, config: BanditConfig):
        self.config = config
        self.env = make_env(config.env_spec)
        if not isinstance(self.env.action_space, Discrete):
            raise ValueError("bandits require a discrete action space")
        self.n_arms = self.env.action_space.n
        self.dim = int(np.prod(self.env.observation_space.shape))
        # A = I + sum x x^T (precision), b = sum r x, per arm
        self.A = np.stack([np.eye(self.dim) for _ in range(self.n_arms)])
        self.b = np.zeros((self.n_arms, self.dim))
        self._rng = np.random.default_rng(config.seed or 0)
        self.iteration = 0
        self._timesteps_total = 0
        self._obs, _ = self.env.reset(seed=config.seed or 0)
        self._reward_window: List[float] = []
        self._regret_window: List[float] = []

    def _select_arm(self, x: np.ndarray) -> int:
        scores = np.zeros(self.n_arms)
        for a in range(self.n_arms):
            A_inv = np.linalg.inv(self.A[a])
            theta = A_inv @ self.b[a]
            if self.exploration == "ucb":
                width = self.config.alpha * np.sqrt(x @ A_inv @ x)
                scores[a] = theta @ x + width
            else:                      # Thompson sampling
                sample = self._rng.multivariate_normal(
                    theta, self.config.alpha ** 2 * A_inv)
                scores[a] = sample @ x
        return int(np.argmax(scores))

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        for _ in range(cfg.steps_per_iteration):
            x = np.asarray(self._obs, np.float64).reshape(-1)
            arm = self._select_arm(x)
            obs, r, *_ = self.env.step(arm)
            self.A[arm] += np.outer(x, x)
            self.b[arm] += r * x
            self._reward_window.append(r)
            if hasattr(self.env, "best_reward"):
                self._regret_window.append(self.env.best_reward(x) - r)
            self._obs = obs
            self._timesteps_total += 1
        self.iteration += 1
        self._reward_window = self._reward_window[-500:]
        self._regret_window = self._regret_window[-500:]
        out = {"training_iteration": self.iteration,
               "timesteps_total": self._timesteps_total,
               "episode_reward_mean": float(np.mean(self._reward_window))}
        if self._regret_window:
            out["mean_regret"] = float(np.mean(self._regret_window))
        return out

    def save(self) -> Checkpoint:
        return Checkpoint.from_dict({"A": self.A, "b": self.b,
                                     "iteration": self.iteration})

    def restore(self, checkpoint: Checkpoint) -> None:
        d = checkpoint.to_dict()
        self.A, self.b = d["A"], d["b"]
        self.iteration = d.get("iteration", 0)

    def stop(self) -> None:
        self.env.close()


class BanditLinTS(BanditLinUCB):
    exploration = "ts"
