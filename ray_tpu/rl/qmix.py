"""QMIX: monotonic value decomposition for cooperative multi-agent RL.

Analog of /root/reference/rllib/algorithms/qmix/qmix.py (Rashid et al.):
per-agent Q networks (shared parameters + agent-id one-hot) whose chosen
Qs feed a mixing network — hypernetworks conditioned on the global state
emit |W| (monotonicity) — trained end-to-end on the team reward with a
target mixer. Includes the QMIX paper's TwoStepGame (the reference's
canonical QMIX testbed, rllib/examples/two_step_game.py): coordination
pays 8, the greedy-independent solution only 7.

Envs are tiny matrix/grid games: stepping runs driver-local (like the
bandits); the jitted mixer update is the compute path.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rl.algorithm import AlgorithmConfig
from ray_tpu.rl.multi_agent import MultiAgentEnv
from ray_tpu.rl.env import Box, Discrete


class TwoStepGame(MultiAgentEnv):
    """QMIX paper matrix game. Step 1: agent_0 picks the branch. Step 2:
    payoff 7 in branch A regardless; branch B pays [[0,1],[1,8]] — the
    8 needs both agents to coordinate on action 1."""

    payoff_b = np.array([[0.0, 1.0], [1.0, 8.0]])

    def __init__(self):
        self.agent_ids = ["agent_0", "agent_1"]
        obs_space = Box(low=0.0, high=1.0, shape=(3,))
        self.observation_spaces = {a: obs_space for a in self.agent_ids}
        self.action_spaces = {a: Discrete(2) for a in self.agent_ids}
        self._stage = 0
        self._branch = 0

    def state(self) -> np.ndarray:
        """Global state for the mixer: one-hot over {s1, s2A, s2B}."""
        s = np.zeros(3, np.float32)
        s[0 if self._stage == 0 else 1 + self._branch] = 1.0
        return s

    def _obs(self):
        return {a: self.state() for a in self.agent_ids}

    def reset(self, *, seed: Optional[int] = None):
        self._stage = 0
        self._branch = 0
        return self._obs(), {}

    def step(self, actions: Dict[str, int]):
        if self._stage == 0:
            self._branch = int(actions["agent_0"])
            self._stage = 1
            zeros = {a: 0.0 for a in self.agent_ids}
            return self._obs(), zeros, \
                {"__all__": False, **{a: False for a in self.agent_ids}}, \
                {"__all__": False}, {}
        if self._branch == 0:
            r = 7.0
        else:
            r = float(self.payoff_b[int(actions["agent_0"]),
                                    int(actions["agent_1"])])
        rews = {a: r / 2.0 for a in self.agent_ids}   # team reward split
        terms = {"__all__": True, **{a: True for a in self.agent_ids}}
        return self._obs(), rews, terms, {"__all__": False}, {}


class QMixConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = QMix
        self.lr = 5e-4
        self.mixing_embed_dim = 16
        self.hidden = (32,)
        self.buffer_size = 2000          # stored joint episodes
        self.train_batch_size = 32
        self.learning_starts = 32
        self.target_update_freq = 200    # env episodes between syncs
        self.n_updates_per_iter = 16
        self.episodes_per_iter = 32
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_timesteps = 3000


class QMix:
    """Driver-local cooperative Q-learner with a monotonic mixer."""

    def __init__(self, config: QMixConfig):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp
        import optax

        self.config = config
        env = config.env_spec() if callable(config.env_spec) \
            else config.env_spec
        if not isinstance(env, MultiAgentEnv):
            raise ValueError("QMIX requires a MultiAgentEnv")
        self.env = env
        self.agents: List[str] = list(env.agent_ids)
        n_agents = len(self.agents)
        a0 = self.agents[0]
        self.n_actions = env.action_spaces[a0].n
        obs_dim = int(np.prod(env.observation_spaces[a0].shape))
        state_dim = len(env.state()) if hasattr(env, "state") \
            else obs_dim * n_agents
        self._has_state = hasattr(env, "state")
        in_dim = obs_dim + n_agents      # obs + agent-id one-hot

        class AgentQ(nn.Module):
            n_actions_: int
            hidden_: Tuple[int, ...]

            @nn.compact
            def __call__(self, x):
                for i, h in enumerate(self.hidden_):
                    x = nn.relu(nn.Dense(h, name=f"fc_{i}")(x))
                return nn.Dense(self.n_actions_, name="q")(x)

        class Mixer(nn.Module):
            """Q_tot = w2 . elu(|W1| q + b1) + b2, |W| from state
            hypernets (monotonic in each agent Q)."""
            embed: int
            n_agents_: int

            @nn.compact
            def __call__(self, agent_qs, state):
                # agent_qs: [B, n_agents]; state: [B, state_dim]
                e, n = self.embed, self.n_agents_
                w1 = jnp.abs(nn.Dense(e * n, name="hyper_w1")(state))
                w1 = w1.reshape(-1, n, e)
                b1 = nn.Dense(e, name="hyper_b1")(state)
                hid = nn.elu(jnp.einsum("bn,bne->be", agent_qs, w1) + b1)
                w2 = jnp.abs(nn.Dense(e, name="hyper_w2")(state))
                b2 = nn.Dense(1, name="hyper_b2")(
                    nn.relu(nn.Dense(e, name="hyper_b2_h")(state)))[:, 0]
                return jnp.einsum("be,be->b", hid, w2) + b2

        self.agent_q = AgentQ(n_actions_=self.n_actions,
                              hidden_=tuple(config.hidden))
        self.mixer = Mixer(embed=config.mixing_embed_dim,
                           n_agents_=n_agents)
        rng = jax.random.PRNGKey(config.seed or 0)
        r1, r2 = jax.random.split(rng)
        q_params = self.agent_q.init(r1, jnp.zeros((1, in_dim)))["params"]
        m_params = self.mixer.init(r2, jnp.zeros((1, n_agents)),
                                   jnp.zeros((1, state_dim)))["params"]
        self.params = {"q": q_params, "mixer": m_params}
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.tx = optax.chain(optax.clip_by_global_norm(config.grad_clip),
                              optax.adam(config.lr))
        self.opt_state = self.tx.init(self.params)

        agent_q, mixer = self.agent_q, self.mixer
        gamma = config.gamma
        eye = np.eye(n_agents, dtype=np.float32)

        def agent_inputs(obs):              # [B, n, obs] -> [B, n, in]
            ids = jnp.broadcast_to(jnp.asarray(eye),
                                   obs.shape[:1] + eye.shape)
            return jnp.concatenate([obs, ids], axis=-1)

        def q_all(params, obs):             # -> [B, n, n_actions]
            return agent_q.apply({"params": params}, agent_inputs(obs))

        def loss_fn(params, target_params, batch):
            q = q_all(params["q"], batch["obs"])
            q_taken = jnp.take_along_axis(
                q, batch["actions"][..., None].astype(jnp.int32),
                axis=-1)[..., 0]                        # [B, n]
            q_tot = mixer.apply({"params": params["mixer"]},
                                q_taken, batch["state"])
            q_next = q_all(target_params["q"], batch["next_obs"])
            q_next_max = jnp.max(q_next, axis=-1)       # [B, n]
            target_tot = mixer.apply({"params": target_params["mixer"]},
                                     q_next_max, batch["next_state"])
            not_done = 1.0 - batch["dones"].astype(jnp.float32)
            y = batch["rewards"] + gamma * not_done * \
                jax.lax.stop_gradient(target_tot)
            loss = jnp.mean(jnp.square(q_tot - y))
            return loss, {"mean_q_tot": q_tot.mean()}

        @jax.jit
        def td_step(params, target_params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["loss"] = loss
            return params, opt_state, aux

        @jax.jit
        def greedy(params, obs):
            return jnp.argmax(q_all(params, obs[None]), axis=-1)[0]

        self._td_step = td_step
        self._greedy = greedy
        self._jnp = jnp
        self._jax = jax
        self._np_rng = np.random.default_rng(config.seed or 0)
        self._buffer: List[Dict[str, np.ndarray]] = []
        self.iteration = 0
        self._timesteps_total = 0
        self._episodes_total = 0
        self._episodes_since_sync = 0
        self._reward_window: List[float] = []

    # -- acting ------------------------------------------------------------
    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(self._timesteps_total / max(cfg.epsilon_timesteps, 1),
                   1.0)
        return cfg.epsilon_initial + frac * (
            cfg.epsilon_final - cfg.epsilon_initial)

    def _act(self, obs_stack: np.ndarray, explore: bool) -> np.ndarray:
        greedy = np.asarray(self._greedy(self.params["q"],
                                         self._jnp.asarray(obs_stack)))
        if explore:
            eps = self._epsilon()
            flip = self._np_rng.random(len(self.agents)) < eps
            randoms = self._np_rng.integers(0, self.n_actions,
                                            len(self.agents))
            return np.where(flip, randoms, greedy)
        return greedy

    def _run_episode(self, explore: bool = True) -> float:
        env = self.env
        obs, _ = env.reset()
        total = 0.0
        steps = 0
        while steps < 200:
            obs_stack = np.stack([np.asarray(obs[a], np.float32).reshape(-1)
                                  for a in self.agents])
            state = env.state() if self._has_state else obs_stack.reshape(-1)
            acts = self._act(obs_stack, explore)
            action_dict = {a: int(acts[i])
                           for i, a in enumerate(self.agents)}
            nobs, rews, terms, truncs, _ = env.step(action_dict)
            team_r = float(sum(rews.values()))
            done = bool(terms.get("__all__")) or bool(truncs.get("__all__"))
            nobs_stack = np.stack(
                [np.asarray(nobs.get(a, obs[a]), np.float32).reshape(-1)
                 for a in self.agents])
            nstate = env.state() if self._has_state \
                else nobs_stack.reshape(-1)
            if explore:
                self._buffer.append({
                    "obs": obs_stack, "actions": acts.astype(np.int64),
                    "state": state, "next_obs": nobs_stack,
                    "next_state": nstate,
                    "rewards": np.float32(team_r),
                    "dones": np.float32(done)})
                if len(self._buffer) > self.config.buffer_size:
                    self._buffer.pop(0)
            total += team_r
            if explore:
                # eval rollouts must not advance the epsilon schedule
                # or the reported training timesteps
                self._timesteps_total += 1
            obs = nobs
            steps += 1
            if done:
                break
        return total

    # -- training ----------------------------------------------------------
    def train(self) -> Dict[str, Any]:
        cfg = self.config
        jnp = self._jnp
        for _ in range(cfg.episodes_per_iter):
            self._reward_window.append(self._run_episode(explore=True))
            self._episodes_total += 1
            self._episodes_since_sync += 1
        self._reward_window = self._reward_window[-200:]

        info: Dict[str, Any] = {"epsilon": self._epsilon(),
                                "buffer_size": len(self._buffer)}
        aux: Dict[str, Any] = {}
        if len(self._buffer) >= cfg.learning_starts:
            for _ in range(cfg.n_updates_per_iter):
                idx = self._np_rng.choice(
                    len(self._buffer),
                    size=min(cfg.train_batch_size, len(self._buffer)),
                    replace=False)
                rows = [self._buffer[i] for i in idx]
                batch = {k: jnp.asarray(np.stack([r[k] for r in rows]))
                         for k in rows[0]}
                self.params, self.opt_state, aux = self._td_step(
                    self.params, self.target_params, self.opt_state, batch)
            info.update({k: float(v) for k, v in aux.items()})
        if self._episodes_since_sync >= cfg.target_update_freq:
            self.target_params = self._jax.tree.map(jnp.copy, self.params)
            self._episodes_since_sync = 0
            info["target_synced"] = True
        self.iteration += 1
        return {"info": info, "training_iteration": self.iteration,
                "timesteps_total": self._timesteps_total,
                "episodes_total": self._episodes_total,
                "episode_reward_mean": float(np.mean(self._reward_window))}

    def evaluate(self, episodes: int = 10) -> float:
        return float(np.mean([self._run_episode(explore=False)
                              for _ in range(episodes)]))

    def get_weights(self) -> Any:
        return self._jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = self._jax.tree.map(self._jnp.asarray, weights)
        # TD targets must come from the restored weights, not a stale net
        self.target_params = self._jax.tree.map(self._jnp.copy, self.params)

    def save(self) -> Checkpoint:
        return Checkpoint.from_dict({"weights": self.get_weights(),
                                     "iteration": self.iteration})

    def restore(self, checkpoint: Checkpoint) -> None:
        d = checkpoint.to_dict()
        self.set_weights(d["weights"])
        self.iteration = d.get("iteration", 0)

    def stop(self) -> None:
        self.env.close()
