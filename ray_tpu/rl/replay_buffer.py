"""Replay buffers: uniform + prioritized.

Analog of /root/reference/rllib/utils/replay_buffers/
(replay_buffer.py, prioritized_replay_buffer.py with sum-tree sampling).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ray_tpu.rl.sample_batch import SampleBatch


class ReplayBuffer:
    """Uniform ring buffer over rows."""

    def __init__(self, capacity: int, seed: Optional[int] = None):
        self.capacity = capacity
        self._cols: Optional[Dict[str, np.ndarray]] = None
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self):
        return self._size

    def add(self, batch: SampleBatch) -> None:
        n = batch.count
        if self._cols is None:
            self._cols = {
                k: np.zeros((self.capacity,) + v.shape[1:], v.dtype)
                for k, v in batch.items()}
        for start in range(0, n, self.capacity):
            chunk = batch.slice(start, min(start + self.capacity, n))
            c = chunk.count
            end = self._next + c
            for k, v in chunk.items():
                if end <= self.capacity:
                    self._cols[k][self._next:end] = v
                else:
                    split = self.capacity - self._next
                    self._cols[k][self._next:] = v[:split]
                    self._cols[k][:end % self.capacity] = v[split:]
            self._next = end % self.capacity
            self._size = min(self._size + c, self.capacity)

    def sample(self, num_items: int) -> SampleBatch:
        idx = self._rng.integers(0, self._size, num_items)
        return SampleBatch({k: v[idx] for k, v in self._cols.items()})


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritization (sum-tree) with importance weights."""

    def __init__(self, capacity: int, alpha: float = 0.6,
                 seed: Optional[int] = None):
        super().__init__(capacity, seed)
        self.alpha = alpha
        # sum tree over capacity leaves
        self._tree_size = 1
        while self._tree_size < capacity:
            self._tree_size *= 2
        self._tree = np.zeros(2 * self._tree_size)
        self._max_priority = 1.0

    def _set_priority(self, idx: int, priority: float) -> None:
        pos = self._tree_size + idx
        delta = priority - self._tree[pos]
        while pos >= 1:
            self._tree[pos] += delta
            pos //= 2

    def add(self, batch: SampleBatch) -> None:
        start = self._next
        n = batch.count
        super().add(batch)
        p = self._max_priority ** self.alpha
        for i in range(n):
            self._set_priority((start + i) % self.capacity, p)

    def sample(self, num_items: int, beta: float = 0.4) -> SampleBatch:
        total = self._tree[1]
        targets = self._rng.uniform(0, total, num_items)
        idx = np.empty(num_items, np.int64)
        for j, t in enumerate(targets):
            pos = 1
            while pos < self._tree_size:
                left = 2 * pos
                if self._tree[left] >= t:
                    pos = left
                else:
                    t -= self._tree[left]
                    pos = left + 1
            idx[j] = min(pos - self._tree_size, self._size - 1)
        probs = self._tree[self._tree_size + idx] / max(total, 1e-9)
        weights = (self._size * probs) ** (-beta)
        weights = weights / weights.max()
        out = SampleBatch({k: v[idx] for k, v in self._cols.items()})
        out["batch_indexes"] = idx
        out["weights"] = weights.astype(np.float32)
        return out

    def update_priorities(self, idx: np.ndarray,
                          priorities: np.ndarray) -> None:
        for i, p in zip(idx, priorities):
            p = float(abs(p)) + 1e-6
            self._max_priority = max(self._max_priority, p)
            self._set_priority(int(i), p ** self.alpha)
