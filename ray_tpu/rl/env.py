"""Environment API + in-repo classic-control envs.

The reference rides gym (rllib/env/); this image ships no gym, so the env
interface here is gymnasium-compatible (reset()->(obs, info),
step()->(obs, reward, terminated, truncated, info)) and user-supplied gym
envs plug in unchanged. CartPole's dynamics follow the classic Barto-
Sutton-Anderson formulation (the same one gym implements) so reference
tuned targets (reward 150 within 100k steps, rllib/tuned_examples/ppo/
cartpole-ppo.yaml) are comparable.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class Space:
    def sample(self, rng: np.random.Generator):
        raise NotImplementedError


class Discrete(Space):
    def __init__(self, n: int):
        self.n = n
        self.shape = ()
        self.dtype = np.int32

    def sample(self, rng):
        return int(rng.integers(self.n))

    def __repr__(self):
        return f"Discrete({self.n})"


class Box(Space):
    def __init__(self, low, high, shape=None, dtype=np.float32):
        self.low = np.broadcast_to(np.asarray(low, dtype),
                                   shape or np.shape(low)).copy()
        self.high = np.broadcast_to(np.asarray(high, dtype),
                                    shape or np.shape(high)).copy()
        self.shape = self.low.shape
        self.dtype = dtype

    def sample(self, rng):
        return rng.uniform(self.low, self.high).astype(self.dtype)

    def __repr__(self):
        return f"Box{self.shape}"


class Env:
    observation_space: Space
    action_space: Space

    def reset(self, *, seed: Optional[int] = None) -> Tuple[Any, dict]:
        raise NotImplementedError

    def step(self, action) -> Tuple[Any, float, bool, bool, dict]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class CartPoleEnv(Env):
    """Pole balancing; solved ≈ mean reward 475 (v1 caps at 500)."""

    def __init__(self, max_steps: int = 500):
        self.gravity = 9.8
        self.masscart, self.masspole = 1.0, 0.1
        self.total_mass = self.masscart + self.masspole
        self.length = 0.5
        self.polemass_length = self.masspole * self.length
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.x_threshold = 2.4
        self.max_steps = max_steps
        high = np.array([self.x_threshold * 2, np.inf,
                         self.theta_threshold * 2, np.inf], np.float32)
        self.observation_space = Box(-high, high)
        self.action_space = Discrete(2)
        self._rng = np.random.default_rng()
        self._state = None
        self._steps = 0

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._steps = 0
        return self._state.astype(np.float32), {}

    def step(self, action):
        x, x_dot, theta, theta_dot = self._state
        force = self.force_mag if action == 1 else -self.force_mag
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + self.polemass_length * theta_dot ** 2 * sintheta) \
            / self.total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0
                           - self.masspole * costheta ** 2 / self.total_mass))
        xacc = temp - self.polemass_length * thetaacc * costheta \
            / self.total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        self._state = np.array([x, x_dot, theta, theta_dot])
        self._steps += 1
        terminated = bool(abs(x) > self.x_threshold
                          or abs(theta) > self.theta_threshold)
        truncated = self._steps >= self.max_steps
        return (self._state.astype(np.float32), 1.0, terminated, truncated,
                {})


class PendulumEnv(Env):
    """Continuous-action swing-up (gym Pendulum-v1 dynamics)."""

    def __init__(self, max_steps: int = 200):
        self.max_speed = 8.0
        self.max_torque = 2.0
        self.dt = 0.05
        self.g, self.m, self.length = 10.0, 1.0, 1.0
        self.max_steps = max_steps
        high = np.array([1.0, 1.0, self.max_speed], np.float32)
        self.observation_space = Box(-high, high)
        self.action_space = Box(-self.max_torque, self.max_torque, (1,))
        self._rng = np.random.default_rng()
        self._steps = 0

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._th = self._rng.uniform(-np.pi, np.pi)
        self._thdot = self._rng.uniform(-1.0, 1.0)
        self._steps = 0
        return self._obs(), {}

    def _obs(self):
        return np.array([np.cos(self._th), np.sin(self._th), self._thdot],
                        np.float32)

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          -self.max_torque, self.max_torque))
        th, thdot = self._th, self._thdot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        thdot = thdot + (3 * self.g / (2 * self.length) * np.sin(th)
                         + 3.0 / (self.m * self.length ** 2) * u) * self.dt
        thdot = np.clip(thdot, -self.max_speed, self.max_speed)
        self._th = th + thdot * self.dt
        self._thdot = thdot
        self._steps += 1
        return self._obs(), -cost, False, self._steps >= self.max_steps, {}


_REGISTRY: Dict[str, Callable[[], Env]] = {
    "CartPole-v1": CartPoleEnv,
    "Pendulum-v1": PendulumEnv,
}


def register_env(name: str, creator: Callable[[], Env]) -> None:
    """cf. ray.tune.registry.register_env."""
    _REGISTRY[name] = creator


def make_env(spec) -> Env:
    if isinstance(spec, Env):
        return spec
    if callable(spec):
        return spec()
    if isinstance(spec, str):
        if spec not in _REGISTRY:
            raise ValueError(
                f"unknown env {spec!r}; register_env() it first "
                f"(known: {sorted(_REGISTRY)})")
        return _REGISTRY[spec]()
    raise TypeError(f"bad env spec {spec!r}")


class VectorEnv:
    """N synchronous env copies with auto-reset (cf. rllib VectorEnv)."""

    def __init__(self, spec, num_envs: int, seed: Optional[int] = None):
        self.envs: List[Env] = [make_env(spec) for _ in range(num_envs)]
        self.num_envs = num_envs
        self.observation_space = self.envs[0].observation_space
        self.action_space = self.envs[0].action_space
        self._seed = seed

    def reset(self) -> np.ndarray:
        obs = []
        for i, e in enumerate(self.envs):
            seed = None if self._seed is None else self._seed + i
            o, _ = e.reset(seed=seed)
            obs.append(o)
        return np.stack(obs)

    def step(self, actions) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                     np.ndarray, List[dict]]:
        obs, rews, terms, truncs, infos = [], [], [], [], []
        for e, a in zip(self.envs, actions):
            o, r, term, trunc, info = e.step(a)
            if term or trunc:
                info = dict(info, terminal_observation=o)
                o, _ = e.reset()
            obs.append(o)
            rews.append(r)
            terms.append(term)
            truncs.append(trunc)
            infos.append(info)
        return (np.stack(obs), np.asarray(rews, np.float32),
                np.asarray(terms), np.asarray(truncs), infos)
