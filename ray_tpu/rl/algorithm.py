"""Algorithm + AlgorithmConfig: the RL training driver.

Analog of /root/reference/rllib/algorithms/algorithm.py:142 (a Trainable;
training_step :1284) and algorithm_config.py:124 (fluent builder). The
TPU-native shape (SURVEY.md §2.6): CPU rollout actors sample; the learner
is a pjit step over the device mesh (data-sharded batch), so gradient
collectives ride ICI inside the compiled step instead of NCCL.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rl.worker_set import WorkerSet


_STATE_ATTRS = ("params", "target_params", "opt_state")
_COUNTER_ATTRS = ("_steps_since_target_sync",)


def full_training_state(algo) -> Optional[dict]:
    """Host-side snapshot of an algorithm's COMPLETE training state — a
    versioned envelope around either a ``self.state`` dict or separate
    params/target_params/opt_state attributes, plus schedule counters.
    One implementation shared by Algorithm subclasses and the standalone
    offline learners so the checkpoint protocol can't drift per-algo."""
    import jax
    out: dict = {"_format": "v2"}
    if getattr(algo, "state", None) is not None:
        out["state"] = jax.tree.map(np.asarray, algo.state)
    elif hasattr(algo, "params") and hasattr(algo, "opt_state"):
        for attr in _STATE_ATTRS:
            if hasattr(algo, attr):
                out[attr] = jax.tree.map(np.asarray, getattr(algo, attr))
    else:
        return None
    counters = {c: int(getattr(algo, c)) for c in _COUNTER_ATTRS
                if hasattr(algo, c)}
    if counters:
        out["_counters"] = counters
    return out


def apply_full_training_state(algo, full: dict) -> None:
    import jax
    import jax.numpy as jnp
    sharding = getattr(algo, "repl_sharding", None)
    if sharding is not None:
        # keep the replicated placement donated jitted updates expect
        put = lambda t: jax.device_put(  # noqa: E731
            jax.tree.map(jnp.asarray, t), sharding)
    else:
        put = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
    if not (isinstance(full, dict) and full.get("_format") == "v2"):
        # pre-envelope full-state checkpoint: the bare self.state tree
        algo.state = put(full)
        return
    if "state" in full:
        algo.state = put(full["state"])
    for attr in _STATE_ATTRS:
        if attr in full:
            setattr(algo, attr, put(full[attr]))
    for c, v in (full.get("_counters") or {}).items():
        setattr(algo, c, v)


def init_actor_critic(cfg):
    """Probe ``cfg.env_spec`` and build the shared ActorCritic tower:
    returns (model, params, continuous, logp_fn, ent_fn).  Module-level
    so the podracer LearnerActor builds the identical tower from a bare
    config object without instantiating an Algorithm (which would spawn
    a WorkerSet inside the learner process)."""
    import jax
    import jax.numpy as jnp
    from ray_tpu.rl import models as M
    from ray_tpu.rl.env import Box, make_env
    probe = make_env(cfg.env_spec)
    continuous = isinstance(probe.action_space, Box)
    act_dim = int(np.prod(probe.action_space.shape)) if continuous \
        else probe.action_space.n
    obs_dim = int(np.prod(probe.observation_space.shape))
    probe.close()
    model = M.ActorCritic(action_dim=act_dim, hidden=tuple(cfg.hidden),
                          continuous=continuous)
    params = model.init(jax.random.PRNGKey(cfg.seed or 0),
                        jnp.zeros((1, obs_dim)))["params"]
    if continuous:
        logp_fn, ent_fn = M.diag_gaussian_logp, M.diag_gaussian_entropy
    else:
        logp_fn, ent_fn = M.categorical_logp, M.categorical_entropy
    return model, params, continuous, logp_fn, ent_fn


class AlgorithmConfig:
    """Fluent builder: ``PPOConfig().environment("CartPole-v1")
    .rollouts(num_rollout_workers=2).training(lr=5e-5).build()``."""

    algo_class: Optional[type] = None

    def __init__(self):
        self.env_spec: Any = None
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 1
        self.rollout_fragment_length = 200
        self.recreate_failed_workers = True
        self.gamma = 0.99
        self.lam = 0.95
        self.lr = 5e-5
        self.train_batch_size = 4000
        self.sgd_minibatch_size = 128
        self.num_sgd_iter = 30
        self.grad_clip = 0.5
        self.hidden = (256, 256)
        self.seed: Optional[int] = None
        self.mesh_shape: Optional[Dict[str, int]] = None
        self.use_podracer = False
        self.podracer_kwargs: Dict[str, Any] = {}
        self.extra: Dict[str, Any] = {}

    # -- fluent sections (reference names) --------------------------------
    def environment(self, env=None, **kwargs) -> "AlgorithmConfig":
        if env is not None:
            self.env_spec = env
        self.extra.update(kwargs)
        return self

    def rollouts(self, *, num_rollout_workers: Optional[int] = None,
                 num_envs_per_worker: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None,
                 recreate_failed_workers: Optional[bool] = None,
                 **kwargs) -> "AlgorithmConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if recreate_failed_workers is not None:
            self.recreate_failed_workers = recreate_failed_workers
        self.extra.update(kwargs)
        return self

    env_runners = rollouts   # newer reference API name

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self

    def resources(self, *, mesh_shape: Optional[Dict[str, int]] = None,
                  **kwargs) -> "AlgorithmConfig":
        if mesh_shape is not None:
            self.mesh_shape = mesh_shape
        self.extra.update(kwargs)
        return self

    def debugging(self, *, seed: Optional[int] = None,
                  **kwargs) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        self.extra.update(kwargs)
        return self

    def podracer(self, enabled: bool = True,
                 **kwargs) -> "AlgorithmConfig":
        """Run on the streaming learner–actor executor
        (docs/rl_podracer.md) instead of the blocking driver.  Extra
        kwargs (e.g. ``strict_zero_submit=False``) reach the
        PodracerExecutor constructor."""
        self.use_podracer = enabled
        self.podracer_kwargs.update(kwargs)
        return self

    def build(self) -> "Algorithm":
        if self.algo_class is None:
            raise ValueError("use a concrete config (PPOConfig, ...)")
        return self.algo_class(self)

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items() if k != "extra"}


class Algorithm:
    """Base driver: owns the WorkerSet + learner; subclasses implement
    training_step() returning a result dict."""

    # subclasses that ride the podracer executor name their step
    # builder here ("impala" / "ppo"); None = classic-only algorithm
    podracer_algo: Optional[str] = None

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        if config.env_spec is None:
            raise ValueError("config.environment(env) is required")
        self.iteration = 0
        self._timesteps_total = 0
        self._episode_history: List[Dict[str, float]] = []
        if getattr(config, "use_podracer", False):
            if self.podracer_algo is None:
                raise ValueError(
                    f"{type(self).__name__} does not support the "
                    "podracer executor (only IMPALA/PPO do)")
            from ray_tpu.rl.podracer import PodracerExecutor
            self.workers = None
            self.podracer = PodracerExecutor(
                self.podracer_algo, config,
                **getattr(config, "podracer_kwargs", {}))
            return
        self.podracer = None
        worker_kwargs = dict(
            num_envs=config.num_envs_per_worker,
            rollout_fragment_length=config.rollout_fragment_length,
            gamma=config.gamma, lam=config.lam,
            hidden=config.hidden, seed=config.seed)
        worker_kwargs.update(self.extra_worker_kwargs(config))
        self.workers = WorkerSet(
            config.env_spec,
            num_workers=max(config.num_rollout_workers, 1),
            worker_kwargs=worker_kwargs,
            recreate_failed_workers=config.recreate_failed_workers)
        self.setup_learner()
        self.workers.sync_weights(self.get_weights())

    # -- learner plumbing shared by the algorithms -------------------------
    def build_learner_mesh(self) -> None:
        """Set self.mesh / self.batch_sharding / self.repl_sharding from
        config.mesh_shape (default: data-parallel over all devices)."""
        import jax
        from jax.experimental import mesh_utils
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        shape = self.config.mesh_shape or {"data": jax.device_count()}
        self.mesh = Mesh(mesh_utils.create_device_mesh(
            tuple(shape.values())), tuple(shape.keys()))
        self.batch_sharding = NamedSharding(self.mesh, P("data"))
        self.repl_sharding = NamedSharding(self.mesh, P())

    def init_actor_critic(self):
        """Probe the env and build the shared ActorCritic tower: returns
        (model, params, continuous, logp_fn, ent_fn). Used by the whole
        on-policy family (PG/A2C/PPO/IMPALA/APPO)."""
        return init_actor_critic(self.config)

    def gather_on_policy_batch(self, min_size: int):
        """synchronous_parallel_sample: pull worker fragments until the
        batch reaches ``min_size`` rows (rollout_ops.py:21)."""
        from ray_tpu.rl.sample_batch import SampleBatch
        batches = self.workers.foreach_worker("sample")
        train_batch = SampleBatch.concat_samples(batches)
        while train_batch.count < min_size:
            more = self.workers.foreach_worker("sample")
            if not more:
                break
            train_batch = SampleBatch.concat_samples([train_batch] + more)
        self._timesteps_total += train_batch.count
        return train_batch

    def round_minibatch(self, size: int) -> int:
        """Largest size >= n_shards divisible by the data-axis shard count."""
        n_shards = self.mesh.devices.size
        size = max(size, n_shards)
        return size - size % n_shards

    def stage_batch(self, sample, keys) -> Dict[str, Any]:
        """device_put selected columns sharded over the data axis."""
        import jax
        return {k: jax.device_put(np.asarray(v), self.batch_sharding)
                for k, v in sample.items() if k in keys}

    # -- subclass surface --------------------------------------------------
    @classmethod
    def extra_worker_kwargs(cls, config: AlgorithmConfig) -> Dict[str, Any]:
        """Extra RolloutWorker kwargs (e.g. DQN selects the Q policy)."""
        return {}

    def setup_learner(self) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def get_weights(self) -> Any:
        raise NotImplementedError

    def set_weights(self, weights: Any) -> None:
        raise NotImplementedError

    # -- public API --------------------------------------------------------
    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        if self.podracer is not None:
            result = self.podracer.train_iteration()
            self._timesteps_total = result.pop("timesteps_this_iter")
            # episode metrics ride the fragment stream's meta (no extra
            # foreach_worker round trip in podracer mode)
            self._episode_history = \
                self.podracer.collect_episode_metrics()
            metrics = self._summarize_episodes()
            restarts = self.podracer.telemetry["replacements"]
        else:
            result = self.training_step()
            metrics = self._collect_episode_metrics()
            restarts = self.workers.num_restarts
        self.iteration += 1
        result.update(metrics)
        result["training_iteration"] = self.iteration
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.perf_counter() - t0
        result["num_worker_restarts"] = restarts
        return result

    def _summarize_episodes(self) -> Dict[str, Any]:
        if not self._episode_history:
            return {"episode_reward_mean": float("nan"),
                    "episode_len_mean": float("nan"), "episodes_total": 0}
        rewards = [e["episode_reward"] for e in self._episode_history]
        lens = [e["episode_len"] for e in self._episode_history]
        return {"episode_reward_mean": float(np.mean(rewards)),
                "episode_reward_max": float(np.max(rewards)),
                "episode_reward_min": float(np.min(rewards)),
                "episode_len_mean": float(np.mean(lens)),
                "episodes_total": len(self._episode_history)}

    def _collect_episode_metrics(self) -> Dict[str, Any]:
        for eps in self.workers.foreach_worker("get_metrics"):
            self._episode_history.extend(eps)
        self._episode_history = self._episode_history[-100:]
        return self._summarize_episodes()

    def get_full_state(self):
        """Complete training state for checkpointing — actor AND critics,
        target networks, optimizer moments, sync counters (reference
        semantics: a resumed run continues training, it doesn't restart
        the critics/Adam moments from scratch).  Covers both storage
        conventions: a ``self.state`` dict, or separate
        params/target_params/opt_state attributes (PPO/DQN style).
        Returns None only for algorithms with neither (they fall back to
        weights-only checkpoints)."""
        if self.podracer is not None:
            return self.podracer.get_full_state()
        return full_training_state(self)

    # (helpers defined at module scope so the standalone offline
    # algorithms — CQL/CRR/MADDPG — share the exact same protocol)

    def set_full_state(self, state) -> None:
        if self.podracer is not None:
            self.podracer.set_full_state(state)
            return
        apply_full_training_state(self, state)

    def save(self) -> Checkpoint:
        full = self.get_full_state()
        d = {"state": full, "iteration": self.iteration,
             "timesteps_total": self._timesteps_total}
        if full is None:
            d["weights"] = self.get_weights()
        return Checkpoint.from_dict(d)

    def restore(self, checkpoint: Checkpoint) -> None:
        d = checkpoint.to_dict()
        if d.get("state") is not None:
            self.set_full_state(d["state"])
        elif self.podracer is not None:
            self.podracer.set_weights(d["weights"])
        else:
            # legacy weight-only checkpoint (or weight-only algorithm)
            self.set_weights(d["weights"])
        self.iteration = d.get("iteration", 0)
        self._timesteps_total = d.get("timesteps_total", 0)
        if self.podracer is None:
            self.workers.sync_weights(self.get_weights())
        # podracer: set_full_state/set_weights republished a version;
        # every actor adopts it at its next fragment boundary

    def stop(self) -> None:
        if self.podracer is not None:
            self.podracer.stop()
            return
        self.workers.stop()

    @classmethod
    def as_trainable(cls, config: AlgorithmConfig) -> Callable:
        """Tune integration: a function trainable running this algorithm."""
        def _trainable(trial_config: Dict[str, Any]):
            from ray_tpu.air import session
            import copy
            cfg = copy.deepcopy(config)
            cfg.training(**trial_config)
            algo = cfg.algo_class(cfg)
            try:
                ckpt = session.get_checkpoint()
                if ckpt is not None:
                    algo.restore(ckpt)
                while True:
                    result = algo.train()
                    session.report(result, checkpoint=algo.save())
            finally:
                algo.stop()
        return _trainable
