"""DatasetPipeline: windowed/streaming execution over a Dataset.

Analog of /root/reference/python/ray/data/dataset_pipeline.py: a pipeline
is a sequence of (lazily executed) Dataset windows; per-window transforms
apply to each window as it streams, letting ingest overlap with training
epochs without materializing the whole dataset.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional


class DatasetPipeline:
    def __init__(self, window_fn: Callable[[], Iterator["Any"]],
                 length: Optional[int] = None):
        self._window_fn = window_fn     # () -> iterator of Datasets
        self._length = length

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_dataset(cls, ds, blocks_per_window: int) -> "DatasetPipeline":
        from ray_tpu.data.dataset import Dataset, ExecutionPlan

        def gen():
            refs = ds._plan.execute()
            for i in range(0, len(refs), blocks_per_window):
                yield Dataset(ExecutionPlan(
                    block_refs=refs[i:i + blocks_per_window]))

        n = (ds.num_blocks() + blocks_per_window - 1) // blocks_per_window
        return cls(gen, n)

    @classmethod
    def from_dataset_repeated(cls, ds,
                              times: Optional[int]) -> "DatasetPipeline":
        def gen():
            import itertools
            it = range(times) if times else itertools.count()
            for _ in it:
                yield ds

        return cls(gen, times)

    # -- per-window transforms --------------------------------------------
    def _transform(self, f: Callable[[Any], Any],
                   name: str) -> "DatasetPipeline":
        prev = self._window_fn

        def gen():
            for w in prev():
                yield f(w)

        return DatasetPipeline(gen, self._length)

    def map(self, fn, **kw) -> "DatasetPipeline":
        return self._transform(lambda d: d.map(fn, **kw), "map")

    def map_batches(self, fn, **kw) -> "DatasetPipeline":
        return self._transform(lambda d: d.map_batches(fn, **kw),
                               "map_batches")

    def filter(self, fn, **kw) -> "DatasetPipeline":
        return self._transform(lambda d: d.filter(fn, **kw), "filter")

    def flat_map(self, fn, **kw) -> "DatasetPipeline":
        return self._transform(lambda d: d.flat_map(fn, **kw), "flat_map")

    def random_shuffle_each_window(self, **kw) -> "DatasetPipeline":
        return self._transform(lambda d: d.random_shuffle(**kw), "shuffle")

    def repartition_each_window(self, n: int) -> "DatasetPipeline":
        return self._transform(lambda d: d.repartition(n), "repartition")

    # -- consumption -------------------------------------------------------
    def iter_datasets(self) -> Iterator[Any]:
        return self._window_fn()

    def iter_rows(self) -> Iterator[Any]:
        for ds in self.iter_datasets():
            yield from ds.iter_rows()

    def iter_batches(self, **kw) -> Iterator[Any]:
        for ds in self.iter_datasets():
            yield from ds.iter_batches(**kw)

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def count(self) -> int:
        return sum(ds.count() for ds in self.iter_datasets())

    def split(self, n: int) -> List["DatasetPipeline"]:
        """Round-robin window assignment to n consumer pipelines (each
        worker consumes its own sub-pipeline)."""
        out = []
        for i in range(n):
            def gen(i=i):
                for j, ds in enumerate(self._window_fn()):
                    if j % n == i:
                        yield ds
            out.append(DatasetPipeline(gen))
        return out

    def __repr__(self):
        ln = self._length if self._length is not None else "inf"
        return f"DatasetPipeline(windows={ln})"
