"""Preprocessors: fit statistics on a Dataset, transform datasets/batches.

Analog of /root/reference/python/ray/data/preprocessors/ (scaler.py,
encoder.py, imputer.py, batch_mapper.py, chain.py, concatenator.py) and the
air Preprocessor base (/root/reference/python/ray/air/_internal — fit/
transform/transform_batch lifecycle).  TPU-shaped: statistics are computed
as one distributed numpy aggregation pass (map_batches over blocks, combine
on the driver) and transform is a stateless map_batches, so a fitted
preprocessor pickles into Train/Serve workers and applies per-batch at
ingest/serving time.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


class Preprocessor:
    """fit(ds) learns state; transform(ds)/transform_batch(batch) apply it."""

    _is_fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._is_fitted = True
        return self

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform(self, ds):
        if not self._is_fitted and type(self)._fit is not Preprocessor._fit:
            raise RuntimeError(f"{type(self).__name__} must be fit first")
        return ds.map_batches(self.transform_batch, batch_format="numpy")

    def transform_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def _fit(self, ds) -> None:
        """Default: stateless preprocessor (nothing to fit)."""

    def _aggregate(self, ds, stat_fn: Callable[[Dict[str, np.ndarray]], Any]
                   ) -> List[Any]:
        """Run ``stat_fn`` over every block (distributed) and collect."""
        stats = ds.map_batches(
            lambda b: [stat_fn(b)], batch_size=None, batch_format="numpy")
        return stats.take_all()


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference preprocessors/scaler.py)."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, Any] = {}

    def _fit(self, ds) -> None:
        cols = self.columns

        def stat(batch):
            return {c: (float(np.sum(batch[c], dtype=np.float64)),
                        float(np.sum(np.square(batch[c], dtype=np.float64))),
                        int(np.asarray(batch[c]).shape[0])) for c in cols}

        agg = {c: [0.0, 0.0, 0] for c in cols}
        for s in self._aggregate(ds, stat):
            for c, (sm, sq, n) in s.items():
                agg[c][0] += sm
                agg[c][1] += sq
                agg[c][2] += n
        for c, (sm, sq, n) in agg.items():
            mean = sm / max(n, 1)
            var = max(sq / max(n, 1) - mean * mean, 0.0)
            self.stats_[c] = (mean, float(np.sqrt(var)))

    def transform_batch(self, batch):
        out = dict(batch)
        for c, (mean, std) in self.stats_.items():
            out[c] = (np.asarray(batch[c]) - mean) / (std or 1.0)
        return out


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, Any] = {}

    def _fit(self, ds) -> None:
        cols = self.columns

        def stat(batch):
            return {c: (float(np.min(batch[c])), float(np.max(batch[c])))
                    for c in cols}

        agg = {c: (np.inf, -np.inf) for c in cols}
        for s in self._aggregate(ds, stat):
            for c, (lo, hi) in s.items():
                agg[c] = (min(agg[c][0], lo), max(agg[c][1], hi))
        self.stats_ = agg

    def transform_batch(self, batch):
        out = dict(batch)
        for c, (lo, hi) in self.stats_.items():
            span = (hi - lo) or 1.0
            out[c] = (np.asarray(batch[c]) - lo) / span
        return out


class LabelEncoder(Preprocessor):
    """Categorical column -> dense int codes (sorted unique order)."""

    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: List[Any] = []

    def _fit(self, ds) -> None:
        col = self.label_column
        uniques = set()
        for s in self._aggregate(
                ds, lambda b: list(np.unique(np.asarray(b[col])))):
            uniques.update(s)
        self.classes_ = sorted(uniques)

    def transform_batch(self, batch):
        out = dict(batch)
        index = {v: i for i, v in enumerate(self.classes_)}
        vals = np.asarray(batch[self.label_column])
        out[self.label_column] = np.asarray(
            [index[v] for v in vals.tolist()], np.int64)
        return out


class OneHotEncoder(Preprocessor):
    """Categorical columns -> {col}_{value} 0/1 indicator columns."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, List[Any]] = {}

    def _fit(self, ds) -> None:
        cols = self.columns
        uniques: Dict[str, set] = {c: set() for c in cols}
        for s in self._aggregate(
                ds, lambda b: {c: list(np.unique(np.asarray(b[c])))
                               for c in cols}):
            for c, vals in s.items():
                uniques[c].update(vals)
        self.stats_ = {c: sorted(v) for c, v in uniques.items()}

    def transform_batch(self, batch):
        out = dict(batch)
        for c, values in self.stats_.items():
            col = np.asarray(batch[c])
            for v in values:
                out[f"{c}_{v}"] = (col == v).astype(np.int64)
            del out[c]
        return out


class SimpleImputer(Preprocessor):
    """Fill NaNs with the column mean ("mean") or a constant."""

    def __init__(self, columns: List[str], strategy: str = "mean",
                 fill_value: Optional[float] = None):
        if strategy not in ("mean", "constant"):
            raise ValueError(f"unknown strategy {strategy!r}")
        if strategy == "constant" and fill_value is None:
            raise ValueError("strategy='constant' requires fill_value")
        self.columns = list(columns)
        self.strategy = strategy
        self.fill_value = fill_value
        self.stats_: Dict[str, float] = {}

    def _fit(self, ds) -> None:
        if self.strategy == "constant":
            self.stats_ = {c: float(self.fill_value) for c in self.columns}
            return
        cols = self.columns

        def stat(batch):
            return {c: (float(np.nansum(np.asarray(batch[c], np.float64))),
                        int(np.sum(~np.isnan(np.asarray(batch[c],
                                                        np.float64)))))
                    for c in cols}

        agg = {c: [0.0, 0] for c in cols}
        for s in self._aggregate(ds, stat):
            for c, (sm, n) in s.items():
                agg[c][0] += sm
                agg[c][1] += n
        self.stats_ = {c: sm / max(n, 1) for c, (sm, n) in agg.items()}

    def transform_batch(self, batch):
        out = dict(batch)
        for c, fill in self.stats_.items():
            col = np.asarray(batch[c], np.float64)
            out[c] = np.where(np.isnan(col), fill, col)
        return out


class Concatenator(Preprocessor):
    """Merge numeric columns into one 2-D feature matrix column."""

    def __init__(self, columns: List[str], output_column_name: str = "features",
                 dtype: Any = np.float32):
        self.columns = list(columns)
        self.output_column_name = output_column_name
        self.dtype = dtype

    def transform_batch(self, batch):
        out = {k: v for k, v in batch.items() if k not in self.columns}
        mats = [np.asarray(batch[c]).reshape(len(np.asarray(batch[c])), -1)
                for c in self.columns]
        out[self.output_column_name] = np.concatenate(
            mats, axis=1).astype(self.dtype)
        return out


class BatchMapper(Preprocessor):
    """Wrap a user batch function as a (stateless) preprocessor."""

    def __init__(self, fn: Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]]):
        self.fn = fn

    def transform_batch(self, batch):
        return self.fn(batch)


class Chain(Preprocessor):
    """Apply preprocessors in sequence; fit each on the previous output."""

    def __init__(self, *preprocessors: Preprocessor):
        self.preprocessors = list(preprocessors)

    def _fit(self, ds) -> None:
        for p in self.preprocessors[:-1]:
            ds = p.fit_transform(ds)
        if self.preprocessors:
            self.preprocessors[-1].fit(ds)

    def transform_batch(self, batch):
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch
