"""Dataset: distributed data as blocks in the object store.

Analog of /root/reference/python/ray/data/dataset.py:139 (Dataset,
map_batches :323) with the lazy ExecutionPlan of _internal/plan.py:74:
stages accumulate lazily, consecutive row-wise stages fuse into one task
per block (read→map fusion), and all-to-all stages (shuffle/sort/
repartition) run the two-phase push-based pattern of
_internal/push_based_shuffle.py. Compute strategies mirror
_internal/compute.py:58/176 (task pool default, actor pool for stateful /
expensive-setup UDFs).
"""

from __future__ import annotations

import itertools
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Tuple, Union)

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor, build_block_like
from ray_tpu.data.datasource import ReadTask


class TaskPoolStrategy:
    """One remote task per block (default)."""


class ActorPoolStrategy:
    """A pool of actors applying the UDF — for stateful/setup-heavy fns
    (model inference). cf. reference _internal/compute.py:176."""

    def __init__(self, min_size: int = 1, max_size: Optional[int] = None):
        self.min_size = min_size
        self.max_size = max_size or min_size


ComputeStrategy = Union[TaskPoolStrategy, ActorPoolStrategy, str, None]


class _OneToOne:
    def __init__(self, name: str, fn: Callable[[Block], Block],
                 compute: ComputeStrategy = None,
                 num_cpus: float = 1.0):
        self.name = name
        self.fn = fn
        self.compute = compute
        self.num_cpus = num_cpus

    def can_fuse(self, other: "_OneToOne") -> bool:
        return not isinstance(self.compute, ActorPoolStrategy) \
            and not isinstance(other.compute, ActorPoolStrategy)

    def fuse(self, other: "_OneToOne") -> "_OneToOne":
        f, g = self.fn, other.fn
        return _OneToOne(f"{self.name}->{other.name}",
                         lambda b: g(f(b)), other.compute,
                         max(self.num_cpus, other.num_cpus))


class _AllToAll:
    def __init__(self, name: str,
                 fn: Callable[[List[Any]], List[Any]]):
        self.name = name
        self.fn = fn   # List[ObjectRef] -> List[ObjectRef]


def _apply_block_fn(fn, block):
    """-> (block, meta): block tasks return their output plus measured
    per-block stats as a second return slot (ds.stats() plumbing)."""
    import time as _time
    from ray_tpu.data import _stats
    w0, c0 = _time.perf_counter(), _time.process_time()
    out = fn(block)
    return out, _stats.block_meta(out, w0, c0)


def _read_and_apply(task: ReadTask, fn):
    import time as _time
    from ray_tpu.data import _stats
    w0, c0 = _time.perf_counter(), _time.process_time()
    block = task()
    if fn is not None:
        block = fn(block)
    return block, _stats.block_meta(block, w0, c0)


class _BlockWorker:
    """Actor-pool worker: applies a (possibly fused) block fn."""

    def __init__(self, fn):
        self._fn = fn

    def apply(self, block):
        return _apply_block_fn(self._fn, block)


class ExecutionPlan:
    def __init__(self, read_tasks: Optional[List[ReadTask]] = None,
                 block_refs: Optional[List[Any]] = None,
                 stats_parent=None):
        assert (read_tasks is None) != (block_refs is None)
        from ray_tpu.data._stats import DatasetStats
        self._read_tasks = read_tasks
        self._input_refs = block_refs
        self._stages: List[Any] = []
        self._cache: Optional[List[Any]] = None
        self.stats = DatasetStats(parent=stats_parent)

    def with_stage(self, stage) -> "ExecutionPlan":
        if self._cache is None:
            p = ExecutionPlan(self._read_tasks, self._input_refs)
            p._stages = list(self._stages)
        else:
            # derived dataset continues from this one's materialized
            # blocks; carry the stats so ds.stats() shows the full chain
            p = ExecutionPlan(read_tasks=None, block_refs=self._cache,
                              stats_parent=self.stats)
        p._stages.append(stage)
        return p

    def execute(self) -> List[Any]:
        if self._cache is not None:
            return self._cache
        import time as _time

        # fuse consecutive one-to-one stages
        fused: List[Any] = []
        for st in self._stages:
            if isinstance(st, _OneToOne) and fused \
                    and isinstance(fused[-1], _OneToOne) \
                    and fused[-1].can_fuse(st):
                fused[-1] = fused[-1].fuse(st)
            else:
                fused.append(st)

        refs: List[Any]
        idx = 0
        if self._read_tasks is not None:
            # fuse the first run of one-to-one stages into the read tasks
            # — but never an actor-pool stage (a model must instantiate
            # once per actor, not once per block) or one with a bigger
            # resource request than the read's num_cpus=1
            first_fn = None
            if fused and isinstance(fused[0], _OneToOne) \
                    and not isinstance(fused[0].compute,
                                       ActorPoolStrategy) \
                    and fused[0].num_cpus <= 1:
                first_fn = fused[0].fn
                idx = 1
            name = "read" if first_fn is None else f"read->{fused[0].name}"
            import ray_tpu
            t0 = _time.perf_counter()
            remote_read = ray_tpu.remote(num_cpus=1,
                                         num_returns=2)(_read_and_apply)
            pairs = [remote_read.remote(t, first_fn)
                     for t in self._read_tasks]
            refs = [p[0] for p in pairs]
            self.stats.record_stage(name, _time.perf_counter() - t0,
                                    meta_refs=[p[1] for p in pairs])
        else:
            refs = list(self._input_refs)

        for st in fused[idx:]:
            t0 = _time.perf_counter()
            if isinstance(st, _OneToOne):
                refs, metas = self._run_one_to_one(st, refs)
                self.stats.record_stage(st.name,
                                        _time.perf_counter() - t0,
                                        meta_refs=metas)
            else:
                refs = st.fn(refs)
                self.stats.record_stage(st.name,
                                        _time.perf_counter() - t0,
                                        block_count=len(refs))
        self._cache = refs
        return refs

    def _run_one_to_one(self, st: _OneToOne, refs: List[Any]):
        """-> (block refs, meta refs): every block task yields its stats
        in a second return slot."""
        import ray_tpu
        if isinstance(st.compute, ActorPoolStrategy):
            pool_size = min(st.compute.max_size, max(len(refs), 1))
            actor_cls = ray_tpu.remote(num_cpus=st.num_cpus)(_BlockWorker)
            actors = [actor_cls.remote(st.fn) for _ in range(pool_size)]
            out, metas = [], []
            for i, ref in enumerate(refs):
                b, m = actors[i % pool_size].apply \
                    .options(num_returns=2).remote(ref)
                out.append(b)
                metas.append(m)
            # keep actor handles alive until results land
            ray_tpu.wait(out, num_returns=len(out))
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
            return out, metas
        remote_fn = ray_tpu.remote(num_cpus=st.num_cpus,
                                   num_returns=2)(_apply_block_fn)
        pairs = [remote_fn.remote(st.fn, ref) for ref in refs]
        return [p[0] for p in pairs], [p[1] for p in pairs]

    def num_blocks_hint(self) -> int:
        if self._cache is not None:
            return len(self._cache)
        if self._read_tasks is not None:
            return len(self._read_tasks)
        return len(self._input_refs)


class Dataset:
    def __init__(self, plan: ExecutionPlan):
        self._plan = plan

    # ---------------------------------------------------------- transforms
    def map(self, fn: Callable[[Any], Any], *,
            compute: ComputeStrategy = None,
            num_cpus: float = 1.0) -> "Dataset":
        def block_fn(block):
            acc = BlockAccessor.for_block(block)
            rows = [fn(r) for r in acc.iter_rows()]
            return build_block_like(block, rows)
        return Dataset(self._plan.with_stage(
            _OneToOne("map", block_fn, compute, num_cpus)))

    def map_batches(self, fn: Callable[[Any], Any], *,
                    batch_size: Optional[int] = None,
                    batch_format: str = "default",
                    compute: ComputeStrategy = None,
                    num_cpus: float = 1.0,
                    fn_constructor_args: Tuple = ()) -> "Dataset":
        """Apply ``fn`` to batches (cf. reference dataset.py:323). When
        ``fn`` is a class, an actor pool instantiates it once per actor
        (stateful inference)."""
        if isinstance(fn, type):
            ctor_args = fn_constructor_args
            cls = fn
            if not isinstance(compute, ActorPoolStrategy):
                compute = ActorPoolStrategy(1, 2)

            class _Stateful:
                def __init__(self):
                    self._obj = cls(*ctor_args)

                def __call__(self, batch):
                    return self._obj(batch)

            holder: Dict[str, Any] = {}

            def block_fn(block):
                if "o" not in holder:
                    holder["o"] = _Stateful()
                return _map_batches_impl(holder["o"], block, batch_size,
                                         batch_format)
        else:
            def block_fn(block):
                return _map_batches_impl(fn, block, batch_size, batch_format)
        return Dataset(self._plan.with_stage(
            _OneToOne("map_batches", block_fn, compute, num_cpus)))

    def flat_map(self, fn: Callable[[Any], Iterable[Any]], *,
                 compute: ComputeStrategy = None) -> "Dataset":
        def block_fn(block):
            acc = BlockAccessor.for_block(block)
            rows = [o for r in acc.iter_rows() for o in fn(r)]
            return build_block_like(block, rows)
        return Dataset(self._plan.with_stage(
            _OneToOne("flat_map", block_fn, compute)))

    def filter(self, fn: Callable[[Any], bool], *,
               compute: ComputeStrategy = None) -> "Dataset":
        def block_fn(block):
            acc = BlockAccessor.for_block(block)
            rows = [r for r in acc.iter_rows() if fn(r)]
            return build_block_like(block, rows)
        return Dataset(self._plan.with_stage(
            _OneToOne("filter", block_fn, compute)))

    def add_column(self, name: str, fn: Callable[[Any], Any]) -> "Dataset":
        def block_fn(block):
            acc = BlockAccessor.for_block(block)
            df = acc.to_pandas()
            df[name] = fn(df)
            return df
        return Dataset(self._plan.with_stage(
            _OneToOne("add_column", block_fn)))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def block_fn(block):
            acc = BlockAccessor.for_block(block)
            arrs = acc.to_numpy()
            return {k: v for k, v in arrs.items() if k not in cols}
        return Dataset(self._plan.with_stage(
            _OneToOne("drop_columns", block_fn)))

    def select_columns(self, cols: List[str]) -> "Dataset":
        def block_fn(block):
            acc = BlockAccessor.for_block(block)
            arrs = acc.to_numpy()
            return {k: arrs[k] for k in cols}
        return Dataset(self._plan.with_stage(
            _OneToOne("select_columns", block_fn)))

    def random_sample(self, fraction: float,
                      seed: Optional[int] = None) -> "Dataset":
        def block_fn(block):
            import random as _r
            rng = _r.Random(seed)
            acc = BlockAccessor.for_block(block)
            rows = [r for r in acc.iter_rows() if rng.random() < fraction]
            return build_block_like(block, rows)
        return Dataset(self._plan.with_stage(
            _OneToOne("random_sample", block_fn)))

    # ---------------------------------------------------------- all-to-all
    def repartition(self, num_blocks: int) -> "Dataset":
        def fn(refs):
            return _repartition_refs(refs, num_blocks)
        return Dataset(self._plan.with_stage(_AllToAll("repartition", fn)))

    def iter_repartitioned(self, rows_per_block: int,
                           ) -> Iterator[Any]:
        """Streaming repartition reader: one ``num_returns="streaming"``
        task re-chunks the dataset into ``rows_per_block``-row blocks
        and yields each the moment it is cut — the consumer (a training
        input pipeline) holds the first re-chunked block while the task
        is still reading later input blocks, instead of waiting for a
        full repartition() barrier.  Backpressure
        (``generator_backpressure_num_objects``) bounds how many
        uncollected blocks accumulate in the object store when the
        consumer is slower than the reader."""
        if rows_per_block <= 0:
            raise ValueError("rows_per_block must be positive")
        import ray_tpu
        refs = self._plan.execute()
        reader = ray_tpu.remote(num_cpus=1)(_rechunk_stream) \
            .options(num_returns="streaming")
        for item_ref in reader.remote(rows_per_block, *refs):
            yield ray_tpu.get(item_ref)

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        """Push-based two-phase shuffle (cf. reference
        _internal/push_based_shuffle.py): map tasks split each block into P
        random parts; reduce tasks concatenate their part from every map."""
        def fn(refs):
            return _shuffle_refs(refs, seed, num_blocks or len(refs))
        return Dataset(self._plan.with_stage(_AllToAll("random_shuffle", fn)))

    def sort(self, key: Any = None, descending: bool = False) -> "Dataset":
        """Distributed sample sort: sample boundaries, range-partition,
        per-partition sort (cf. reference _internal/sort.py)."""
        def fn(refs):
            return _sort_refs(refs, key, descending)
        return Dataset(self._plan.with_stage(_AllToAll("sort", fn)))

    def groupby(self, key: Any) -> "GroupedData":
        return GroupedData(self, key)

    def zip(self, other: "Dataset") -> "Dataset":
        import ray_tpu
        left = self._plan.execute()
        right = other.repartition(len(left))._plan.execute()
        remote_zip = ray_tpu.remote(num_cpus=1)(_zip_blocks)
        refs = [remote_zip.remote(l, r) for l, r in zip(left, right)]
        return Dataset(ExecutionPlan(block_refs=refs))

    def union(self, *others: "Dataset") -> "Dataset":
        refs = list(self._plan.execute())
        for o in others:
            refs.extend(o._plan.execute())
        return Dataset(ExecutionPlan(block_refs=refs))

    def limit(self, n: int) -> "Dataset":
        import ray_tpu
        refs = self._plan.execute()
        out, remaining = [], n
        for ref in refs:
            if remaining <= 0:
                break
            block = ray_tpu.get(ref)
            acc = BlockAccessor.for_block(block)
            take = min(acc.num_rows(), remaining)
            out.append(ray_tpu.put(acc.slice(0, take)))
            remaining -= take
        return Dataset(ExecutionPlan(block_refs=out))

    # ---------------------------------------------------------- consumption
    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def count(self) -> int:
        import ray_tpu
        refs = self._plan.execute()
        remote_count = ray_tpu.remote(num_cpus=1)(_count_block)
        return sum(ray_tpu.get([remote_count.remote(r) for r in refs]))

    def schema(self) -> Any:
        import ray_tpu
        for ref in self._plan.execute():
            block = ray_tpu.get(ref)
            acc = BlockAccessor.for_block(block)
            if acc.num_rows():
                return acc.schema()
        return None

    def num_blocks(self) -> int:
        return self._plan.num_blocks_hint()

    def size_bytes(self) -> int:
        import ray_tpu
        refs = self._plan.execute()
        remote_size = ray_tpu.remote(num_cpus=1)(_size_block)
        return sum(ray_tpu.get([remote_size.remote(r) for r in refs]))

    def input_files(self) -> List[str]:
        tasks = self._plan._read_tasks or []
        return [f for t in tasks for f in t.input_files]

    def iter_rows(self) -> Iterator[Any]:
        import ray_tpu
        for ref in self._plan.execute():
            block = ray_tpu.get(ref)
            yield from BlockAccessor.for_block(block).iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "default",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     prefetch_blocks: int = 1) -> Iterator[Any]:
        """Stream batches to the host train loop; with a shuffle buffer this
        is the per-host input pipeline for JaxTrainer (get_dataset_shard)."""
        import ray_tpu
        refs = self._plan.execute()
        if local_shuffle_buffer_size:
            yield from self._iter_shuffled(refs, batch_size, batch_format,
                                           drop_last,
                                           local_shuffle_buffer_size,
                                           local_shuffle_seed)
            return
        carry: Optional[Block] = None
        for i, ref in enumerate(refs):
            block = ray_tpu.get(ref)
            if carry is not None:
                block = _concat_blocks([carry, block])
                carry = None
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            if batch_size is None:
                if n:
                    yield acc.to_batch(batch_format)
                continue
            pos = 0
            while n - pos >= batch_size:
                yield BlockAccessor.for_block(
                    acc.slice(pos, pos + batch_size)).to_batch(batch_format)
                pos += batch_size
            if pos < n:
                carry = acc.slice(pos, n)
        if carry is not None:
            acc = BlockAccessor.for_block(carry)
            if acc.num_rows() and not drop_last:
                yield acc.to_batch(batch_format)

    def _iter_shuffled(self, refs, batch_size, batch_format, drop_last,
                       buffer_size, seed):
        import random as _r

        import ray_tpu
        rng = _r.Random(seed)
        buf: List[Any] = []
        template = None

        def emit():
            rows = [buf.pop(rng.randrange(len(buf)))
                    for _ in range(batch_size)]
            return BlockAccessor.for_block(
                build_block_like(template, rows)).to_batch(batch_format)

        for ref in refs:
            block = ray_tpu.get(ref)
            if template is None:
                template = block
            buf.extend(BlockAccessor.for_block(block).iter_rows())
            while len(buf) >= max(buffer_size, batch_size or 1):
                yield emit()
        while batch_size and len(buf) >= batch_size:
            yield emit()
        if buf and not drop_last:
            yield BlockAccessor.for_block(
                build_block_like(template, buf)).to_batch(batch_format)

    def iter_torch_batches(self, **kwargs) -> Iterator[Any]:
        import torch
        for batch in self.iter_batches(batch_format="numpy", **kwargs):
            yield {k: torch.as_tensor(v) for k, v in batch.items()}

    def to_torch(self, **iter_kwargs):
        """A torch IterableDataset over this dataset's batches (cf.
        reference dataset.py to_torch): each item is a dict of tensors."""
        import torch
        ds = self

        class _TorchIterable(torch.utils.data.IterableDataset):
            def __iter__(self):
                return ds.iter_torch_batches(**iter_kwargs)

        return _TorchIterable()

    # ---------------------------------------------------------- splitting
    def split(self, n: int, *, equal: bool = False,
              locality_hints: Optional[List[Any]] = None) -> List["Dataset"]:
        """Split into n datasets by block (cf. reference dataset.py
        split :978) — the per-host shard entry point for trainers."""
        import ray_tpu
        refs = self._plan.execute()
        if equal:
            total = self.count()
            per = total // n
            return self.split_at_indices(
                [per * i for i in range(1, n)])
        shards: List[List[Any]] = [[] for _ in range(n)]
        for i, ref in enumerate(refs):
            shards[i % n].append(ref)
        return [Dataset(ExecutionPlan(block_refs=s)) for s in shards]

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        import ray_tpu
        refs = self._plan.execute()
        bounds = [0] + list(indices)
        lengths = ray_tpu.get(
            [ray_tpu.remote(num_cpus=1)(_count_block).remote(r)
             for r in refs])
        out: List[List[Any]] = []
        cur: List[Any] = []
        block_starts = list(itertools.accumulate([0] + lengths))
        total = block_starts[-1]
        cuts = list(indices) + [total]
        # slice blocks so each output shard covers [bounds[i], bounds[i+1])
        remote_slice = ray_tpu.remote(num_cpus=1)(_slice_block)
        shard_refs: List[List[Any]] = [[] for _ in cuts]
        for bi, ref in enumerate(refs):
            b_start, b_end = block_starts[bi], block_starts[bi + 1]
            for si, cut_end in enumerate(cuts):
                cut_start = 0 if si == 0 else cuts[si - 1]
                lo, hi = max(b_start, cut_start), min(b_end, cut_end)
                if lo < hi:
                    if lo == b_start and hi == b_end:
                        shard_refs[si].append(ref)
                    else:
                        shard_refs[si].append(
                            remote_slice.remote(ref, lo - b_start,
                                                hi - b_start))
        return [Dataset(ExecutionPlan(block_refs=s)) for s in shard_refs]

    def train_test_split(self, test_size: float, *,
                         shuffle: bool = False,
                         seed: Optional[int] = None) -> Tuple["Dataset",
                                                              "Dataset"]:
        ds = self.random_shuffle(seed=seed) if shuffle else self
        total = ds.count()
        cut = int(total * (1 - test_size))
        left, right = ds.split_at_indices([cut])
        return left, right

    # ---------------------------------------------------------- conversion
    def to_pandas(self):
        import pandas as pd

        import ray_tpu
        dfs = [BlockAccessor.for_block(ray_tpu.get(r)).to_pandas()
               for r in self._plan.execute()]
        return pd.concat(dfs, ignore_index=True) if dfs else pd.DataFrame()

    def to_numpy(self) -> Dict[str, np.ndarray]:
        import ray_tpu
        parts = [BlockAccessor.for_block(ray_tpu.get(r)).to_numpy()
                 for r in self._plan.execute()]
        parts = [p for p in parts if p and len(next(iter(p.values())))]
        if not parts:
            return {}
        return {k: np.concatenate([p[k] for p in parts])
                for k in parts[0].keys()}

    def get_internal_block_refs(self) -> List[Any]:
        return self._plan.execute()

    def materialize(self) -> "Dataset":
        self._plan.execute()
        return self

    fully_executed = materialize

    # ---------------------------------------------------------- io
    def write_parquet(self, path: str) -> List[str]:
        return self._write(path, "parquet")

    def write_csv(self, path: str) -> List[str]:
        return self._write(path, "csv")

    def write_json(self, path: str) -> List[str]:
        return self._write(path, "json")

    def write_tfrecords(self, path: str) -> List[str]:
        import ray_tpu
        from ray_tpu.data.tfrecords import write_tfrecords_block
        refs = self._plan.execute()
        remote_write = ray_tpu.remote(num_cpus=1)(write_tfrecords_block)
        return ray_tpu.get([remote_write.remote(r, path, i)
                            for i, r in enumerate(refs)])

    def write_numpy(self, path: str, *, column: str = "data") -> List[str]:
        import ray_tpu
        from ray_tpu.data import datasource as dsrc
        refs = self._plan.execute()
        remote_write = ray_tpu.remote(num_cpus=1)(dsrc.write_numpy_block)
        return ray_tpu.get([remote_write.remote(r, path, i, column)
                            for i, r in enumerate(refs)])

    def _write(self, path: str, fmt: str) -> List[str]:
        import ray_tpu
        from ray_tpu.data import datasource as dsrc
        writer = {"parquet": dsrc.write_parquet_block,
                  "csv": dsrc.write_csv_block,
                  "json": dsrc.write_json_block}[fmt]
        refs = self._plan.execute()
        remote_write = ray_tpu.remote(num_cpus=1)(writer)
        return ray_tpu.get([remote_write.remote(r, path, i)
                            for i, r in enumerate(refs)])

    # ---------------------------------------------------------- pipeline
    def to_random_access_dataset(self, key: str, num_workers: int = 2):
        """Sorted actor-served point lookups (reference
        random_access_dataset.py)."""
        from ray_tpu.data.random_access_dataset import RandomAccessDataset
        return RandomAccessDataset(self, key, num_workers=num_workers)

    def window(self, *, blocks_per_window: int = 10) -> "DatasetPipeline":
        from ray_tpu.data.dataset_pipeline import DatasetPipeline
        return DatasetPipeline.from_dataset(self, blocks_per_window)

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        from ray_tpu.data.dataset_pipeline import DatasetPipeline
        return DatasetPipeline.from_dataset_repeated(self, times)

    def stats(self) -> str:
        """Per-stage execution report: blocks, driver wall time, remote
        wall/CPU time, output rows and bytes (reference ds.stats(),
        data/_internal/stats.py:161). Executes the plan if needed."""
        self._plan.execute()
        return self._plan.stats.summary()

    def __repr__(self):
        return f"Dataset(num_blocks={self.num_blocks()})"


# -- grouped aggregation -----------------------------------------------------

class GroupedData:
    """cf. reference data/grouped_dataset.py."""

    def __init__(self, ds: Dataset, key: Any):
        self._ds = ds
        self._key = key

    def _agg(self, init, update, merge, finalize, on: Optional[str],
             name: str) -> Dataset:
        import ray_tpu
        key = self._key
        refs = self._ds._plan.execute()
        remote_partial = ray_tpu.remote(num_cpus=1)(_partial_agg)
        partials = ray_tpu.get([
            remote_partial.remote(r, key, on, init, update) for r in refs])
        merged: Dict[Any, Any] = {}
        for part in partials:
            for k, acc in part.items():
                merged[k] = acc if k not in merged else merge(merged[k], acc)
        rows = [{key if isinstance(key, str) else "key": k,
                 name: finalize(v)} for k, v in sorted(
                     merged.items(), key=lambda kv: str(kv[0]))]
        return Dataset(ExecutionPlan(block_refs=[ray_tpu.put(rows)]))

    def count(self) -> Dataset:
        return self._agg(lambda: 0, lambda a, r, v: a + 1,
                         lambda a, b: a + b, lambda a: a, None, "count")

    def sum(self, on: str) -> Dataset:
        return self._agg(lambda: 0, lambda a, r, v: a + v,
                         lambda a, b: a + b, lambda a: a, on, f"sum({on})")

    def min(self, on: str) -> Dataset:
        return self._agg(lambda: None,
                         lambda a, r, v: v if a is None else min(a, v),
                         lambda a, b: min(a, b), lambda a: a, on,
                         f"min({on})")

    def max(self, on: str) -> Dataset:
        return self._agg(lambda: None,
                         lambda a, r, v: v if a is None else max(a, v),
                         lambda a, b: max(a, b), lambda a: a, on,
                         f"max({on})")

    def mean(self, on: str) -> Dataset:
        return self._agg(lambda: (0.0, 0),
                         lambda a, r, v: (a[0] + v, a[1] + 1),
                         lambda a, b: (a[0] + b[0], a[1] + b[1]),
                         lambda a: a[0] / a[1] if a[1] else 0.0, on,
                         f"mean({on})")


# -- remote helpers (module-level for picklability) -------------------------

def _map_batches_impl(fn, block, batch_size, batch_format):
    acc = BlockAccessor.for_block(block)
    n = acc.num_rows()
    out_blocks = []
    size = batch_size or n or 1
    for start in range(0, n, size):
        batch = BlockAccessor.for_block(
            acc.slice(start, min(start + size, n))).to_batch(batch_format)
        result = fn(batch)
        out_blocks.append(BlockAccessor.batch_to_block(result))
    if not out_blocks:
        return block
    return _concat_blocks(out_blocks)


def _concat_blocks(blocks: List[Block]) -> Block:
    if len(blocks) == 1:
        return blocks[0]
    first = blocks[0]
    if isinstance(first, dict):
        keys = first.keys()
        return {k: np.concatenate(
            [np.asarray(b[k]) for b in blocks]) for k in keys}
    if isinstance(first, list):
        return [r for b in blocks for r in b]
    try:
        import pandas as pd
        if isinstance(first, pd.DataFrame):
            return pd.concat(blocks, ignore_index=True)
    except ImportError:
        pass
    import pyarrow as pa
    return pa.concat_tables(blocks)


def _count_block(block) -> int:
    return BlockAccessor.for_block(block).num_rows()


def _size_block(block) -> int:
    return BlockAccessor.for_block(block).size_bytes()


def _slice_block(block, start: int, end: int):
    return BlockAccessor.for_block(block).slice(start, end)


def _zip_blocks(left, right):
    la = BlockAccessor.for_block(left).to_numpy()
    ra = BlockAccessor.for_block(right).to_numpy()
    out = dict(la)
    for k, v in ra.items():
        out[k if k not in out else f"{k}_1"] = v
    return out


def _partial_agg(block, key, on, init, update):
    acc = BlockAccessor.for_block(block)
    groups: Dict[Any, Any] = {}
    for row in acc.iter_rows():
        k = key(row) if callable(key) else row[key]
        v = row[on] if on else None
        groups[k] = update(groups.get(k, init()), row, v)
    return groups


def _split_block_random(block, parts: int, seed):
    import random as _r
    rng = _r.Random(seed)
    acc = BlockAccessor.for_block(block)
    rows = acc.to_list()
    rng.shuffle(rows)
    out = []
    for i in range(parts):
        out.append(build_block_like(block, rows[i::parts]))
    return out if parts > 1 else out[0]


def _merge_shuffled(seed, *parts):
    import random as _r
    rng = _r.Random(seed)
    block = _concat_blocks(list(parts))
    acc = BlockAccessor.for_block(block)
    rows = acc.to_list()
    rng.shuffle(rows)
    return build_block_like(block, rows)


def _shuffle_refs(refs: List[Any], seed, num_out: int) -> List[Any]:
    import ray_tpu
    num_out = max(1, num_out)
    remote_split = ray_tpu.remote(num_cpus=1)(_split_block_random) \
        .options(num_returns=num_out)
    parts: List[List[Any]] = []
    for i, ref in enumerate(refs):
        s = None if seed is None else seed + i
        res = remote_split.remote(ref, num_out, s)
        parts.append(res if isinstance(res, list) else [res])
    remote_merge = ray_tpu.remote(num_cpus=1)(_merge_shuffled)
    out = []
    for j in range(num_out):
        s = None if seed is None else seed * 1000 + j
        out.append(remote_merge.remote(s, *[p[j] for p in parts]))
    return out


def _split_block_ranges(block, bounds, key, descending):
    """Partition a block's rows into len(bounds)+1 range buckets."""
    from ray_tpu.data.block import _key_of
    acc = BlockAccessor.for_block(block)
    buckets: List[List[Any]] = [[] for _ in range(len(bounds) + 1)]
    for row in acc.iter_rows():
        k = _key_of(row, key) if key is not None else row
        import bisect
        idx = bisect.bisect_right(bounds, k)
        buckets[idx].append(row)
    out = [build_block_like(block, b) for b in buckets]
    return out if len(out) > 1 else out[0]


def _merge_sorted(key, descending, *parts):
    block = _concat_blocks(list(parts))
    return BlockAccessor.for_block(block).sort_block(
        key if key is not None else (lambda r: r), descending)


def _sort_refs(refs: List[Any], key, descending) -> List[Any]:
    import ray_tpu
    n_out = len(refs)
    if n_out == 0:
        return refs
    # sample boundaries
    remote_sample = ray_tpu.remote(num_cpus=1)(_sample_block)
    samples = [s for chunk in ray_tpu.get(
        [remote_sample.remote(r, 20, key) for r in refs]) for s in chunk]
    samples.sort()
    if not samples:
        return refs
    bounds = [samples[int(len(samples) * i / n_out)]
              for i in range(1, n_out)]
    remote_split = ray_tpu.remote(num_cpus=1)(_split_block_ranges) \
        .options(num_returns=n_out)
    parts = []
    for ref in refs:
        res = remote_split.remote(ref, bounds, key, descending)
        parts.append(res if isinstance(res, list) else [res])
    remote_merge = ray_tpu.remote(num_cpus=1)(_merge_sorted)
    order = range(n_out - 1, -1, -1) if descending else range(n_out)
    return [remote_merge.remote(key, descending, *[p[j] for p in parts])
            for j in order]


def _sample_block(block, n, key):
    return BlockAccessor.for_block(block).sample(n, key)


def _rechunk_stream(rows_per_block: int, *blocks):
    """Generator body of Dataset.iter_repartitioned: cut the input
    blocks' row stream into ``rows_per_block``-row output blocks,
    yielding each the moment it fills (streamed to the consumer as its
    own object — never materializing the whole repartition)."""
    pending: List[Any] = []
    template = None
    for block in blocks:
        template = block
        for row in BlockAccessor.for_block(block).iter_rows():
            pending.append(row)
            if len(pending) >= rows_per_block:
                yield build_block_like(block, pending)
                pending = []
    if pending and template is not None:
        yield build_block_like(template, pending)


def _repartition_refs(refs: List[Any], num_blocks: int) -> List[Any]:
    import ray_tpu
    remote_count = ray_tpu.remote(num_cpus=1)(_count_block)
    counts = ray_tpu.get([remote_count.remote(r) for r in refs])
    total = sum(counts)
    per = [total // num_blocks + (1 if i < total % num_blocks else 0)
           for i in range(num_blocks)]
    # assemble output blocks from input slices
    remote_slice = ray_tpu.remote(num_cpus=1)(_slice_block)
    remote_concat = ray_tpu.remote(num_cpus=1)(_concat_parts)
    out = []
    in_idx, in_off = 0, 0
    for want in per:
        pieces = []
        need = want
        while need > 0 and in_idx < len(refs):
            avail = counts[in_idx] - in_off
            take = min(avail, need)
            if take > 0:
                if take == counts[in_idx] and in_off == 0:
                    pieces.append(refs[in_idx])
                else:
                    pieces.append(remote_slice.remote(
                        refs[in_idx], in_off, in_off + take))
                in_off += take
                need -= take
            if in_off >= counts[in_idx]:
                in_idx += 1
                in_off = 0
        if not pieces:
            out.append(ray_tpu.put([]))
        elif len(pieces) == 1:
            out.append(pieces[0])
        else:
            out.append(remote_concat.remote(*pieces))
    return out


def _concat_parts(*parts):
    return _concat_blocks(list(parts))
