"""Blocks: the unit of distributed data.

Analog of /root/reference/python/ray/data/block.py + _internal/arrow_block.py
/ pandas_block.py / simple_block.py: a block is a batch of rows in one of
three formats (pyarrow.Table, pandas.DataFrame, or a Python list), stored as
one object in the object store. BlockAccessor unifies the per-format ops the
execution plan needs (slice, take, schema, to_batch, ...).

TPU note: the "tensor batch" interchange format is a dict of numpy arrays —
what a JaxTrainer host feeds to device shards — so every accessor can
produce ``batch_format="numpy"`` without pandas/arrow in the loop.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np

Block = Any   # list | pandas.DataFrame | pyarrow.Table | dict[str, ndarray]


def _try_import_pandas():
    try:
        import pandas
        return pandas
    except ImportError:
        return None


def _try_import_pyarrow():
    try:
        import pyarrow
        return pyarrow
    except ImportError:
        return None


class BlockAccessor:
    """Format-generic view over one block."""

    def __init__(self, block: Block):
        self._block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        pd = _try_import_pandas()
        pa = _try_import_pyarrow()
        if pd is not None and isinstance(block, pd.DataFrame):
            return _PandasAccessor(block)
        if pa is not None and isinstance(block, pa.Table):
            return _ArrowAccessor(block)
        if isinstance(block, dict) and block and all(
                isinstance(v, np.ndarray) for v in block.values()):
            return _NumpyAccessor(block)
        if isinstance(block, list):
            return _SimpleAccessor(block)
        raise TypeError(f"unsupported block type {type(block)}")

    # interface
    def num_rows(self) -> int:
        raise NotImplementedError

    def iter_rows(self) -> Iterator[Any]:
        raise NotImplementedError

    def slice(self, start: int, end: int) -> Block:
        raise NotImplementedError

    def to_pandas(self):
        raise NotImplementedError

    def to_numpy(self) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def to_arrow(self):
        raise NotImplementedError

    def to_list(self) -> List[Any]:
        return list(self.iter_rows())

    def to_batch(self, batch_format: str) -> Any:
        if batch_format in ("default", "native"):
            return self._block
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format == "numpy":
            return self.to_numpy()
        if batch_format == "pyarrow":
            return self.to_arrow()
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def schema(self) -> Any:
        raise NotImplementedError

    def size_bytes(self) -> int:
        raise NotImplementedError

    def sample(self, n: int, key: Optional[Any] = None) -> List[Any]:
        rows = self.to_list()
        step = max(1, len(rows) // max(n, 1))
        picked = rows[::step][:n]
        if key is not None:
            picked = [_key_of(r, key) for r in picked]
        return picked

    def sort_block(self, key: Any, descending: bool = False) -> Block:
        rows = sorted(self.to_list(), key=lambda r: _key_of(r, key),
                      reverse=descending)
        return build_block_like(self._block, rows)

    @staticmethod
    def batch_to_block(batch: Any) -> Block:
        """Normalize a user-returned batch into a block."""
        pd = _try_import_pandas()
        pa = _try_import_pyarrow()
        if pd is not None and isinstance(batch, pd.DataFrame):
            return batch
        if pa is not None and isinstance(batch, pa.Table):
            return batch
        if isinstance(batch, dict):
            return {k: np.asarray(v) for k, v in batch.items()}
        if isinstance(batch, np.ndarray):
            return {"data": batch}
        if isinstance(batch, list):
            return batch
        raise TypeError(f"map_batches returned unsupported type "
                        f"{type(batch)}")


def _key_of(row: Any, key: Any) -> Any:
    if callable(key):
        return key(row)
    if isinstance(row, dict):
        return row[key]
    return getattr(row, key, row)


def build_block_like(template: Block, rows: List[Any]) -> Block:
    """Rebuild a block of ``template``'s format from python rows."""
    pd = _try_import_pandas()
    pa = _try_import_pyarrow()
    if pd is not None and isinstance(template, pd.DataFrame):
        return pd.DataFrame(rows)
    if pa is not None and isinstance(template, pa.Table):
        return pa.Table.from_pylist(rows)
    if isinstance(template, dict):
        if not rows:
            return {k: np.empty((0,) + v.shape[1:], v.dtype)
                    for k, v in template.items()}
        if isinstance(rows[0], dict):
            # the map fn may have CHANGED the row schema: build from the
            # output rows' keys, not the input template's
            return {k: np.asarray([r[k] for r in rows])
                    for k in rows[0].keys()}
    return list(rows)


class _SimpleAccessor(BlockAccessor):
    def num_rows(self):
        return len(self._block)

    def iter_rows(self):
        return iter(self._block)

    def slice(self, start, end):
        return self._block[start:end]

    def to_pandas(self):
        pd = _try_import_pandas()
        rows = self._block
        if rows and isinstance(rows[0], dict):
            return pd.DataFrame(rows)
        return pd.DataFrame({"value": rows})

    def to_numpy(self):
        rows = self._block
        if rows and isinstance(rows[0], dict):
            return {k: np.asarray([r[k] for r in rows])
                    for k in rows[0].keys()}
        return {"value": np.asarray(rows)}

    def to_arrow(self):
        pa = _try_import_pyarrow()
        rows = self._block
        if rows and isinstance(rows[0], dict):
            return pa.Table.from_pylist(rows)
        return pa.table({"value": rows})

    def schema(self):
        if not self._block:
            return None
        first = self._block[0]
        if isinstance(first, dict):
            return {k: type(v).__name__ for k, v in first.items()}
        return type(first).__name__

    def size_bytes(self):
        import sys
        if not self._block:
            return 0
        return sys.getsizeof(self._block[0]) * len(self._block)


class _NumpyAccessor(BlockAccessor):
    def num_rows(self):
        return len(next(iter(self._block.values())))

    def iter_rows(self):
        keys = list(self._block.keys())
        for i in range(self.num_rows()):
            yield {k: self._block[k][i] for k in keys}

    def slice(self, start, end):
        return {k: v[start:end] for k, v in self._block.items()}

    def to_pandas(self):
        pd = _try_import_pandas()
        cols = {}
        for k, v in self._block.items():
            cols[k] = list(v) if v.ndim > 1 else v
        return pd.DataFrame(cols)

    def to_numpy(self):
        return self._block

    def to_arrow(self):
        pa = _try_import_pyarrow()
        return pa.table({k: list(v) if v.ndim > 1 else v
                         for k, v in self._block.items()})

    def schema(self):
        return {k: str(v.dtype) for k, v in self._block.items()}

    def size_bytes(self):
        return int(sum(v.nbytes for v in self._block.values()))


class _PandasAccessor(BlockAccessor):
    def num_rows(self):
        return len(self._block)

    def iter_rows(self):
        for _, row in self._block.iterrows():
            yield row.to_dict()

    def slice(self, start, end):
        return self._block.iloc[start:end].reset_index(drop=True)

    def to_pandas(self):
        return self._block

    def to_numpy(self):
        return {c: self._block[c].to_numpy() for c in self._block.columns}

    def to_arrow(self):
        pa = _try_import_pyarrow()
        return pa.Table.from_pandas(self._block, preserve_index=False)

    def schema(self):
        return {c: str(t) for c, t in self._block.dtypes.items()}

    def size_bytes(self):
        return int(self._block.memory_usage(deep=True).sum())


class _ArrowAccessor(BlockAccessor):
    def num_rows(self):
        return self._block.num_rows

    def iter_rows(self):
        for batch in self._block.to_pylist():
            yield batch

    def slice(self, start, end):
        return self._block.slice(start, end - start)

    def to_pandas(self):
        return self._block.to_pandas()

    def to_numpy(self):
        return {name: self._block[name].to_numpy(zero_copy_only=False)
                for name in self._block.column_names}

    def to_arrow(self):
        return self._block

    def schema(self):
        return self._block.schema

    def size_bytes(self):
        return self._block.nbytes
