"""Per-stage timing stats (cf. reference data/_internal/stats.py)."""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List

_lock = threading.Lock()
_timings: Dict[str, List[float]] = {}


@contextlib.contextmanager
def timed(stage: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            _timings.setdefault(stage, []).append(dt)


def summary() -> str:
    with _lock:
        lines = []
        for stage, times in _timings.items():
            lines.append(
                f"stage {stage}: n={len(times)} total={sum(times):.3f}s "
                f"mean={sum(times) / len(times):.3f}s max={max(times):.3f}s")
    return "\n".join(lines) or "(no stages executed)"


def reset() -> None:
    with _lock:
        _timings.clear()
