"""Per-dataset, per-stage execution statistics.

Analog of /root/reference/python/ray/data/_internal/stats.py:161
(``DatasetStats``): every executed stage records its driver-side wall
span plus per-block metadata measured inside the workers — remote wall
time, CPU time, output rows, and output bytes — and ``ds.stats()``
prints the per-stage report users tune against.

Block metadata travels as a second return value of each block task
(``num_returns=2``), so collecting it adds no extra tasks; the tiny
meta objects are resolved lazily the first time ``summary()`` runs.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional


def block_meta(block, wall_start: float, cpu_start: float) -> Dict:
    """Worker-side: measure one produced block (called at task end)."""
    from ray_tpu.data.block import BlockAccessor
    acc = BlockAccessor.for_block(block)
    try:
        rows = acc.num_rows()
    except Exception:
        rows = 0
    try:
        nbytes = acc.size_bytes()
    except Exception:
        nbytes = 0
    return {
        "wall_s": time.perf_counter() - wall_start,
        "cpu_s": time.process_time() - cpu_start,
        "rows": rows,
        "bytes": nbytes,
    }


class _StageStats:
    def __init__(self, name: str):
        self.name = name
        self.wall_s = 0.0            # driver-side stage span (submission)
        self.meta_refs: List[Any] = []   # one per output block
        self.block_count = 0
        self._resolved: Optional[List[Dict]] = None

    def _metas(self) -> List[Dict]:
        if self._resolved is None:
            import ray_tpu
            out = []
            for ref in self.meta_refs:
                # per-ref: one lost block's meta (node death mid-chaos)
                # must not discard every other block's measurements
                try:
                    m = ray_tpu.get(ref, timeout=30)
                except Exception:
                    continue
                if m:
                    out.append(m)
            self._resolved = out
        return self._resolved

    def report(self) -> str:
        metas = self._metas()
        n = self.block_count or len(metas)
        head = (f"Stage {self.name}: {n} blocks, "
                f"{self.wall_s:.3f}s driver wall time")
        if not metas:
            return head
        lines = [head]

        def agg(key, label):
            vals = [m.get(key, 0) for m in metas]
            return (f"  * {label}: min={min(vals):.4g} max={max(vals):.4g} "
                    f"mean={sum(vals) / len(vals):.4g} "
                    f"total={sum(vals):.4g}")
        lines.append(agg("wall_s", "remote wall time (s)"))
        lines.append(agg("cpu_s", "remote cpu time (s)"))
        lines.append(agg("rows", "output rows"))
        lines.append(agg("bytes", "output size (bytes)"))
        return "\n".join(lines)


class DatasetStats:
    """Stats ledger of one ExecutionPlan; stages append as they run."""

    def __init__(self, parent: Optional["DatasetStats"] = None):
        self._lock = threading.Lock()
        self.stages: List[_StageStats] = []
        self.parent = parent

    def record_stage(self, name: str, wall_s: float,
                     meta_refs: Optional[List[Any]] = None,
                     block_count: int = 0) -> None:
        st = _StageStats(name)
        st.wall_s = wall_s
        st.meta_refs = list(meta_refs or [])
        st.block_count = block_count or len(st.meta_refs)
        with self._lock:
            self.stages.append(st)

    def summary(self) -> str:
        parts: List[str] = []
        if self.parent is not None:
            parent_text = self.parent.summary()
            if parent_text != "(no stages executed)":
                parts.append(parent_text)
        with self._lock:
            stages = list(self.stages)
        parts.extend(st.report() for st in stages)
        return "\n".join(parts) or "(no stages executed)"

    # datasets (and thus their plans/stats) are shipped to trainer actors
    def __getstate__(self):
        state = dict(self.__dict__)
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()
