"""Path partitioning: read hive-layout data lakes with partition pruning.

Analog of /root/reference/python/ray/data/datasource/partitioning.py
(Partitioning, PathPartitionParser, PathPartitionFilter): file paths
under a base directory encode column values either hive-style
(``base/year=2024/month=06/f.parquet``) or positionally
(``base/2024/06/f.parquet`` with ``field_names=["year", "month"]``).
Readers use the parsed values twice:

  - PRUNING: a ``partition_filter`` drops files before any byte is read
    (the reason hive layouts exist — predicate pushdown on the path).
  - ENRICHMENT: surviving files' partition values are appended as
    columns to the blocks they produce (hive readers' usual contract).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass
class Partitioning:
    """Declares how paths encode partition fields.

    ``style``: "hive" (``key=value`` directories, self-describing) or
    "dir" (bare value directories, named by ``field_names`` in order).
    ``base_dir``: the prefix below which partition directories start;
    path components above it are ignored.
    """

    style: str = "hive"
    base_dir: str = ""
    field_names: Optional[List[str]] = field(default=None)

    def __post_init__(self):
        if self.style not in ("hive", "dir"):
            raise ValueError(f"unknown partitioning style {self.style!r}")
        if self.style == "dir" and not self.field_names:
            raise ValueError('style="dir" requires field_names')


class PathPartitionParser:
    """Extract {field: value} from one file path."""

    def __init__(self, partitioning: Partitioning):
        self._p = partitioning

    def __call__(self, path: str) -> Dict[str, str]:
        rel = path
        base = self._p.base_dir.rstrip("/")
        if base:
            # tolerate absolute/relative mismatches: split on the base
            # dir's last occurrence, anchored at path-component
            # boundaries so base "data" can't match inside "/mydata/"
            marker = base + "/"
            idx = rel.rfind(marker)
            while idx > 0 and rel[idx - 1] != "/":
                idx = rel.rfind(marker, 0, idx)
            if idx >= 0:
                rel = rel[idx + len(base):]
        parts = [c for c in rel.split("/") if c][:-1]   # drop filename
        out: Dict[str, str] = {}
        if self._p.style == "hive":
            for comp in parts:
                if "=" in comp:
                    k, _, v = comp.partition("=")
                    out[k] = v
            return out
        names = self._p.field_names or []
        for name, comp in zip(names, parts):
            out[name] = comp
        return out


class PathPartitionFilter:
    """Filter callable over file paths, built from a partition-value
    predicate: ``filter_fn({field: value}) -> keep?``."""

    def __init__(self, partitioning: Partitioning,
                 filter_fn: Callable[[Dict[str, str]], bool]):
        self.parser = PathPartitionParser(partitioning)
        self._fn = filter_fn

    @classmethod
    def of(cls, filter_fn: Callable[[Dict[str, str]], bool], *,
           style: str = "hive", base_dir: str = "",
           field_names: Optional[List[str]] = None
           ) -> "PathPartitionFilter":
        return cls(Partitioning(style, base_dir, field_names), filter_fn)

    def __call__(self, path: str) -> bool:
        return bool(self._fn(self.parser(path)))


def apply_partitioning(files: List[str],
                       partitioning: Optional[Partitioning],
                       partition_filter: Optional[PathPartitionFilter]):
    """(surviving files, per-file partition dicts or None).

    Pruning happens HERE, on paths — excluded files are never opened."""
    values: Optional[List[Dict[str, str]]] = None
    if partition_filter is not None:
        files = [f for f in files if partition_filter(f)]
        if not files:
            raise FileNotFoundError(
                "partition_filter excluded every input file")
        if partitioning is None:
            # enrichment uses the filter's own parser when no explicit
            # partitioning was passed
            values = [partition_filter.parser(f) for f in files]
    if partitioning is not None:
        parser = PathPartitionParser(partitioning)
        values = [parser(f) for f in files]
    return files, values


def add_partition_columns(block, values: Dict[str, str]):
    """Append constant partition columns to one block (arrow table,
    pandas frame, or dict-of-arrays)."""
    if not values:
        return block
    try:
        import pyarrow as pa
        if isinstance(block, pa.Table):
            n = block.num_rows
            for k, v in values.items():
                if k in block.column_names:
                    continue
                block = block.append_column(k, pa.array([v] * n))
            return block
    except ImportError:
        pass
    try:
        import pandas as pd
        if isinstance(block, pd.DataFrame):
            for k, v in values.items():
                if k not in block.columns:
                    block[k] = v
            return block
    except ImportError:
        pass
    if isinstance(block, dict):
        import numpy as np
        n = len(next(iter(block.values()))) if block else 0
        for k, v in values.items():
            block.setdefault(k, np.array([v] * n))
    return block
