"""RandomAccessDataset: O(1)-ish distributed point lookups by sort key.

Analog of /root/reference/python/ray/data/random_access_dataset.py: the
dataset is sorted by a key column and repartitioned; a pool of actors each
pins one contiguous span of the sorted data and serves binary-search
lookups.  Blocks travel to the actors as object refs (never through the
driver), and span boundaries come from tiny per-block tasks — the driver
holds only the boundary keys, so dataset size is bounded by the actor
pool's memory, not the driver's.
"""

from __future__ import annotations

import bisect
from typing import Any, List, Optional


class _BlockHolder:
    """Actor: pins one sorted block span, answers key lookups."""

    def __init__(self, block: Any, key: str):
        import numpy as np

        from ray_tpu.data.block import BlockAccessor
        self._rows = list(BlockAccessor.for_block(block).iter_rows())
        self._keys = np.asarray([r[key] for r in self._rows])

    def get(self, key_value) -> Optional[Any]:
        i = bisect.bisect_left(self._keys, key_value)  # type: ignore[arg-type]
        if i < len(self._rows) and self._keys[i] == key_value:
            return self._rows[i]
        return None

    def multiget(self, key_values: List[Any]) -> List[Optional[Any]]:
        return [self.get(k) for k in key_values]

    def num_rows(self) -> int:
        return len(self._rows)


def _span_info(block, key: str):
    """(num_rows, last_key) — runs as a task next to the block."""
    from ray_tpu.data.block import BlockAccessor
    acc = BlockAccessor.for_block(block)
    n = acc.num_rows()
    if n == 0:
        return 0, None
    last = None
    for row in acc.iter_rows():
        last = row[key]
    return n, last


class RandomAccessDataset:
    """Built via ``Dataset.to_random_access_dataset(key, num_workers)``."""

    def __init__(self, ds, key: str, num_workers: int = 2):
        import ray_tpu

        sorted_ds = ds.sort(key).repartition(num_workers).materialize()
        refs = sorted_ds.get_internal_block_refs()
        span_task = ray_tpu.remote(num_cpus=0.5)(_span_info)
        infos = ray_tpu.get([span_task.remote(r, key) for r in refs],
                            timeout=120)
        self._key = key
        # span i owns keys <= bounds[i] (last span unbounded)
        self._bounds: List[Any] = []
        holder_cls = ray_tpu.remote(num_cpus=0.5)(_BlockHolder)
        self._actors = []
        spans = [(r, last) for r, (n, last) in zip(refs, infos) if n > 0]
        for i, (ref, last) in enumerate(spans):
            if i < len(spans) - 1:
                self._bounds.append(last)
            # the ref resolves to the block inside the actor's __init__ —
            # the block never passes through the driver
            self._actors.append(holder_cls.remote(ref, key))
        if not self._actors:
            raise ValueError("empty dataset")

    def _route(self, key_value) -> int:
        return bisect.bisect_left(self._bounds, key_value)

    def get_async(self, key_value):
        """ObjectRef of the row with key == key_value (None if absent)."""
        return self._actors[self._route(key_value)].get.remote(key_value)

    def multiget(self, key_values: List[Any],
                 timeout: Optional[float] = 60.0) -> List[Optional[Any]]:
        import ray_tpu
        by_actor: dict = {}
        for j, kv in enumerate(key_values):
            by_actor.setdefault(self._route(kv), []).append((j, kv))
        out: List[Optional[Any]] = [None] * len(key_values)
        pending = []
        for idx, items in by_actor.items():
            ref = self._actors[idx].multiget.remote([kv for _, kv in items])
            pending.append((items, ref))
        for items, ref in pending:
            values = ray_tpu.get(ref, timeout=timeout)
            for (j, _), v in zip(items, values):
                out[j] = v
        return out

    def stats(self) -> str:
        import ray_tpu
        counts = ray_tpu.get([a.num_rows.remote() for a in self._actors])
        return (f"RandomAccessDataset: {len(self._actors)} workers, "
                f"{sum(counts)} rows, per-worker {counts}")
