"""Datasources: read tasks and file writers.

Analog of /root/reference/python/ray/data/read_api.py (read_parquet :429)
and data/datasource/*: a read produces ReadTasks — serializable callables,
one per output block — that the execution plan submits as remote tasks, so
IO parallelizes across the cluster and blocks land in the object store on
the node that read them.
"""

from __future__ import annotations

import glob as _glob
import io
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu._private import storage as _storage


def _is_remote(path: str) -> bool:
    """URI handled by the storage seam rather than the local filesystem.

    ``file://`` strips to a plain path (read tasks run on any node; a
    file:// URI means a shared/local filesystem, same as the reference's
    default pyarrow LocalFileSystem). ``mock://`` is per-process memory —
    fine for driver-side tests, not shared with remote read workers.
    """
    return _storage.is_uri(path) and _storage.parse_uri(path)[0] != "file"


def _localize(path: str) -> str:
    if _storage.is_uri(path) and _storage.parse_uri(path)[0] == "file":
        return _storage.parse_uri(path)[1]
    return path


def _open(path: str, mode: str = "rb"):
    """File-like opener for both local paths and storage URIs (reference
    read_api.py threads a pyarrow ``filesystem`` through every reader;
    here the seam yields whole-object readers)."""
    if _is_remote(path):
        buf = io.BytesIO(_storage.read_bytes(path))
        return io.TextIOWrapper(buf) if "b" not in mode else buf
    return open(path, mode)


def _out_target(path: str, filename: str):
    """-> (local_path_or_None, uri_or_None) for one output file under
    ``path``: local destinations stream straight to disk, remote URIs
    buffer and go through the seam."""
    if _is_remote(path):
        return None, _storage.join_uri(path, filename)
    path = _localize(path)
    os.makedirs(path, exist_ok=True)
    return os.path.join(path, filename), None


class ReadTask:
    """One unit of input IO → one block."""

    def __init__(self, fn: Callable[[], Any],
                 num_rows: Optional[int] = None,
                 input_files: Optional[List[str]] = None):
        self._fn = fn
        self.num_rows = num_rows
        self.input_files = input_files or []

    def __call__(self):
        return self._fn()


def _expand_paths(paths, suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if _is_remote(p):
            if _storage.exists(p):
                files.append(p)
                continue
            rels = _storage.list_prefix(p)
            files.extend(_storage.join_uri(p, r) for r in sorted(rels)
                         if suffix is None or r.endswith(suffix))
            continue
        p = _localize(p)
        out: List[str] = []
        if os.path.isdir(p):
            pat = os.path.join(p, "**", f"*{suffix}" if suffix else "*")
            out.extend(sorted(_glob.glob(pat, recursive=True)))
        elif any(c in p for c in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
        files.extend(f for f in out if os.path.isfile(f))
    if not files:
        raise FileNotFoundError(f"no input files for {paths!r}")
    return files


# -- readers (each returns a list of ReadTasks) -----------------------------

def range_tasks(n: int, parallelism: int) -> List[ReadTask]:
    parallelism = max(1, min(parallelism, n or 1))
    step = (n + parallelism - 1) // parallelism
    tasks = []
    for start in range(0, n, step):
        end = min(start + step, n)
        tasks.append(ReadTask(
            lambda s=start, e=end: {"id": np.arange(s, e)},
            num_rows=end - start))
    return tasks


def items_tasks(items: List[Any], parallelism: int) -> List[ReadTask]:
    parallelism = max(1, min(parallelism, len(items) or 1))
    step = (len(items) + parallelism - 1) // parallelism
    tasks = []
    for start in range(0, len(items), step):
        chunk = items[start:start + step]
        tasks.append(ReadTask(lambda c=chunk: list(c), num_rows=len(chunk)))
    return tasks


def parquet_tasks(paths, columns: Optional[List[str]] = None,
                  partitioning=None,
                  partition_filter=None) -> List[ReadTask]:
    from ray_tpu.data.partitioning import (add_partition_columns,
                                           apply_partitioning)
    files = _expand_paths(paths, ".parquet")
    files, values = apply_partitioning(files, partitioning,
                                       partition_filter)

    def read_one(path: str, vals):
        import pyarrow.parquet as pq
        # read THIS file only, not pq.read_table: read_table routes
        # through the dataset API, whose hive inference re-derives
        # partition columns from the path with GUESSED dtypes
        # (year=2024 -> int32) — shadowing the path parser's string
        # values that add_partition_columns appends below (it skips
        # columns that already exist).  ParquetFile reads the file as a
        # file; partition enrichment stays the parser's job.
        src = _open(path) if _is_remote(path) else path
        table = pq.ParquetFile(src).read(columns=columns)
        return add_partition_columns(table, vals) if vals else table

    return [ReadTask(lambda p=f, v=(values[i] if values else None):
                     read_one(p, v), input_files=[f])
            for i, f in enumerate(files)]


def csv_tasks(paths, partitioning=None, partition_filter=None,
              **pandas_kwargs) -> List[ReadTask]:
    from ray_tpu.data.partitioning import (add_partition_columns,
                                           apply_partitioning)
    files = _expand_paths(paths, ".csv")
    files, part_values = apply_partitioning(files, partitioning,
                                            partition_filter)

    def read_one(path: str, vals):
        import pandas as pd
        frame = pd.read_csv(
            _open(path, "r") if _is_remote(path) else path,
            **pandas_kwargs)
        return add_partition_columns(frame, vals) if vals else frame

    return [ReadTask(lambda p=f, v=(part_values[i] if part_values
                                    else None): read_one(p, v),
                     input_files=[f])
            for i, f in enumerate(files)]


def json_tasks(paths, lines: bool = True) -> List[ReadTask]:
    files = _expand_paths(paths, ".json")

    def read_one(path: str):
        import json
        rows = []
        with _open(path, "r") as fh:
            if lines:
                for line in fh:
                    line = line.strip()
                    if line:
                        rows.append(json.loads(line))
            else:
                data = json.load(fh)
                rows = data if isinstance(data, list) else [data]
        return rows

    return [ReadTask(lambda p=f: read_one(p), input_files=[f])
            for f in files]


def numpy_tasks(paths) -> List[ReadTask]:
    files = _expand_paths(paths, ".npy")
    return [ReadTask(lambda p=f: {"data": np.load(_open(p))},
                     input_files=[f]) for f in files]


def text_tasks(paths) -> List[ReadTask]:
    files = _expand_paths(paths)

    def read_one(path: str):
        with _open(path, "r") as fh:
            return [line.rstrip("\n") for line in fh]

    return [ReadTask(lambda p=f: read_one(p), input_files=[f])
            for f in files]


def binary_tasks(paths) -> List[ReadTask]:
    files = _expand_paths(paths)

    def read_one(path: str):
        with _open(path, "rb") as fh:
            return [{"path": path, "bytes": fh.read()}]

    return [ReadTask(lambda p=f: read_one(p), input_files=[f])
            for f in files]


# -- writers (run as remote tasks, one file per block) ----------------------

def image_tasks(paths, *, size=None, mode: Optional[str] = None
                ) -> List[ReadTask]:
    """Image folder reader (cf. reference data/datasource/
    image_datasource.py): one block of {"image": [N,H,W,C], "path": [N]}
    per batch of files; PIL decodes, optional resize + mode conversion."""
    files = [f for f in _expand_paths(paths)
             if f.lower().endswith((".png", ".jpg", ".jpeg", ".bmp",
                                    ".gif", ".webp"))]
    if not files:
        raise ValueError(f"no image files under {paths!r}")
    batch = max(1, len(files) // 8)
    tasks = []
    for start in range(0, len(files), batch):
        chunk = files[start:start + batch]

        def read_chunk(chunk=chunk):
            from PIL import Image
            imgs, names = [], []
            for f in chunk:
                im = Image.open(_open(f))
                if mode:
                    im = im.convert(mode)
                if size:
                    im = im.resize(size)
                imgs.append(np.asarray(im))
                names.append(f)
            shapes = {a.shape for a in imgs}
            if len(shapes) > 1:
                raise ValueError(
                    f"images have differing shapes {sorted(shapes)}; "
                    "pass size=(W, H) and/or mode='RGB' to read_images "
                    "to homogenize them")
            return {"image": np.stack(imgs), "path": np.asarray(names)}

        tasks.append(ReadTask(read_chunk, num_rows=len(chunk),
                              input_files=chunk))
    return tasks


def write_parquet_block(block, path: str, idx: int) -> str:
    from ray_tpu.data.block import BlockAccessor
    import pyarrow.parquet as pq
    table = BlockAccessor.for_block(block).to_arrow()
    local, uri = _out_target(path, f"part-{idx:05d}.parquet")
    if local is not None:
        pq.write_table(table, local)
        return local
    buf = io.BytesIO()
    pq.write_table(table, buf)
    _storage.write_bytes(uri, buf.getvalue())
    return uri


def write_csv_block(block, path: str, idx: int) -> str:
    from ray_tpu.data.block import BlockAccessor
    df = BlockAccessor.for_block(block).to_pandas()
    local, uri = _out_target(path, f"part-{idx:05d}.csv")
    if local is not None:
        df.to_csv(local, index=False)
        return local
    _storage.write_bytes(uri, df.to_csv(index=False).encode())
    return uri


def write_json_block(block, path: str, idx: int) -> str:
    import json

    from ray_tpu.data.block import BlockAccessor
    acc = BlockAccessor.for_block(block)
    local, uri = _out_target(path, f"part-{idx:05d}.json")
    if local is not None:
        with open(local, "w") as fh:
            for row in acc.iter_rows():
                fh.write(json.dumps(_jsonable(row)) + "\n")
        return local
    lines = "".join(json.dumps(_jsonable(row)) + "\n"
                    for row in acc.iter_rows())
    _storage.write_bytes(uri, lines.encode())
    return uri


def write_numpy_block(block, path: str, idx: int, column: str) -> str:
    from ray_tpu.data.block import BlockAccessor
    arrs = BlockAccessor.for_block(block).to_numpy()
    local, uri = _out_target(path, f"part-{idx:05d}.npy")
    if local is not None:
        np.save(local, arrs[column])
        return local
    buf = io.BytesIO()
    np.save(buf, arrs[column])
    _storage.write_bytes(uri, buf.getvalue())
    return uri


def _jsonable(row: Any) -> Any:
    if isinstance(row, dict):
        return {k: _jsonable(v) for k, v in row.items()}
    if isinstance(row, np.ndarray):
        return row.tolist()
    if isinstance(row, (np.integer,)):
        return int(row)
    if isinstance(row, (np.floating,)):
        return float(row)
    return row
