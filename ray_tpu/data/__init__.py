"""ray_tpu.data: distributed datasets over object-store blocks.

Analog of /root/reference/python/ray/data (SURVEY.md §2.4): read_* → lazy
plan → map/shuffle/sort/split → iter_batches/to_* consumption; blocks are
objects, transforms are tasks/actor pools, splits feed per-host trainer
shards.
"""

from typing import Any, List, Optional

from ray_tpu.data.block import Block, BlockAccessor  # noqa: F401
from ray_tpu.data.dataset import (ActorPoolStrategy, Dataset,  # noqa: F401
                                  ExecutionPlan, GroupedData,
                                  TaskPoolStrategy)
from ray_tpu.data.dataset_pipeline import DatasetPipeline  # noqa: F401
from ray_tpu.data.random_access_dataset import \
    RandomAccessDataset  # noqa: F401
from ray_tpu.data.preprocessors import (BatchMapper, Chain,  # noqa: F401
                                        Concatenator, LabelEncoder,
                                        MinMaxScaler, OneHotEncoder,
                                        Preprocessor, SimpleImputer,
                                        StandardScaler)
from ray_tpu.data import datasource as _dsrc
from ray_tpu.data.partitioning import (Partitioning,  # noqa: F401
                                       PathPartitionFilter)


def _from_tasks(tasks) -> Dataset:
    return Dataset(ExecutionPlan(read_tasks=tasks))


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    return _from_tasks(_dsrc.range_tasks(n, parallelism))


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    return _from_tasks(_dsrc.items_tasks(list(items), parallelism))


def from_pandas(dfs) -> Dataset:
    import ray_tpu
    if not isinstance(dfs, list):
        dfs = [dfs]
    return Dataset(ExecutionPlan(
        block_refs=[ray_tpu.put(df) for df in dfs]))


def from_numpy(arrays) -> Dataset:
    import numpy as np

    import ray_tpu
    if not isinstance(arrays, list):
        arrays = [arrays]
    return Dataset(ExecutionPlan(block_refs=[
        ray_tpu.put({"data": np.asarray(a)}) for a in arrays]))


def from_arrow(tables) -> Dataset:
    import ray_tpu
    if not isinstance(tables, list):
        tables = [tables]
    return Dataset(ExecutionPlan(
        block_refs=[ray_tpu.put(t) for t in tables]))


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 partitioning=None, partition_filter=None) -> Dataset:
    """``partitioning``/``partition_filter``: hive-layout lakes — prune
    files by path-encoded values before reading, and append the values
    as columns (data/partitioning.py)."""
    return _from_tasks(_dsrc.parquet_tasks(
        paths, columns, partitioning=partitioning,
        partition_filter=partition_filter))


def read_csv(paths, *, partitioning=None, partition_filter=None,
             **kwargs) -> Dataset:
    return _from_tasks(_dsrc.csv_tasks(
        paths, partitioning=partitioning,
        partition_filter=partition_filter, **kwargs))


def read_tfrecords(paths, *, partitioning=None,
                   partition_filter=None) -> Dataset:
    """tf.train.Example records (data/tfrecords.py: framing + protobuf
    decoded without a tensorflow dependency)."""
    from ray_tpu.data import tfrecords as _tfr
    return _from_tasks(_tfr.tfrecord_tasks(
        paths, partitioning=partitioning,
        partition_filter=partition_filter))


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline: Optional[List[dict]] = None,
               parallelism: int = 4) -> Dataset:
    """Read a MongoDB collection (cf. reference
    python/ray/data/datasource/mongo_datasource.py).  Paginates with
    $skip/$limit into parallel read tasks — simpler than the
    reference's _id-range splitting, with the standard caveats: each
    task re-scans O(skip) documents server-side, and concurrent writes
    during the read can duplicate or miss documents.  Use a quiesced
    collection (or a pipeline filter pinning a snapshot) for exact
    results.  Requires pymongo (not baked into this image — the import
    error says so at call time, not deep in a worker)."""
    try:
        import pymongo  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "read_mongo requires pymongo, which is not installed in "
            "this environment") from e

    def make_task(skip: int, limit: int):
        def read_block():
            import pymongo as pm
            coll = pm.MongoClient(uri)[database][collection]
            stages = list(pipeline or [])
            stages += [{"$skip": skip}, {"$limit": limit}]
            return list(coll.aggregate(stages))
        return _dsrc.ReadTask(read_block)

    import pymongo as pm
    total = pm.MongoClient(uri)[database][collection] \
        .estimated_document_count()
    parallelism = max(1, min(parallelism, total or 1))
    per = max(1, (total + parallelism - 1) // parallelism)
    tasks = [make_task(s, per) for s in range(0, total, per)]
    if not tasks:
        return from_items([])
    return _from_tasks(tasks)


def read_json(paths, *, lines: bool = True) -> Dataset:
    return _from_tasks(_dsrc.json_tasks(paths, lines))


def read_numpy(paths) -> Dataset:
    return _from_tasks(_dsrc.numpy_tasks(paths))


def read_text(paths) -> Dataset:
    return _from_tasks(_dsrc.text_tasks(paths))


def read_binary_files(paths) -> Dataset:
    return _from_tasks(_dsrc.binary_tasks(paths))


def read_images(paths, *, size=None, mode: Optional[str] = None) -> Dataset:
    """Decode an image folder into {"image", "path"} blocks (PIL)."""
    return _from_tasks(_dsrc.image_tasks(paths, size=size, mode=mode))


def from_torch(torch_dataset, *, parallelism: int = 8) -> Dataset:
    """Read a torch map-style Dataset in parallel (cf. reference
    read_api.from_torch): the index range splits into per-block read
    tasks that call ``__getitem__`` inside workers, so the driver never
    materializes the whole dataset (the dataset object itself must be
    small enough to pickle to each task — true for the common
    lazy-loading map-style datasets)."""
    import builtins
    n = len(torch_dataset)
    parallelism = max(1, min(parallelism, n or 1))
    per = max(1, (n + parallelism - 1) // parallelism)

    def make_read(start: int, stop: int):
        def read_block():
            return [torch_dataset[i] for i in builtins.range(start, stop)]
        return _dsrc.ReadTask(read_block, num_rows=stop - start)

    tasks = [make_read(s, min(s + per, n))
             for s in builtins.range(0, n, per)]
    if not tasks:
        return from_items([])
    return _from_tasks(tasks)


def from_huggingface(hf_dataset) -> Dataset:
    """Wrap a Hugging Face datasets.Dataset (cf. reference
    read_api.from_huggingface) via its Arrow table. Datasets carrying an
    indices mapping (select/shuffle/filter results) are flattened first —
    the raw table ignores the mapping and would return the wrong rows."""
    import ray_tpu
    if getattr(hf_dataset, "_indices", None) is not None:
        hf_dataset = hf_dataset.flatten_indices()
    table = hf_dataset.data.table
    return Dataset(ExecutionPlan(block_refs=[ray_tpu.put(table)]))


__all__ = [
    "Dataset", "DatasetPipeline", "BlockAccessor", "Block",
    "TaskPoolStrategy", "ActorPoolStrategy", "GroupedData",
    "range", "from_items", "from_pandas", "from_numpy", "from_arrow",
    "read_parquet", "read_csv", "read_json", "read_numpy", "read_text",
    "read_binary_files", "read_images", "read_tfrecords", "read_mongo",
    "from_torch", "from_huggingface",
    "Partitioning", "PathPartitionFilter",
    "RandomAccessDataset", "Preprocessor", "StandardScaler", "MinMaxScaler", "LabelEncoder",
    "OneHotEncoder", "SimpleImputer", "Concatenator", "BatchMapper", "Chain",
]
