"""TFRecord datasource: read/write tf.train.Example without TensorFlow.

Analog of /root/reference/python/ray/data/datasource/tfrecords_datasource.py
— but that one calls into tensorflow/pyarrow readers; this image has no
tensorflow, so both layers are implemented directly:

  - TFRecord framing: ``u64 length | u32 masked-crc32c(length) | payload
    | u32 masked-crc32c(payload)`` per record.
  - tf.train.Example: a fixed, tiny protobuf schema
    (Example -> Features -> map<string, Feature> ->
    bytes_list|float_list|int64_list), decoded/encoded with a minimal
    wire-format codec below — the fixed shape needs varints, length-
    delimited fields, and little-endian floats, nothing more.

Rows decode to {feature_name: scalar-or-list} dicts; singleton lists
unwrap to scalars (the reference's behavior).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List

import numpy as np

# ----------------------------------------------------------------- crc32c
_CRC_TABLE = []


def _crc_table():
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78            # Castagnoli, reflected
        table = []
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------- protobuf wire helpers
def _read_varint(buf: bytes, pos: int):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(out: bytearray, value: int) -> None:
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _iter_fields(buf: bytes) -> Iterator[tuple]:
    """(field_number, wire_type, value) over one message's bytes."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:                      # varint
            val, pos = _read_varint(buf, pos)
        elif wt == 1:                    # fixed64
            val = buf[pos:pos + 8]
            pos += 8
        elif wt == 2:                    # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:                    # fixed32
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield field, wt, val


def _ld(field: int, payload: bytes) -> bytes:
    out = bytearray()
    _write_varint(out, field << 3 | 2)
    _write_varint(out, len(payload))
    return bytes(out) + payload


# ------------------------------------------------------ Example codec
def decode_example(buf: bytes) -> Dict[str, Any]:
    features = b""
    for field, _wt, val in _iter_fields(buf):      # Example
        if field == 1:
            features = val
    out: Dict[str, Any] = {}
    for field, _wt, entry in _iter_fields(features):   # Features.feature
        if field != 1:
            continue
        name = b""
        feat = b""
        for f2, _w2, v2 in _iter_fields(entry):        # map entry
            if f2 == 1:
                name = v2
            elif f2 == 2:
                feat = v2
        out[name.decode()] = _decode_feature(feat)
    return out


def _decode_feature(buf: bytes):
    for field, _wt, val in _iter_fields(buf):          # Feature oneof
        if field == 1:                                 # BytesList
            items = [v for f, _w, v in _iter_fields(val) if f == 1]
            return items[0] if len(items) == 1 else items
        if field == 2:                                 # FloatList
            floats: List[float] = []
            for f, w, v in _iter_fields(val):
                if f != 1:
                    continue
                if w == 2:                             # packed
                    floats.extend(struct.unpack(
                        f"<{len(v) // 4}f", v))
                else:                                  # unpacked fixed32
                    floats.append(struct.unpack("<f", v)[0])
            return floats[0] if len(floats) == 1 else floats
        if field == 3:                                 # Int64List
            ints: List[int] = []
            for f, w, v in _iter_fields(val):
                if f != 1:
                    continue
                if w == 2:                             # packed varints
                    pos = 0
                    while pos < len(v):
                        x, pos = _read_varint(v, pos)
                        ints.append(_signed64(x))
                else:
                    ints.append(_signed64(v))
            return ints[0] if len(ints) == 1 else ints
    return None


def _signed64(x: int) -> int:
    return x - (1 << 64) if x >= (1 << 63) else x


def encode_example(row: Dict[str, Any]) -> bytes:
    entries = b""
    for name, value in row.items():
        feat = _encode_feature(value)
        entry = _ld(1, name.encode()) + _ld(2, feat)
        entries += _ld(1, entry)
    return _ld(1, entries)                             # Example.features


def _encode_feature(value) -> bytes:
    if isinstance(value, np.ndarray):
        value = value.tolist()
    items = value if isinstance(value, (list, tuple)) else [value]
    if not items:
        return _ld(3, b"")                             # empty Int64List
    first = items[0]
    if isinstance(first, bytes):
        payload = b"".join(_ld(1, b) for b in items)
        return _ld(1, payload)                         # BytesList
    if isinstance(first, str):
        payload = b"".join(_ld(1, s.encode()) for s in items)
        return _ld(1, payload)
    if isinstance(first, (bool, int, np.integer)):
        packed = bytearray()
        for i in items:
            _write_varint(packed, int(i) & ((1 << 64) - 1))
        return _ld(3, _ld(1, bytes(packed)))           # Int64List packed
    if isinstance(first, (float, np.floating)):
        packed = struct.pack(f"<{len(items)}f",
                             *[float(f) for f in items])
        return _ld(2, _ld(1, packed))                  # FloatList packed
    raise TypeError(
        f"tf.train.Example features hold bytes/str/int/float "
        f"(lists thereof); got {type(first).__name__}")


# -------------------------------------------------------- file framing
def read_tfrecord_file(path_or_file) -> List[Dict[str, Any]]:
    close = False
    f = path_or_file
    if isinstance(path_or_file, str):
        f = open(path_or_file, "rb")
        close = True
    rows = []
    try:
        while True:
            head = f.read(12)
            if len(head) < 12:
                break
            (length,) = struct.unpack("<Q", head[:8])
            (crc,) = struct.unpack("<I", head[8:])
            if crc != _masked_crc(head[:8]):
                raise ValueError("tfrecord length crc mismatch "
                                 "(corrupt or not a TFRecord file)")
            payload = f.read(length)
            (pcrc,) = struct.unpack("<I", f.read(4))
            if pcrc != _masked_crc(payload):
                raise ValueError("tfrecord payload crc mismatch")
            rows.append(decode_example(payload))
    finally:
        if close:
            f.close()
    return rows


def _write_tfrecord_stream(f, rows) -> int:
    n = 0
    for row in rows:
        payload = encode_example(row)
        head = struct.pack("<Q", len(payload))
        f.write(head)
        f.write(struct.pack("<I", _masked_crc(head)))
        f.write(payload)
        f.write(struct.pack("<I", _masked_crc(payload)))
        n += 1
    return n


def write_tfrecord_file(path: str, rows) -> int:
    with open(path, "wb") as f:
        return _write_tfrecord_stream(f, rows)


# ----------------------------------------------------------- datasource
def tfrecord_tasks(paths, partitioning=None, partition_filter=None):
    from ray_tpu.data.datasource import ReadTask, _expand_paths, _is_remote, \
        _open
    from ray_tpu.data.partitioning import (add_partition_columns,
                                           apply_partitioning)
    # accept both .tfrecord and .tfrecords file extensions
    files = [f for f in _expand_paths(paths)
             if ".tfrecord" in f or f in (paths if isinstance(paths, list)
                                          else [paths])]
    files, values = apply_partitioning(files, partitioning,
                                       partition_filter)

    def read_one(path: str, vals):
        rows = read_tfrecord_file(
            _open(path) if _is_remote(path) else path)
        if vals:
            rows = [dict(r, **{k: v for k, v in vals.items()
                               if k not in r}) for r in rows]
        return rows

    return [ReadTask(lambda p=f, v=(values[i] if values else None):
                     read_one(p, v), input_files=[f])
            for i, f in enumerate(files)]


def write_tfrecords_block(block, path: str, idx: int) -> str:
    import io

    from ray_tpu.data.block import BlockAccessor
    from ray_tpu.data.datasource import _out_target, _storage
    local, uri = _out_target(path, f"part-{idx:05d}.tfrecords")
    rows = (_rowdict(r) for r in BlockAccessor.for_block(block).iter_rows())
    if local is not None:
        write_tfrecord_file(local, rows)
        return local
    buf = io.BytesIO()
    _write_tfrecord_stream(buf, rows)
    _storage.write_bytes(uri, buf.getvalue())
    return uri


def _rowdict(row) -> Dict[str, Any]:
    if isinstance(row, dict):
        return row
    if hasattr(row, "_asdict"):
        return row._asdict()
    if hasattr(row, "to_dict"):
        return row.to_dict()
    raise TypeError(
        f"tfrecords need dict-like rows, got {type(row).__name__}")
