from ray_tpu.scripts.scripts import main

if __name__ == "__main__":
    main()
