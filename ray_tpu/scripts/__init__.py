"""CLI: `python -m ray_tpu.scripts <command>`.

Analog of /root/reference/python/ray/scripts/scripts.py (`ray start` :529,
stop, status, memory, timeline, job ...).
"""
