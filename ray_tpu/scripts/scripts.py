"""ray_tpu CLI.

Cite: /root/reference/python/ray/scripts/scripts.py — `ray start` (:529),
`ray stop`, `ray status`, `ray memory`, `ray timeline`, plus the job CLI
(/root/reference/python/ray/dashboard/modules/job/cli.py) and the state
CLI (`ray list ...`, experimental/state/state_cli.py) folded in as
subcommands.

Usage:
  python -m ray_tpu.scripts start --head [--num-cpus N] [--dashboard] [--block]
  python -m ray_tpu.scripts start --address HOST:PORT       # join as worker node
  python -m ray_tpu.scripts stop
  python -m ray_tpu.scripts status [--address ...]
  python -m ray_tpu.scripts list tasks|actors|nodes|jobs|objects|workers|placement-groups
  python -m ray_tpu.scripts summary tasks|actors|objects|metrics|stacks
  python -m ray_tpu.scripts events [--type T] [--node N] [--dossier ID]
  python -m ray_tpu.scripts memory
  python -m ray_tpu.scripts timeline [-o trace.json]
  python -m ray_tpu.scripts job submit|status|logs|stop|list ...
  python -m ray_tpu.scripts debug
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from typing import Optional


def _resolve_address(args) -> str:
    addr = getattr(args, "address", None) or \
        os.environ.get("RAY_TPU_ADDRESS")
    if addr:
        return addr
    from ray_tpu.job_submission.job_manager import latest_session_address
    return latest_session_address()


def _connect(args) -> None:
    import ray_tpu
    if not ray_tpu.is_initialized():
        ray_tpu.init(address=_resolve_address(args))


# ------------------------------------------------------------------ start
def cmd_start(args) -> None:
    from ray_tpu.runtime.node import NodeProcesses, new_session_dir
    import atexit

    session_dir = new_session_dir()
    node = NodeProcesses(session_dir)
    # the daemons must outlive this CLI process unless --block
    if not args.block:
        atexit.unregister(node.stop)

    resources = json.loads(args.resources) if args.resources else {}
    if args.num_cpus is not None:
        resources["CPU"] = float(args.num_cpus)
    if args.num_tpus is not None:
        resources["TPU"] = float(args.num_tpus)

    if args.head:
        gcs_addr = node.start_gcs(port=args.port)
        print(f"GCS listening at {gcs_addr[0]}:{gcs_addr[1]}")
    else:
        if not args.address:
            sys.exit("--address required to join an existing cluster "
                     "(or pass --head)")
        host, port = args.address.rsplit(":", 1)
        gcs_addr = (host, int(port))
    node.start_raylet(gcs_addr, resources=resources or None,
                      object_store_memory=args.object_store_memory or None)
    print(f"node {node.node_id[:12]} started (session: {session_dir})")

    dashboard = None
    if args.head and args.dashboard:
        if args.block:
            from ray_tpu.dashboard import start_dashboard
            dashboard = start_dashboard(gcs_addr, port=args.dashboard_port)
            print(f"dashboard at http://{dashboard.host}:{dashboard.port}")
        else:
            # must outlive this CLI process -> own daemon
            from ray_tpu.runtime.node import _spawn
            proc = _spawn(
                [sys.executable, "-m", "ray_tpu.dashboard",
                 "--gcs-host", gcs_addr[0],
                 "--gcs-port", str(gcs_addr[1]),
                 "--port", str(args.dashboard_port)],
                session_dir, "dashboard")
            node.dashboard_proc = proc
            print(f"dashboard at http://127.0.0.1:{args.dashboard_port}")
    _write_pids(session_dir, node)

    if args.head:
        from ray_tpu._private.usage.usage_lib import record_usage_report
        from ray_tpu.runtime.gcs import GcsClient
        probe = GcsClient(gcs_addr)
        try:
            record_usage_report(session_dir, probe)
        finally:
            probe.close()
        print(f"connect with: ray_tpu.init(address="
              f"\"{gcs_addr[0]}:{gcs_addr[1]}\")")

    if args.block:
        print("--block: press Ctrl-C to stop this node")
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            if dashboard is not None:
                dashboard.stop()
            node.stop()


def _write_pids(session_dir: str, node) -> None:
    pids = [p.pid for p in (node.gcs_proc, node.raylet_proc,
                            getattr(node, "dashboard_proc", None))
            if p is not None]
    with open(os.path.join(session_dir, "pids.json"), "w") as f:
        json.dump(pids, f)


def _latest_session_dir() -> Optional[str]:
    """Session dir advertised by the most recent local `init`/`start`."""
    try:
        with open(os.path.join("/tmp", "ray_tpu_sessions",
                               "latest.json")) as f:
            return json.load(f)["session_dir"]
    except (OSError, ValueError, KeyError):
        return None


def cmd_stop(args) -> None:
    """Kill daemons of the latest session (plus their workers).
    ``--session-dir`` stops exactly one session — the cluster launcher's
    teardown path on hosts shared by several nodes/clusters."""
    import subprocess
    killed = 0
    base = "/tmp/ray_tpu_sessions"
    sessions = []
    one_session = getattr(args, "session_dir", None)
    if one_session:
        sessions = [one_session]
    elif args.all and os.path.isdir(base):
        sessions = [os.path.join(base, d) for d in os.listdir(base)
                    if d.startswith("session_")]
    else:
        latest = _latest_session_dir()
        if latest:
            sessions = [latest]
    all_pids = []
    for sess in sessions:
        pid_file = os.path.join(sess, "pids.json")
        try:
            with open(pid_file) as f:
                pids = json.load(f)
        except (OSError, ValueError):
            continue
        for pid in pids:
            try:
                os.kill(pid, signal.SIGTERM)
                killed += 1
                all_pids.append(pid)
            except ProcessLookupError:
                pass
        # a session's daemons/workers carry its dir on their command line
        # (match only runtime processes, not this CLI invocation itself)
        subprocess.run(["pkill", "-f", f"ray_tpu.runtime.*{sess}"],
                       check=False)
    # grace period, then SIGKILL stragglers (reference `ray stop` waits for
    # procs to exit and force-kills what remains)
    def _alive(pid: int) -> bool:
        try:
            os.kill(pid, 0)
            return True
        except ProcessLookupError:
            return False
        except PermissionError:
            return True

    deadline = time.monotonic() + 5.0
    while all_pids and time.monotonic() < deadline:
        all_pids = [p for p in all_pids if _alive(p)]
        if all_pids:
            time.sleep(0.2)
    for pid in all_pids:
        try:
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
    if not one_session:
        # workers/daemons not tracked by pid files (started via init())
        subprocess.run(
            ["pkill", "-f",
             "ray_tpu.(runtime.(gcs|raylet|worker_main)|dashboard)"],
            check=False)
    print(f"stopped {killed} tracked daemon(s)")


# -------------------------------------------------- cluster launcher verbs
# (reference scripts.py:1161 `ray up` + down/attach/exec/submit)
def cmd_up(args) -> None:
    from ray_tpu.autoscaler.cluster_launcher import create_or_update_cluster
    create_or_update_cluster(args.config, dry_run=args.dry_run,
                             no_start_workers=args.no_workers)


def cmd_down(args) -> None:
    from ray_tpu.autoscaler.cluster_launcher import teardown_cluster
    teardown_cluster(args.config)


def cmd_attach(args) -> None:
    from ray_tpu.autoscaler.cluster_launcher import attach_cluster
    attach_cluster(args.config)


def cmd_exec(args) -> None:
    import shlex
    from ray_tpu.autoscaler.cluster_launcher import exec_cluster
    # shlex.join preserves the user's quoting through the remote re-parse
    rc, _ = exec_cluster(args.config, shlex.join(args.command))
    sys.exit(rc)


def cmd_submit(args) -> None:
    from ray_tpu.autoscaler.cluster_launcher import submit_job
    rc, _ = submit_job(args.config, args.script, args.script_args)
    sys.exit(rc)


# ----------------------------------------------------------------- status
def cmd_status(args) -> None:
    _connect(args)
    import ray_tpu
    nodes = ray_tpu.nodes()
    alive = [n for n in nodes if n["alive"]]
    print(f"Nodes: {len(alive)} alive / {len(nodes)} total")
    total = ray_tpu.cluster_resources()
    avail = ray_tpu.available_resources()
    print("Resources:")
    for r in sorted(total):
        print(f"  {r}: {avail.get(r, 0):g} / {total[r]:g} available")
    for n in alive:
        print(f"  node {n['node_id'][:12]} @ "
              f"{n['address'][0]}:{n['address'][1]} {n['resources']}")
    # cluster health table off the heartbeat-piggybacked snapshots
    # (docs/observability.md node health plane)
    from ray_tpu.experimental.state.api import node_health_table
    health_lines = node_health_table(nodes)
    if health_lines:
        print("Health:")
        for line in health_lines:
            print("  " + line)


def cmd_list(args) -> None:
    _connect(args)
    from ray_tpu.experimental import state
    fn = {
        "tasks": state.list_tasks,
        "actors": state.list_actors,
        "nodes": state.list_nodes,
        "jobs": state.list_jobs,
        "objects": state.list_objects,
        "workers": state.list_workers,
        "placement-groups": state.list_placement_groups,
    }[args.resource]
    rows = fn(limit=args.limit)
    for row in rows:
        row = {k: v for k, v in row.items() if k != "events"}
        print(json.dumps(row, default=str))
    print(f"({len(rows)} {args.resource})", file=sys.stderr)


def cmd_summary(args) -> None:
    _connect(args)
    from ray_tpu.experimental import state
    if args.resource == "metrics":
        # runtime telemetry as a sorted operator table (top RPC methods
        # by p50/p95, stream stalls, pin counts) — docs/observability.md
        print(state.metrics_summary())
        return
    if args.resource == "training":
        # the goodput ledger: init/compile/productive/checkpoint/idle
        # buckets, MFU and goodput per rank (docs/observability.md
        # training performance plane)
        print(state.training_summary_text(getattr(args, "run", None)))
        return
    if args.resource == "stacks":
        _summary_stacks(args)
        return
    fn = {"tasks": state.summarize_tasks,
          "actors": state.summarize_actors,
          "objects": state.summarize_objects}[args.resource]
    print(json.dumps(fn(), indent=1, default=str))


def _summary_stacks(args) -> None:
    """`ray-tpu summary stacks [--pid P | --actor A]`: per-thread stack
    dumps + a short flame sample of live cluster processes, without
    gdb (docs/observability.md).  Default: the GCS and every raylet;
    --pid targets the worker process with that pid, --actor the worker
    hosting that actor (id prefix or name)."""
    from ray_tpu._private import rpc
    from ray_tpu._private.profiler import stacks_text, top_summary
    from ray_tpu.experimental import state
    from ray_tpu.runtime.core_worker import get_global_worker

    gcs = get_global_worker().gcs

    def show(title, reply):
        print(f"===== {title} =====")
        print(stacks_text(reply.get("threads", {})))
        folded = reply.get("folded")
        if folded:
            print("-- hot leaves (sampled) --")
            print(top_summary(folded, limit=8))
        print()

    pid = getattr(args, "pid", None)
    actor = getattr(args, "actor", None)
    if actor:
        cand = next(
            (a for a in state.list_actors()
             if a["actor_id"].startswith(actor)
             or (a.get("name") or "") == actor), None)
        if cand is None or not cand.get("address"):
            sys.exit(f"no live actor matching {actor!r}")
        conn = rpc.connect(tuple(cand["address"]), timeout=5.0)
        try:
            show(f"actor {cand['actor_id'][:12]}",
                 conn.call("dump_stacks", {}, timeout=30))
        finally:
            conn.close()
        return
    if pid:
        for w in state.list_workers():
            if w.get("pid") == int(pid) and w.get("alive"):
                node = next((n for n in state.list_nodes()
                             if n["node_id"] == w["node_id"]), None)
                if node is None:
                    sys.exit(f"worker pid {pid}'s node "
                             f"{w['node_id'][:12]} is gone")
                conn = rpc.connect(tuple(node["address"]), timeout=5.0)
                try:
                    show(f"worker pid {pid}",
                         conn.call("dump_stacks", {"pid": int(pid)},
                                   timeout=30))
                finally:
                    conn.close()
                return
        sys.exit(f"no live worker with pid {pid}")
    show("gcs", gcs.call("dump_stacks", {}, timeout=30))
    for node in state.list_nodes():
        if not node.get("alive"):
            continue
        try:
            conn = rpc.connect(tuple(node["address"]), timeout=5.0)
        except OSError:
            continue
        try:
            show(f"raylet {node['node_id'][:12]}",
                 conn.call("dump_stacks", {}, timeout=30))
        except (rpc.RpcError, ConnectionError, TimeoutError):
            pass
        finally:
            conn.close()


def cmd_drain(args) -> None:
    """`ray-tpu drain <node-id-prefix>`: graceful-preemption drain of
    one node (docs/fault_tolerance.md): emits NODE_PREEMPTING with the
    grace deadline, the raylet stops granting leases, lets short tasks
    finish and evacuates primary object copies to surviving nodes."""
    _connect(args)
    from ray_tpu.runtime.core_worker import get_global_worker
    worker = get_global_worker()
    matches = [n for n in worker.gcs.call("list_nodes")
               if n["alive"] and n["node_id"].startswith(args.node_id)]
    if not matches:
        sys.exit(f"no alive node matching {args.node_id!r}")
    if len(matches) > 1:
        sys.exit(f"ambiguous node prefix {args.node_id!r}: "
                 + ", ".join(n["node_id"][:12] for n in matches))
    node = matches[0]
    # omit grace_s when unset so the server-side CONFIG.drain_grace_s
    # default applies (an explicit --grace 0 still means "die ASAP")
    payload = {
        "node_id": node["node_id"],
        "reason": args.reason or "operator drain (ray-tpu drain)",
    }
    if args.grace is not None:
        payload["grace_s"] = args.grace
    reply = worker.gcs.call("drain_node", payload)
    if not reply.get("ok"):
        sys.exit(f"drain refused: {reply.get('reason')}")
    grace = "default" if args.grace is None else f"{args.grace:g}s"
    print(f"node {node['node_id'][:12]} draining "
          f"(grace {grace}, forwarded={reply.get('forwarded')})")


def cmd_events(args) -> None:
    """`ray-tpu events`: the cluster event table as an operator table;
    `--dossier <id>` dumps a crash dossier instead."""
    _connect(args)
    from ray_tpu.experimental import state
    if args.dossier:
        from ray_tpu._private.cluster_events import format_dossier
        d = state.get_dossier(args.dossier)
        if d is None:
            sys.exit(f"no dossier matching {args.dossier!r} "
                     "(rotated out, or the process died cleanly)")
        print(format_dossier(d))
        return
    rows = state.list_cluster_events(
        node_id=args.node, job_id=args.job, actor_id=args.actor,
        worker_id=args.worker, severity=args.severity,
        min_severity=args.min_severity, type=args.type,
        limit=args.limit)
    print("%-8s %-7s %-22s %-8s %-12s %s" % (
        "TIME", "SEV", "TYPE", "SOURCE", "NODE", "MESSAGE"))
    for e in rows:
        print("%-8s %-7s %-22s %-8s %-12s %s" % (
            time.strftime("%H:%M:%S", time.localtime(e.get("ts", 0))),
            e.get("severity", "?")[:7], e.get("type", "?")[:22],
            e.get("source", "")[:8],
            str(e.get("node_id") or "")[:12],
            e.get("message", "")))
    print(f"({len(rows)} events)", file=sys.stderr)


def cmd_traces(args) -> None:
    """`ray-tpu traces`: the trace directory as an operator table —
    newest first, with the SLO verdict per request root;
    `--slo-violations` narrows to requests that missed a target
    (docs/observability.md request tracing plane)."""
    _connect(args)
    from ray_tpu.experimental import state
    rows = state.list_traces(slo_violations=args.slo_violations,
                             route=args.route, limit=args.limit)
    print("%-18s %-8s %-22s %6s %9s %9s %-9s %s" % (
        "TRACE", "TIME", "ROUTE", "SPANS", "TTFT(ms)", "TPOT(ms)",
        "SLO", "STATUS"))
    for r in rows:
        slo = ("-" if r.get("slo_ok") is None else
               ("ok" if r["slo_ok"] else
                "VIOL:" + ",".join(r.get("slo_violated") or [])))
        print("%-18s %-8s %-22s %6d %9s %9s %-9s %s" % (
            r["trace_id"][:16] + "..",
            time.strftime("%H:%M:%S", time.localtime(r.get("start") or 0)),
            (r.get("route") or r.get("name") or "")[:22],
            r.get("nspans", 0),
            r.get("ttft_ms") if r.get("ttft_ms") is not None else "-",
            r.get("tpot_ms") if r.get("tpot_ms") is not None else "-",
            slo, r.get("status") or ""))
    print(f"({len(rows)} traces)", file=sys.stderr)


def cmd_trace(args) -> None:
    """`ray-tpu trace <trace_id>`: one request's span tree — which hop
    (queue wait, prefill, handoff pull, import wait, decode) ate the
    budget.  `--perfetto FILE` exports the trace merged with the
    cluster timeline's same-trace slices for ui.perfetto.dev."""
    _connect(args)
    from ray_tpu.experimental import state
    trace = state.get_trace(args.trace_id)
    if trace is None:
        sys.exit(f"no trace matching {args.trace_id!r} "
                 "(rotated out, unsampled, or not flushed yet)")
    print(state.trace_tree_text(trace))
    if args.perfetto:
        events = state.trace_timeline(trace["trace_id"], args.perfetto)
        print(f"wrote {len(events)} merged trace events to "
              f"{args.perfetto} (open in ui.perfetto.dev)")


def cmd_memory(args) -> None:
    _connect(args)
    from ray_tpu.experimental.state import memory_summary
    print(memory_summary())


def cmd_timeline(args) -> None:
    _connect(args)
    from ray_tpu.experimental.state import timeline
    out = args.output or f"timeline-{int(time.time())}.json"
    events = timeline(out)
    print(f"wrote {len(events)} trace events to {out} "
          "(open in chrome://tracing or ui.perfetto.dev)")


def cmd_doctor(args) -> None:
    """`ray-tpu doctor`: the cross-plane correlation report — node
    health, recovery episodes + SLO verdicts, recent WARNING+ events,
    straggler flags, worst-trace exemplars and open dossiers ranked
    into findings with evidence lines (docs/observability.md)."""
    import json as _json
    _connect(args)
    from ray_tpu.experimental import state
    if args.json:
        print(_json.dumps(state.doctor_report(), indent=1,
                          default=str))
        return
    print(state.doctor_report_text())


def cmd_debug_bundle(args) -> None:
    """`ray-tpu debug-bundle`: export every observability plane —
    events, dossiers, traces, metrics snapshot + history, step stats,
    recovery episodes, doctor report, merged Perfetto timeline — as
    one tarball for offline forensics."""
    _connect(args)
    from ray_tpu.experimental import state
    out = args.output or f"debug-bundle-{int(time.time())}.tar.gz"
    manifest = state.collect_debug_bundle(out)
    total = sum(manifest["members"].values())
    print(f"wrote {out}: {len(manifest['members'])} members, "
          f"{total:,} bytes")
    for name, size in sorted(manifest["members"].items()):
        print(f"  {name:32s} {size:>10,} B")


def cmd_debug(args) -> None:
    _connect(args)
    from ray_tpu.util.rpdb import list_breakpoints
    sessions = list_breakpoints()
    if not sessions:
        print("no active breakpoints")
        return
    for bid, addr in sessions:
        print(f"{bid}  {addr}   (attach: nc {addr.replace(':', ' ')})")


def cmd_profile(args) -> None:
    """Flame-sample a live cluster process (reference `ray stack`/py-spy
    reporter path): GCS by default, a raylet with --node, one of its
    workers with --worker.  `--group <name>` gang-fans-out instead:
    every rank of the named training run captures the SAME time window
    (folded host stacks always; a jax.profiler device trace with
    --device, TPU only — on a CPU-only box each rank reports the
    caveat and ships host stacks) and the captures merge into one
    Perfetto trace keyed by rank, correlated with the run's STEP
    timeline slices (docs/observability.md).  Prints folded stacks
    (-o writes .folded, or the merged .json for --group) or a top-N
    leaf summary."""
    from ray_tpu._private import rpc
    from ray_tpu._private.profiler import (folded_text, split_leaf_detail,
                                           top_summary)
    from ray_tpu.runtime.gcs import GcsClient

    if args.worker and not args.node:
        sys.exit("--worker requires --node (the worker's raylet)")
    if args.device and not args.group:
        sys.exit("--device requires --group (gang device capture)")
    addr = _resolve_address(args)
    host, port = addr.rsplit(":", 1)
    gcs = GcsClient((host, int(port)))
    try:
        if args.group:
            _profile_group(args, gcs)
            return
        if args.node:
            node = next((n for n in gcs.call("list_nodes")
                         if n["node_id"].startswith(args.node)
                         and n.get("alive")), None)
            if node is None:
                sys.exit(f"no alive node matching {args.node!r}")
            conn = rpc.connect(tuple(node["address"]), timeout=5.0)
            try:
                counts = conn.call("profile",
                                   {"duration": args.duration,
                                    "worker_id": args.worker},
                                   timeout=args.duration + 40)
            finally:
                conn.close()
        else:
            counts = gcs.call("profile", {"duration": args.duration},
                              timeout=args.duration + 40)
    finally:
        gcs.close()
    if args.output:
        clean, _ = split_leaf_detail(counts)
        with open(args.output, "w") as f:
            f.write(folded_text(counts) + "\n")
        print(f"wrote {sum(clean.values())} samples to {args.output}")
    else:
        print(top_summary(counts))


def _profile_group(args, gcs) -> None:
    """Gang-coordinated capture: one profile window on every rank of a
    training run, merged into a single Perfetto trace keyed by rank."""
    import threading
    from ray_tpu._private import rpc
    from ray_tpu._private import step_stats
    from ray_tpu._private.profiler import merge_folded, top_summary

    info = gcs.call("list_step_stats", {"run": args.group})
    runs = info.get("runs") or []
    if not runs:
        sys.exit(f"no training run matching {args.group!r} has reported "
                 "step stats (is the gang running with "
                 "RAY_TPU_STEP_STATS on?)")
    run = runs[-1]   # latest matching
    ranks = {int(r): m for r, m in (run.get("ranks") or {}).items()
             if m.get("address")}
    if not ranks:
        sys.exit(f"run {run['run']}: no rank has reported its RPC "
                 "address yet")
    results: dict = {}
    errors: dict = {}

    def capture(rank: int, meta: dict) -> None:
        try:
            conn = rpc.connect(tuple(meta["address"]), timeout=5.0)
            try:
                results[rank] = conn.call(
                    "profile", {"duration": args.duration,
                                "device": bool(args.device)},
                    timeout=args.duration + 40)
            finally:
                conn.close()
        except Exception as e:
            errors[rank] = repr(e)

    t_start = time.time()
    threads = [threading.Thread(target=capture, args=(r, m), daemon=True)
               for r, m in sorted(ranks.items())]
    for t in threads:
        t.start()   # all ranks sample the same wall-clock window
    for t in threads:
        t.join(args.duration + 60)
    t_end = time.time()
    for rank, err in sorted(errors.items()):
        print(f"rank {rank}: capture failed: {err}", file=sys.stderr)
    if not results:
        sys.exit("no rank produced a capture")

    per_rank = {}
    merged: dict = {}
    for rank, reply in sorted(results.items()):
        folded = reply.get("folded", reply) if isinstance(reply, dict) \
            and "folded" in reply else reply
        per_rank[rank] = folded
        merge_folded(merged, folded)
        if isinstance(reply, dict):
            if reply.get("device_trace"):
                print(f"rank {rank}: device trace at "
                      f"{reply['device_trace']} (on the rank's host)")
            elif reply.get("device_error"):
                print(f"rank {rank}: {reply['device_error']}",
                      file=sys.stderr)
    # correlate with the run's STEP slices from the GCS task table
    try:
        rows = gcs.call("list_task_events",
                        {"name": f"train_step:{run['run']}",
                         "limit": 4096})
    except Exception:
        rows = []
    step_events = step_stats.step_trace_events(
        rows, window=(t_start - 300.0, t_end))
    trace = step_stats.merged_profile_trace(
        per_rank, interval_s=0.01, t_start=t_start,
        step_events=step_events)
    out = args.output or f"profile-{run['run']}.json"
    with open(out, "w") as f:
        json.dump(trace, f)
    print(f"wrote {len(trace)} trace events for {len(per_rank)} ranks "
          f"to {out} (open in ui.perfetto.dev)")
    print(top_summary(merged))


def cmd_stack(args) -> None:
    """Dump every session process's Python thread stacks (py-spy /
    `ray stack` analog): SIGUSR1 each process whose cmdline references the
    session dir, then print the faulthandler dumps they wrote."""
    import glob

    session_dir = getattr(args, "session_dir", None) or \
        _latest_session_dir()
    if not session_dir:
        print("no session found; pass --session-dir")
        return
    session_dir = os.path.abspath(session_dir).rstrip("/")
    # faulthandler APPENDS to each per-pid file: remember current sizes so
    # only this run's dumps are printed (older runs' output and files of
    # dead/recycled pids would otherwise masquerade as live stacks)
    offsets = {}
    for path in glob.glob(os.path.join(session_dir, "logs",
                                       "stack_*.txt")):
        try:
            offsets[path] = os.path.getsize(path)
        except OSError:
            pass
    signalled = []
    for proc_dir in glob.glob("/proc/[0-9]*"):
        try:
            with open(os.path.join(proc_dir, "cmdline"), "rb") as f:
                cmdline = f.read().decode(errors="replace")
        except OSError:
            continue
        if session_dir in cmdline and "ray_tpu" in cmdline:
            pid = int(os.path.basename(proc_dir))
            if pid == os.getpid():
                continue
            try:
                os.kill(pid, signal.SIGUSR1)
                signalled.append(pid)
            except OSError:
                pass
    if not signalled:
        print(f"no ray_tpu processes found for session {session_dir}")
        return
    time.sleep(0.4)  # let faulthandler flush
    print(f"signalled {len(signalled)} processes: {signalled}")
    for pid in signalled:
        path = os.path.join(session_dir, "logs", f"stack_{pid}.txt")
        try:
            with open(path) as f:
                f.seek(offsets.get(path, 0))
                content = f.read().strip()
        except OSError:
            continue
        if content:
            print(f"\n===== pid {pid} =====")
            print(content)


def cmd_microbenchmark(args) -> None:
    from ray_tpu._private.ray_perf import main as perf_main
    perf_main(min_time=args.min_time)


# ------------------------------------------------------------------- jobs
def cmd_job(args) -> None:
    from ray_tpu.job_submission import JobSubmissionClient
    client = JobSubmissionClient(getattr(args, "address", None))
    if args.job_cmd == "submit":
        import shlex
        entrypoint = list(args.entrypoint)
        if entrypoint and entrypoint[0] == "--":
            entrypoint = entrypoint[1:]
        sid = client.submit_job(
            entrypoint=shlex.join(entrypoint),
            runtime_env=json.loads(args.runtime_env)
            if args.runtime_env else None)
        print(f"submitted: {sid}")
        if args.wait:
            status = client.wait_until_finished(sid, timeout=args.timeout)
            print(f"{sid}: {status}")
            print(client.get_job_logs(sid), end="")
            sys.exit(0 if status == "SUCCEEDED" else 1)
    elif args.job_cmd == "status":
        print(client.get_job_status(args.submission_id))
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.submission_id), end="")
    elif args.job_cmd == "stop":
        print("stopping" if client.stop_job(args.submission_id)
              else "not running")
    elif args.job_cmd == "list":
        for info in client.list_jobs():
            print(f"{info.submission_id}  {info.status:10s}  "
                  f"{info.entrypoint}")


def cmd_lint(args) -> None:
    """`ray-tpu lint`: the raylint static analyzer over the package
    (docs/static_analysis.md).  Exits nonzero on any unallowlisted
    violation — the same entry the tier-1 gate runs."""
    from ray_tpu._private.analysis import cli as lint_cli
    argv = []
    if args.root:
        argv += ["--root", args.root]
    for r in args.rules or ():
        argv += ["--rule", r]
    if args.no_baseline:
        argv.append("--no-baseline")
    if args.list_rules:
        argv.append("--list-rules")
    sys.exit(lint_cli.run(argv))


def cmd_serve(args) -> None:
    """serve status / run / deploy / shutdown (reference `serve` CLI)."""
    _connect(args)
    from ray_tpu import serve as serve_api
    from ray_tpu.serve.schema import ServeApplicationSchema

    if args.serve_cmd == "status":
        for name, st in sorted(serve_api.status().items()):
            print(f"{name:24s} {st['status']:10s} "
                  f"{st['running_replicas']}/{st['target_replicas']} replicas "
                  f"v{st['version']}")
    elif args.serve_cmd == "run":
        schema = ServeApplicationSchema(import_path=args.import_path)
        schema.apply()
        print(f"deployed {args.import_path}")
        if args.blocking:
            import time as _time
            try:
                while True:
                    _time.sleep(3600)
            except KeyboardInterrupt:
                serve_api.shutdown()
                print("serve shut down")
    elif args.serve_cmd == "deploy":
        import yaml
        with open(args.config_file) as f:
            cfg = yaml.safe_load(f)
        apps = cfg.get("applications", [cfg])
        for app in apps:
            ServeApplicationSchema.from_dict(app).apply()
            print(f"deployed {app.get('name', 'default')}")
    elif args.serve_cmd == "shutdown":
        serve_api.shutdown()
        print("serve shut down")


# ------------------------------------------------------------------ parser
def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ray_tpu",
                                description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head or worker node")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", help="GCS host:port to join")
    sp.add_argument("--port", type=int, default=0, help="GCS port (head)")
    sp.add_argument("--num-cpus", type=float)
    sp.add_argument("--num-tpus", type=float)
    sp.add_argument("--resources", help="extra resources as JSON")
    sp.add_argument("--object-store-memory", type=int)
    sp.add_argument("--dashboard", action="store_true")
    sp.add_argument("--dashboard-port", type=int, default=8265)
    sp.add_argument("--block", action="store_true")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop local daemons")
    sp.add_argument("--all", action="store_true",
                    help="stop every session, not just the latest")
    sp.add_argument("--session-dir",
                    help="stop exactly this session (launcher teardown)")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("up", help="launch a cluster from a YAML config")
    sp.add_argument("config", help="cluster YAML path")
    sp.add_argument("--dry-run", action="store_true",
                    help="print the gcloud/SSH plan without executing")
    sp.add_argument("--no-workers", action="store_true",
                    help="bring up only the head node")
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear down a launched cluster")
    sp.add_argument("config", help="cluster YAML path (or cluster name)")
    sp.set_defaults(fn=cmd_down)

    sp = sub.add_parser("attach", help="interactive shell on the head node")
    sp.add_argument("config", help="cluster YAML path")
    sp.set_defaults(fn=cmd_attach)

    sp = sub.add_parser("exec", help="run a shell command on the head node")
    sp.add_argument("config", help="cluster YAML path")
    sp.add_argument("command", nargs=argparse.REMAINDER,
                    help="command to run")
    sp.set_defaults(fn=cmd_exec)

    sp = sub.add_parser("submit",
                        help="run a driver script against the cluster")
    sp.add_argument("config", help="cluster YAML path")
    sp.add_argument("script", help="local python script to run on the head")
    sp.add_argument("script_args", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_submit)

    for name, fn in (("status", cmd_status), ("memory", cmd_memory),
                     ("debug", cmd_debug)):
        sp = sub.add_parser(name)
        sp.add_argument("--address")
        sp.set_defaults(fn=fn)

    sp = sub.add_parser("list", help="list cluster state")
    sp.add_argument("resource", choices=[
        "tasks", "actors", "nodes", "jobs", "objects", "workers",
        "placement-groups"])
    sp.add_argument("--address")
    sp.add_argument("--limit", type=int, default=100)
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("summary", help="summarize cluster state")
    sp.add_argument("resource",
                    choices=["tasks", "actors", "objects", "metrics",
                             "stacks", "training"])
    sp.add_argument("--address")
    sp.add_argument("--pid", help="(stacks) worker pid to sample")
    sp.add_argument("--actor",
                    help="(stacks) actor id prefix or name to sample")
    sp.add_argument("--run",
                    help="(training) run id or group prefix "
                         "(default: latest run)")
    sp.set_defaults(fn=cmd_summary)

    sp = sub.add_parser("drain",
                        help="gracefully drain a node before preemption "
                             "(stop leases, evacuate objects)")
    sp.add_argument("node_id", help="node id hex (prefix ok)")
    sp.add_argument("--grace", type=float, default=None,
                    help="grace window in seconds before the node is "
                         "expected to die (default: the cluster's "
                         "drain_grace_s)")
    sp.add_argument("--reason", default="")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_drain)

    sp = sub.add_parser("events",
                        help="cluster lifecycle events / crash dossiers")
    sp.add_argument("--address")
    sp.add_argument("--severity", help="exact severity filter")
    sp.add_argument("--min-severity", dest="min_severity",
                    help="severity floor (DEBUG|INFO|WARNING|ERROR)")
    sp.add_argument("--type", help="event type (e.g. WORKER_EXIT)")
    sp.add_argument("--node", help="node id prefix")
    sp.add_argument("--job", help="job id")
    sp.add_argument("--actor", help="actor id prefix")
    sp.add_argument("--worker", help="worker id prefix")
    sp.add_argument("--limit", type=int, default=100)
    sp.add_argument("--dossier",
                    help="dump the crash dossier with this id "
                         "(worker/node id hex) instead of listing events")
    sp.set_defaults(fn=cmd_events)

    sp = sub.add_parser("timeline", help="export Chrome trace")
    sp.add_argument("-o", "--output")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser("doctor",
                        help="cross-plane health report: ranked "
                             "findings with evidence lines")
    sp.add_argument("--address")
    sp.add_argument("--json", action="store_true",
                    help="emit the raw report as JSON")
    sp.set_defaults(fn=cmd_doctor)

    sp = sub.add_parser("debug-bundle",
                        help="export all observability planes as one "
                             "tarball for offline forensics")
    sp.add_argument("-o", "--output",
                    help="tarball path (default debug-bundle-"
                         "<ts>.tar.gz)")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_debug_bundle)

    sp = sub.add_parser("traces",
                        help="list request traces (span table)")
    sp.add_argument("--address")
    sp.add_argument("--slo-violations", dest="slo_violations",
                    action="store_true",
                    help="only requests that missed a TTFT/TPOT target")
    sp.add_argument("--route", help="route/deployment prefix filter")
    sp.add_argument("--limit", type=int, default=50)
    sp.set_defaults(fn=cmd_traces)

    sp = sub.add_parser("trace",
                        help="show one request trace's span tree")
    sp.add_argument("trace_id", help="trace id (prefix ok)")
    sp.add_argument("--address")
    sp.add_argument("--perfetto", metavar="FILE",
                    help="also export the trace merged with the "
                         "timeline's same-trace slices")
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser("stack",
                        help="dump all session processes' thread stacks")
    sp.add_argument("--session-dir")
    sp.set_defaults(fn=cmd_stack)

    sp = sub.add_parser("profile",
                        help="flame-sample a live cluster process, or a "
                             "whole training gang with --group")
    sp.add_argument("--address")
    sp.add_argument("--node", help="node id prefix (default: the GCS)")
    sp.add_argument("--worker", help="worker id prefix on that node")
    sp.add_argument("--group",
                    help="training run id or group prefix: capture the "
                         "same window on EVERY rank and merge into one "
                         "Perfetto trace keyed by rank")
    sp.add_argument("--device", action="store_true",
                    help="(--group) also capture a jax.profiler device "
                         "trace per rank (TPU only; CPU-only boxes "
                         "report the caveat and ship host stacks)")
    sp.add_argument("--duration", type=float, default=2.0)
    sp.add_argument("-o", "--output",
                    help="write folded stacks (.folded) or the merged "
                         "gang trace (.json) here")
    sp.set_defaults(fn=cmd_profile)

    sp = sub.add_parser("lint",
                        help="raylint: framework-invariant static "
                             "analyzer (docs/static_analysis.md)")
    sp.add_argument("--root", help="package dir to lint (default: the "
                                   "installed ray_tpu package)")
    sp.add_argument("--rule", action="append", dest="rules",
                    help="run only this rule (repeatable)")
    sp.add_argument("--no-baseline", action="store_true",
                    help="ignore the allowlist baseline")
    sp.add_argument("--list-rules", action="store_true",
                    help="print the checker catalog and exit")
    sp.set_defaults(fn=cmd_lint)

    sp = sub.add_parser("microbenchmark",
                        help="core-runtime ops/s suite (ray_perf analog)")
    sp.add_argument("--min-time", type=float, default=2.0)
    sp.set_defaults(fn=cmd_microbenchmark)

    sp = sub.add_parser("serve", help="serve deployments")
    ssub = sp.add_subparsers(dest="serve_cmd", required=True)
    s = ssub.add_parser("status")
    s.add_argument("--address")
    s = ssub.add_parser("run")
    s.add_argument("import_path", help="module:app bound Application")
    s.add_argument("--address")
    s.add_argument("--blocking", action="store_true")
    s = ssub.add_parser("deploy")
    s.add_argument("config_file", help="YAML app config")
    s.add_argument("--address")
    s = ssub.add_parser("shutdown")
    s.add_argument("--address")
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("job", help="job submission")
    jsub = sp.add_subparsers(dest="job_cmd", required=True)
    j = jsub.add_parser("submit")
    j.add_argument("--address")
    j.add_argument("--runtime-env", help="runtime env as JSON")
    j.add_argument("--wait", action="store_true")
    j.add_argument("--timeout", type=float, default=3600.0)
    j.add_argument("entrypoint", nargs=argparse.REMAINDER)
    for name in ("status", "logs", "stop"):
        j = jsub.add_parser(name)
        j.add_argument("--address")
        j.add_argument("submission_id")
    j = jsub.add_parser("list")
    j.add_argument("--address")
    sp.set_defaults(fn=cmd_job)

    return p


def main(argv: Optional[list] = None) -> None:
    args = build_parser().parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
