"""@ray_tpu.remote for functions.

Analog of /root/reference/python/ray/remote_function.py (RemoteFunction :35,
_remote :241, .options :141).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu.runtime.core_worker import (get_global_worker,
                                         normalize_num_returns)


class RemoteFunction:
    def __init__(self, func, *, num_returns=1,
                 num_cpus: float = 1.0, num_tpus: float = 0.0,
                 resources: Optional[Dict[str, float]] = None,
                 max_retries: int = 3,
                 scheduling_strategy: Any = None,
                 runtime_env: Optional[Dict[str, Any]] = None):
        self._func = func
        self._num_returns = normalize_num_returns(num_returns)
        self._resources = dict(resources or {})
        self._resources["CPU"] = num_cpus
        if num_tpus:
            self._resources["TPU"] = num_tpus
        self._max_retries = max_retries
        self._scheduling_strategy = scheduling_strategy
        self._runtime_env = runtime_env
        # every .options(...) key as given, carried into .bind() nodes
        self._bound_options: Dict[str, Any] = {}
        functools.update_wrapper(self, func)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._func.__name__!r} cannot be called "
            "directly; use .remote()")

    def remote(self, *args, **kwargs):
        from ray_tpu.util import client as client_mod
        ctx = client_mod.current()
        if ctx is not None:
            if self._num_returns == "streaming":
                raise NotImplementedError(
                    'num_returns="streaming" is not supported in '
                    "remote-driver (client://) mode: the stream is owned "
                    "by the submitting process")
            # remote-driver mode is decided at *call* time so functions
            # decorated before init("client://...") still route correctly
            return ctx.remote(
                self._func,
                num_returns=self._num_returns,
                num_cpus=self._resources.get("CPU", 1.0),
                num_tpus=self._resources.get("TPU", 0.0),
                resources={k: v for k, v in self._resources.items()
                           if k not in ("CPU", "TPU")},
                max_retries=self._max_retries,
            ).remote(*args, **kwargs)
        from ray_tpu.util.scheduling_strategies import encode_strategy
        worker = get_global_worker()
        refs = worker.submit_task(
            self._func, args, kwargs,
            num_returns=self._num_returns,
            resources=self._resources,
            max_retries=self._max_retries,
            name=getattr(self._func, "__name__", "task"),
            scheduling_strategy=encode_strategy(self._scheduling_strategy),
            runtime_env=worker.prepare_runtime_env(self._runtime_env))
        if self._num_returns == "streaming":
            # per-yield delivery: hand back the live stream, not a ref
            return worker.make_streaming_generator(refs[0])
        if self._num_returns == 1 or self._num_returns == "dynamic":
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Lazy DAG authoring (cf. reference dag/function_node.py).  The
        accumulated .options(...) ride along so DAG consumers (Serve,
        Workflow) see them — including extension keys like the Workflow
        step options ("_workflow") that plain .remote() ignores."""
        from ray_tpu.dag import FunctionNode
        return FunctionNode(self, args, kwargs,
                            options=dict(self._bound_options))

    def options(self, **opts) -> "RemoteFunction":
        new = RemoteFunction(
            self._func,
            num_returns=opts.get("num_returns", self._num_returns),
            num_cpus=opts.get("num_cpus", self._resources.get("CPU", 1.0)),
            num_tpus=opts.get("num_tpus", self._resources.get("TPU", 0.0)),
            resources=opts.get("resources",
                               {k: v for k, v in self._resources.items()
                                if k not in ("CPU", "TPU")}),
            max_retries=opts.get("max_retries", self._max_retries),
            scheduling_strategy=opts.get("scheduling_strategy",
                                         self._scheduling_strategy),
            runtime_env=opts.get("runtime_env", self._runtime_env))
        new._bound_options = dict(self._bound_options, **opts)
        return new
