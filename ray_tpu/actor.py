"""@ray_tpu.remote for classes: ActorClass / ActorHandle / ActorMethod.

Analog of /root/reference/python/ray/actor.py (ActorClass :377,
ActorHandle :1022, ActorMethod :92).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu._private.ids import ActorID
from ray_tpu.runtime.core_worker import (get_global_worker,
                                         normalize_num_returns)


def method(*args, **kwargs):
    """``@ray_tpu.method(concurrency_group=..., num_returns=...)`` — method
    options read worker-side at dispatch (cf. reference ray.method and
    concurrency groups, src/ray/core_worker/transport/
    concurrency_group_manager.h)."""
    def decorate(fn):
        fn.__ray_tpu_method_opts__ = dict(kwargs)
        return fn
    if len(args) == 1 and not kwargs and callable(args[0]):
        return decorate(args[0])
    if args:
        raise TypeError("@method takes keyword arguments only")
    return decorate


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str,
                 num_returns: int = 1,
                 concurrency_group: Optional[str] = None):
        self._handle = handle
        self._name = name
        # one normalization point shared with RemoteFunction: string
        # modes ("dynamic", "streaming") are validated here instead of
        # silently falling through int-only selection in remote()
        self._num_returns = normalize_num_returns(num_returns)
        self._concurrency_group = concurrency_group

    def remote(self, *args, **kwargs):
        worker = get_global_worker()
        refs = worker.submit_actor_task(
            self._handle._actor_id, self._name, args, kwargs,
            num_returns=self._num_returns,
            concurrency_group=self._concurrency_group)
        if self._num_returns == "streaming":
            return worker.make_streaming_generator(refs[0])
        if self._num_returns == 1 or self._num_returns == "dynamic":
            # "dynamic" reserves one slot: its ref resolves to the
            # ObjectRefGenerator at completion, same as task semantics
            return refs[0]
        return refs

    def options(self, num_returns: int = 1,
                concurrency_group: Optional[str] = None) -> "ActorMethod":
        return ActorMethod(self._handle, self._name, num_returns,
                           concurrency_group)

    def bind(self, *args, **kwargs):
        """Lazy DAG authoring against a LIVE actor (cf. reference
        actor-method ``.bind``): the node targets this handle's existing
        instance — classic ``execute()`` submits a normal actor task,
        and ``experimental_compile()`` schedules the method into a
        compiled graph without creating a new actor."""
        from ray_tpu.dag.dag_node import ClassMethodNode, ExistingActorNode
        return ClassMethodNode(ExistingActorNode(self._handle), self._name,
                               args, kwargs)


def _collect_method_opts(cls) -> Dict[str, dict]:
    """Per-method @ray_tpu.method(...) options, harvested from the class at
    handle-creation time (the handle alone can't see the class later)."""
    opts = {}
    for name in dir(cls):
        if name.startswith("__"):
            continue
        m = getattr(cls, name, None)
        o = getattr(m, "__ray_tpu_method_opts__", None)
        if o:
            opts[name] = dict(o)
    return opts


class ActorHandle:
    def __init__(self, actor_id: ActorID,
                 method_opts: Optional[Dict[str, dict]] = None):
        self._actor_id = actor_id
        self._method_opts = method_opts or {}

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        o = self._method_opts.get(name, {})
        return ActorMethod(self, name,
                           num_returns=o.get("num_returns", 1),
                           concurrency_group=o.get("concurrency_group"))

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._method_opts))


class ActorClass:
    def __init__(self, cls, *, num_cpus: float = 1.0, num_tpus: float = 0.0,
                 resources: Optional[Dict[str, float]] = None,
                 max_restarts: int = 0, name: Optional[str] = None,
                 namespace: str = "", lifetime: Optional[str] = None,
                 max_concurrency: Optional[int] = None,
                 concurrency_groups: Optional[Dict[str, int]] = None,
                 scheduling_strategy=None,
                 runtime_env: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._runtime_env = runtime_env
        self._resources = dict(resources or {})
        self._resources["CPU"] = num_cpus
        if num_tpus:
            self._resources["TPU"] = num_tpus
        self._max_restarts = max_restarts
        self._name = name
        self._namespace = namespace
        self._lifetime = lifetime
        self._max_concurrency = max_concurrency
        self._concurrency_groups = dict(concurrency_groups or {})
        self._scheduling_strategy = scheduling_strategy

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._cls.__name__!r} cannot be instantiated "
            "directly; use .remote()")

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ray_tpu.util import client as client_mod
        ctx = client_mod.current()
        if ctx is not None:
            # remote-driver mode is decided at *call* time (see
            # RemoteFunction.remote)
            return ctx.remote(
                self._cls,
                num_cpus=self._resources.get("CPU", 1.0),
                num_tpus=self._resources.get("TPU", 0.0),
                resources={k: v for k, v in self._resources.items()
                           if k not in ("CPU", "TPU")},
                max_restarts=self._max_restarts,
                name=self._name,
                max_concurrency=self._max_concurrency,
                concurrency_groups=self._concurrency_groups,
            ).remote(*args, **kwargs)
        from ray_tpu.util.scheduling_strategies import encode_strategy
        worker = get_global_worker()
        actor_id = worker.create_actor(
            self._cls, args, kwargs,
            name=self._name,
            namespace=self._namespace,
            detached=self._lifetime == "detached",
            max_restarts=self._max_restarts,
            max_concurrency=self._max_concurrency,
            concurrency_groups=self._concurrency_groups,
            resources=self._resources,
            scheduling_strategy=encode_strategy(self._scheduling_strategy),
            runtime_env=worker.prepare_runtime_env(self._runtime_env))
        return ActorHandle(actor_id, _collect_method_opts(self._cls))

    def bind(self, *args, **kwargs):
        """Lazy DAG authoring (cf. reference dag/class_node.py)."""
        from ray_tpu.dag import ClassNode
        return ClassNode(self, args, kwargs)

    def options(self, **opts) -> "ActorClass":
        return ActorClass(
            self._cls,
            num_cpus=opts.get("num_cpus", self._resources.get("CPU", 1.0)),
            num_tpus=opts.get("num_tpus", self._resources.get("TPU", 0.0)),
            resources=opts.get("resources",
                               {k: v for k, v in self._resources.items()
                                if k not in ("CPU", "TPU")}),
            max_restarts=opts.get("max_restarts", self._max_restarts),
            name=opts.get("name", self._name),
            namespace=opts.get("namespace", self._namespace),
            lifetime=opts.get("lifetime", self._lifetime),
            max_concurrency=opts.get("max_concurrency",
                                     self._max_concurrency),
            concurrency_groups=opts.get("concurrency_groups",
                                        self._concurrency_groups),
            scheduling_strategy=opts.get("scheduling_strategy",
                                         self._scheduling_strategy),
            runtime_env=opts.get("runtime_env", self._runtime_env))


def get_actor(name: str, namespace: str = "") -> ActorHandle:
    """Look up a named actor (cf. ray.get_actor)."""
    worker = get_global_worker()
    info = worker.gcs.call("get_actor", {"name": name,
                                         "namespace": namespace})
    if info is None:
        raise ValueError(f"no actor named {name!r}")
    return ActorHandle(ActorID.from_hex(info["actor_id"]))


def kill(handle) -> None:
    """Forcibly terminate an actor (cf. ray.kill)."""
    from ray_tpu.util import client as client_mod
    ctx = client_mod.current()
    if ctx is not None:
        ctx.kill(handle)
        return
    get_global_worker().kill_actor(handle._actor_id)
