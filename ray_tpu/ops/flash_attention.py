"""Flash attention as a Pallas TPU kernel (forward + backward).

Blockwise online-softmax attention: O(S) memory, [block_q, block_k] tiles on
the MXU, fp32 accumulators in VMEM, causal block skipping via dynamic loop
bounds.  The reference framework has no attention kernel at all (its compute
lives in torch user code — SURVEY.md §2.6); this is the framework-native hot
op that Train/Serve model families build on.

On non-TPU backends the same kernels run under ``interpret=True`` so unit
tests exercise the identical code path (SURVEY.md §4 device-simulation
strategy).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# pltpu.TPUCompilerParams -> CompilerParams rename shim
from ray_tpu._private.jax_compat import tpu_compiler_params as \
    _CompilerParams

NEG_INF = -1e30


def _interpret() -> bool:
    try:
        return jax.devices()[0].platform != "tpu"
    except (RuntimeError, IndexError):
        return True


def _pick_block(seq: int, target: int) -> int:
    b = min(target, seq)
    while seq % b:
        b //= 2
    return max(b, 1)


# --------------------------------------------------------------------------- #
# Forward                                                                     #
# --------------------------------------------------------------------------- #

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                sm_scale: float, causal: bool, block_k: int):
    block_q = q_ref.shape[1]
    kv_len = k_ref.shape[1]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # [bq, d]

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q_ref.shape[2]), jnp.float32)

    if causal:
        # blocks strictly above the diagonal contribute nothing
        num_kb = jnp.minimum(
            (qi * block_q + block_q + block_k - 1) // block_k,
            kv_len // block_k)
    else:
        num_kb = kv_len // block_k

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l_safe))[:, None]


def _fwd(q3, k3, v3, causal: bool, sm_scale: float,
         block_q: int, block_k: int, interpret: bool):
    bh, q_len, d = q3.shape
    kv_len = k3.shape[1]
    grid = (bh, q_len // block_q)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          block_k=block_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, kv_len, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, kv_len, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, q_len, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, q_len, 1), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(q3, k3, v3)
    return o, lse


# --------------------------------------------------------------------------- #
# Backward                                                                    #
# --------------------------------------------------------------------------- #

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   sm_scale: float, causal: bool, block_k: int):
    block_q = q_ref.shape[1]
    kv_len = k_ref.shape[1]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, 0]
    delta = delta_ref[0][:, 0]

    if causal:
        num_kb = jnp.minimum(
            (qi * block_q + block_q + block_k - 1) // block_k,
            kv_len // block_k)
    else:
        num_kb = kv_len // block_k

    def body(kb, dq):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        return dq + jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, num_kb, body, jnp.zeros((block_q, q_ref.shape[2]), jnp.float32))
    # q was pre-scaled; k inside the loop is unscaled, so dq is exact.
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *,
                    sm_scale: float, causal: bool, block_q: int):
    block_k = k_ref.shape[1]
    q_len = q_ref.shape[1]
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)

    num_qb = q_len // block_q
    start_qb = (ki * block_k) // block_q if causal else 0

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32) * sm_scale
        do = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * block_q, block_q), 0]
        delta = delta_ref[0, pl.ds(qb * block_q, block_q), 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            rows = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])  # [bq, bk]
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        # dk = sm_scale * ds^T @ q; q here is pre-scaled, so this is exact.
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return dk_new, dv_new

    d = k_ref.shape[2]
    dk, dv = jax.lax.fori_loop(
        start_qb, num_qb, body,
        (jnp.zeros((block_k, d), jnp.float32), jnp.zeros((block_k, d), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(q3, k3, v3, o3, lse, do3, causal: bool, sm_scale: float,
         block_q: int, block_k: int, interpret: bool):
    bh, q_len, d = q3.shape
    kv_len = k3.shape[1]
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1, keepdims=True)

    qspec = pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0))
    full_q = pl.BlockSpec((1, q_len, d), lambda i, j: (i, 0, 0))
    full_kv = pl.BlockSpec((1, kv_len, d), lambda i, j: (i, 0, 0))
    vec_q = pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0))
    full_vec_q = pl.BlockSpec((1, q_len, 1), lambda i, j: (i, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_k=block_k),
        grid=(bh, q_len // block_q),
        in_specs=[qspec, full_kv, full_kv, qspec, vec_q, vec_q],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, q_len, d), q3.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)

    kspec = pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q),
        grid=(bh, kv_len // block_k),
        in_specs=[full_q, kspec, kspec, full_q, full_vec_q, full_vec_q],
        out_specs=[kspec, kspec],
        out_shape=[jax.ShapeDtypeStruct((bh, kv_len, d), k3.dtype),
                   jax.ShapeDtypeStruct((bh, kv_len, d), v3.dtype)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------------------- #
# custom-vjp wrapper                                                          #
# --------------------------------------------------------------------------- #

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q3, k3, v3, causal, sm_scale, block_q, block_k, interpret):
    o, _ = _fwd(q3, k3, v3, causal, sm_scale, block_q, block_k, interpret)
    return o


def _flash_fwd(q3, k3, v3, causal, sm_scale, block_q, block_k, interpret):
    o, lse = _fwd(q3, k3, v3, causal, sm_scale, block_q, block_k, interpret)
    return o, (q3, k3, v3, o, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, res, do3):
    q3, k3, v3, o3, lse = res
    return _bwd(q3, k3, v3, o3, lse, do3, causal, sm_scale,
                block_q, block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, sm_scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 1024,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Flash attention on [B, S, H, D] / [B, Sk, H, D] inputs (heads equal;
    GQA expansion happens in ops.attention)."""
    b, q_len, h, d = q.shape
    kv_len = k.shape[1]
    if causal and q_len != kv_len:
        raise ValueError(
            "causal flash attention requires q_len == kv_len (got "
            f"{q_len} vs {kv_len}); use ops.attention with q_offset for "
            "decode-style queries")
    scale = sm_scale if sm_scale is not None else d ** -0.5
    bq = _pick_block(q_len, block_q)
    bk = _pick_block(kv_len, block_k)
    if interpret is None:
        interpret = _interpret()

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    o3 = _flash(to3(q), to3(k), to3(v), causal, scale, bq, bk, bool(interpret))
    return o3.reshape(b, h, q_len, d).transpose(0, 2, 1, 3)
