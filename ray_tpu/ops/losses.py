"""Loss functions for LM training.

``chunked_lm_loss`` is the memory-lean head: the [B, S, vocab] logits
tensor (the HBM peak of LM training — fp32 logits for gpt-small at
batch 32 are ~6.6 GiB, twice that with their gradient) never
materializes. The final projection + CE runs per sequence chunk under
``jax.checkpoint`` inside a ``lax.scan``/``lax.map``, so only one chunk's
logits live at a time and the backward recomputes them — a few percent
extra FLOPs for a ~S/chunk_size reduction in the logits' peak memory,
buying larger batches on the same chip.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _token_ce(logits: jax.Array, labels: jax.Array,
              z_loss: float = 0.0) -> jax.Array:
    """Unreduced per-token CE (+ z-loss) in fp32 — the shared core of the
    dense and chunked heads."""
    logits32 = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits32, axis=-1)
    label_logits = jnp.take_along_axis(
        logits32, labels[..., None], axis=-1).squeeze(-1)
    losses = lse - label_logits
    if z_loss:
        losses = losses + z_loss * jnp.square(lse)
    return losses


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          mask: Optional[jax.Array] = None,
                          z_loss: float = 0.0) -> Tuple[jax.Array, jax.Array]:
    """Token-level CE with optional z-loss; returns (mean_loss, denominator).

    logits: [..., vocab] (any dtype; softmax in fp32), labels: [...] int,
    mask: [...] with 0 to exclude (padding).
    """
    losses = _token_ce(logits, labels, z_loss)
    if mask is not None:
        losses = losses * mask
        denom = jnp.maximum(jnp.sum(mask), 1.0)
    else:
        denom = jnp.asarray(losses.size, jnp.float32)
    return jnp.sum(losses) / denom, denom


def chunked_lm_loss(hidden: jax.Array, weight: jax.Array,
                    labels: jax.Array,
                    mask: Optional[jax.Array] = None,
                    z_loss: float = 0.0,
                    chunk_size: int = 128,
                    transpose_weight: bool = False
                    ) -> Tuple[jax.Array, jax.Array]:
    """CE over chunked final projection: loss(hidden @ W, labels) without
    materializing full logits (see module docstring).

    hidden: [B, S, D] (post final-norm); weight: [D, V] (lm_head kernel)
    or [V, D] with ``transpose_weight`` (tied embedding); labels: [B, S];
    mask: [B, S] with 0 to exclude. Returns (mean_loss, denominator).
    """
    b, s, d = hidden.shape
    if s % chunk_size:
        # pad the sequence up to a chunk multiple; padded rows get mask 0
        pad = chunk_size - s % chunk_size
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)))
        mask = jnp.pad(mask if mask is not None
                       else jnp.ones((b, s), jnp.float32),
                       ((0, 0), (0, pad)))
        s += pad
    n_chunks = s // chunk_size
    hidden = hidden.reshape(b, n_chunks, chunk_size, d).transpose(1, 0, 2, 3)
    labels = labels.reshape(b, n_chunks, chunk_size).transpose(1, 0, 2)
    if mask is not None:
        mask_c = mask.reshape(b, n_chunks, chunk_size).transpose(1, 0, 2)
    else:
        mask_c = jnp.ones((n_chunks, b, chunk_size), jnp.float32)

    @jax.checkpoint
    def chunk_fn(h, t, m):
        if transpose_weight:
            logits = jnp.einsum("bcd,vd->bcv", h, weight.astype(h.dtype),
                                preferred_element_type=jnp.float32)
        else:
            logits = jnp.einsum("bcd,dv->bcv", h, weight.astype(h.dtype),
                                preferred_element_type=jnp.float32)
        return jnp.sum(_token_ce(logits, t, z_loss) * m)

    total = jax.lax.map(lambda args: chunk_fn(*args),
                        (hidden, labels, mask_c)).sum()
    denom = jnp.maximum(jnp.sum(mask_c), 1.0)
    return total / denom, denom
