"""Loss functions for LM training."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          mask: Optional[jax.Array] = None,
                          z_loss: float = 0.0) -> Tuple[jax.Array, jax.Array]:
    """Token-level CE with optional z-loss; returns (mean_loss, denominator).

    logits: [..., vocab] (any dtype; softmax in fp32), labels: [...] int,
    mask: [...] with 0 to exclude (padding).
    """
    logits32 = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits32, axis=-1)
    label_logits = jnp.take_along_axis(
        logits32, labels[..., None], axis=-1).squeeze(-1)
    losses = lse - label_logits
    if z_loss:
        losses = losses + z_loss * jnp.square(lse)
    if mask is not None:
        losses = losses * mask
        denom = jnp.maximum(jnp.sum(mask), 1.0)
    else:
        denom = jnp.asarray(losses.size, jnp.float32)
    return jnp.sum(losses) / denom, denom
