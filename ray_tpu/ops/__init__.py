"""TPU compute ops: attention kernels, fused layers, losses.

The hot-op layer of the framework.  Where the reference leans on torch/CUDA
kernels inside user training loops, these are Pallas TPU kernels (MXU-shaped
block sizes, VMEM-resident tiles, fp32 accumulation) with jax-native
fallbacks that run anywhere (CPU tests, interpret mode).
"""

from ray_tpu.ops.attention import attention
from ray_tpu.ops.layers import apply_rope, rms_norm, rope_frequencies, swiglu
from ray_tpu.ops.losses import softmax_cross_entropy
from ray_tpu.ops.ring_attention import ring_attention

__all__ = [
    "attention", "ring_attention", "rms_norm", "apply_rope",
    "rope_frequencies", "swiglu", "softmax_cross_entropy",
]
