"""Ulysses (DeepSpeed-style) sequence parallelism via head/sequence all-to-all.

Like ring attention (ray_tpu/ops/ring_attention.py), this is a long-context
primitive absent from the reference (SURVEY.md §5 "Long-context": no Ulysses
anywhere).  The sequence axis is sharded over the mesh ``context`` axis; an
``all_to_all`` swaps the shard dimension from sequence to heads, so each
device runs *exact* full-sequence attention for ``H/N`` heads with any local
kernel (the Pallas flash kernel on TPU), then a second all-to-all swaps back.

Trade-off vs ring attention: two all-to-alls per layer (O(S·H·D/N) bytes over
ICI) instead of N ppermute steps, and the full [S] sequence is materialized
per device for its head slice — better when heads ≥ ring size and the flash
kernel dominates; ring is better when S/N is all that fits in HBM.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

# renamed-API shims (shard_map promotion, lax.axis_size)
from ray_tpu._private.jax_compat import axis_size as _axis_size
from ray_tpu._private.jax_compat import shard_map as _shard_map


def ulysses_attention_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                            axis_name: str, causal: bool = True,
                            sm_scale: Optional[float] = None,
                            impl: str = "auto") -> jax.Array:
    """Per-shard Ulysses attention; call inside shard_map over ``axis_name``.

    q: local shard [B, S_local, H, D]; k/v: [B, S_local, KvH, D].  Requires
    H % axis_size == 0 and KvH % axis_size == 0 (repeat KV first for GQA
    ratios finer than the axis size).
    """
    n = _axis_size(axis_name)
    if q.shape[2] % n or k.shape[2] % n:
        raise ValueError(
            f"heads {q.shape[2]}/kv_heads {k.shape[2]} not divisible by "
            f"sequence-parallel axis size {n}")
    # [B, S/N, H, D] -> [B, S, H/N, D]: split heads, concat sequence.
    swap = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                             split_axis=2, concat_axis=1, tiled=True)
    q_full, k_full, v_full = swap(q), swap(k), swap(v)

    from ray_tpu.ops.attention import attention
    out = attention(q_full, k_full, v_full, causal=causal,
                    sm_scale=sm_scale, impl=impl)
    # [B, S, H/N, D] -> [B, S/N, H, D]
    return jax.lax.all_to_all(out, axis_name=axis_name, split_axis=1,
                              concat_axis=2, tiled=True)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      mesh: Mesh, axis_name: str = "context",
                      causal: bool = True, sm_scale: Optional[float] = None,
                      impl: str = "auto",
                      batch_axes=("data", "fsdp")) -> jax.Array:
    """Global-array entry point: shard_maps over the context axis.

    q/k/v are global [B, S, H, D] arrays inside jit; the sequence dimension
    is (re)sharded over ``axis_name``, each shard all-to-alls into full-
    sequence/partial-heads layout, attends locally, and swaps back.
    """
    batch_axes = tuple(a for a in batch_axes if mesh.shape.get(a, 1) > 1) or None
    spec = P(batch_axes, axis_name, None, None)
    fn = functools.partial(ulysses_attention_local, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale, impl=impl)
    return _shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
