"""Ring attention: exact causal attention over a context-parallel mesh axis.

Sequence/context parallelism is absent from the reference (SURVEY.md §5
"Long-context": no ring attention, no Ulysses anywhere); here it is a
first-class op.  The sequence axis is sharded over the mesh's ``context``
axis; each device holds a [B, S/N, H, D] shard of q/k/v, and K/V shards
rotate around the ICI ring via ``jax.lax.ppermute`` while every device
accumulates its local q block's attention with an online softmax — flash
attention's rescaling trick applied across devices.  The whole thing is
differentiable (scan + ppermute autodiff), so the same code path serves
training.

Causal skipping: a device only attends to K/V shards at or before its own
global offset, so steps with fully-masked blocks skip the matmuls via
``lax.cond``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

# renamed-API shims (shard_map promotion, lax.axis_size)
from ray_tpu._private.jax_compat import axis_size as _axis_size
from ray_tpu._private.jax_compat import shard_map as _shard_map

NEG_INF = -1e30


def _block_attend(q_scaled, k, v, q_off, kv_off, causal, block_size):
    """Unnormalized blockwise attention; returns (m, l, o) partials."""
    bq = q_scaled.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q_scaled, k,
                   preferred_element_type=jnp.float32)
    if causal:
        rows = q_off + jnp.arange(bq)[:, None]
        cols = kv_off + jnp.arange(block_size)[None, :]
        s = jnp.where(rows >= cols, s, NEG_INF)
    m = jnp.max(s, axis=-1)                      # [b, h, q]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return m, l, o


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         axis_name: str, causal: bool = True,
                         sm_scale: Optional[float] = None) -> jax.Array:
    """Per-shard ring attention; call inside shard_map over ``axis_name``.

    q/k/v: local shards [B, S_local, H, D]; sequence is sharded contiguously
    (shard i holds global positions [i*S_local, (i+1)*S_local)).
    """
    b, s_local, h, d = q.shape
    n = _axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    scale = sm_scale if sm_scale is not None else d ** -0.5
    q_scaled = q.astype(jnp.float32) * scale
    q_off = my * s_local

    m0 = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    acc0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        m, l, acc, kv = carry
        k_t, v_t = kv
        src = (my - t) % n           # which shard's kv we currently hold
        kv_off = src * s_local

        def attend(_):
            ms, ls, os_ = _block_attend(q_scaled, k_t, v_t, q_off, kv_off,
                                        causal, s_local)
            m_new = jnp.maximum(m, ms)
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(ms - m_new)
            l_new = l * alpha + ls * beta
            acc_new = acc * alpha[..., None] + os_ * beta[..., None]
            return m_new, l_new, acc_new

        if causal:
            # Shards strictly after ours in global order are fully masked.
            m, l, acc = jax.lax.cond(kv_off <= q_off, attend,
                                     lambda _: (m, l, acc), None)
        else:
            m, l, acc = attend(None)
        kv = jax.lax.ppermute((k_t, v_t), axis_name, perm)
        return (m, l, acc, kv), None

    (m, l, acc, _), _ = jax.lax.scan(step, (m0, l0, acc0, (k, v)),
                                     jnp.arange(n))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l_safe[..., None]).transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   mesh: Mesh, axis_name: str = "context",
                   causal: bool = True,
                   sm_scale: Optional[float] = None,
                   batch_axes=("data", "fsdp")) -> jax.Array:
    """Global-array entry point: shard_maps over the context axis.

    q/k/v are global [B, S, H, D] arrays inside jit; the sequence dimension
    is (re)sharded over ``axis_name`` and attention runs as a ring.  Batch
    stays sharded over the data axes; heads/head_dim replicated across the
    ring (tensor-parallel head sharding composes outside, since shard_map
    only binds the named axes in ``in_specs``).
    """
    batch_axes = tuple(a for a in batch_axes if mesh.shape.get(a, 1) > 1) or None
    spec = P(batch_axes, axis_name, None, None)
    fn = functools.partial(ring_attention_local, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale)
    return _shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
