"""Splash-attention wrapper: JAX's production TPU attention kernel.

The hand-rolled Pallas kernel (ops/flash_attention.py) reaches ~59%
hardware utilization on 1B-scale shapes; ``jax.experimental.pallas.ops
.tpu.splash_attention`` is the heavily tuned public kernel (fused
causal-grid skipping, tuned block sizes per generation) exposed here as
``attention(..., impl="splash")``.  Layout adapter only — inputs stay
[B, S, H, D] like every other impl.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _make_kernel(n_heads: int, q_len: int, kv_len: int, causal: bool):
    # built fresh per trace: caching the kernel object would leak arrays
    # created under one trace into the next (UnexpectedTracerError);
    # mask construction is cheap numpy and jit caching dedups the rest
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel, splash_attention_mask)
    if causal:
        mask = splash_attention_mask.CausalMask((q_len, kv_len))
    else:
        mask = splash_attention_mask.FullMask((q_len, kv_len))
    mh = splash_attention_mask.MultiHeadMask([mask] * n_heads)
    return splash_attention_kernel.make_splash_mha(
        mask=mh, head_shards=1, q_seq_shards=1)


def splash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                     causal: bool = True,
                     sm_scale: Optional[float] = None) -> jax.Array:
    """[B, S, H, D] x3 -> [B, S, H, D]; heads must already match
    (GQA expansion happens in ops.attention)."""
    b, s, h, d = q.shape
    kv_len = k.shape[1]
    if causal and s != kv_len:
        raise ValueError(
            "causal splash attention requires q_len == kv_len (got "
            f"{s} vs {kv_len}); decode-style queries use ops.attention "
            "with q_offset")
    scale = sm_scale if sm_scale is not None else d ** -0.5
    kernel = _make_kernel(h, s, kv_len, causal)

    def per_example(qi, ki, vi):
        # splash wants [H, S, D] and pre-scaled queries
        return kernel(qi.transpose(1, 0, 2) * scale,
                      ki.transpose(1, 0, 2),
                      vi.transpose(1, 0, 2)).transpose(1, 0, 2)

    out = jax.vmap(per_example)(q, k, v)
    return out.astype(q.dtype)


