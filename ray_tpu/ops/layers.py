"""Fused layer primitives: RMSNorm, rotary embeddings, SwiGLU.

Kept as jax-native expressions — XLA fuses these elementwise chains into the
surrounding matmuls on TPU (HBM-bandwidth note in the repo brief); Pallas is
reserved for ops XLA can't fuse well (attention, ring collectives).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm in fp32, cast back to input dtype."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(x.dtype)


def rope_frequencies(head_dim: int, max_len: int, theta: float = 10000.0
                     ) -> Tuple[jax.Array, jax.Array]:
    """Precompute (cos, sin) tables of shape [max_len, head_dim // 2]."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                                / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
               positions: jax.Array | None = None) -> jax.Array:
    """Rotary position embedding on [B, S, H, D] (D split into even/odd halves).

    ``positions``: [B, S] global positions (for context-parallel shards /
    decode offsets); default arange(S).
    """
    b, s, h, d = x.shape
    if positions is None:
        cos_s = cos[:s][None, :, None, :]
        sin_s = sin[:s][None, :, None, :]
    else:
        cos_s = cos[positions][:, :, None, :]
        sin_s = sin[positions][:, :, None, :]
    x32 = x.astype(jnp.float32)
    x1, x2 = jnp.split(x32, 2, axis=-1)
    rotated = jnp.concatenate(
        [x1 * cos_s - x2 * sin_s, x2 * cos_s + x1 * sin_s], axis=-1)
    return rotated.astype(x.dtype)


def swiglu(x: jax.Array, w_gate: jax.Array, w_up: jax.Array,
           w_down: jax.Array) -> jax.Array:
    """SwiGLU MLP: silu(x @ w_gate) * (x @ w_up) @ w_down."""
    gate = jax.nn.silu(x @ w_gate)
    return (gate * (x @ w_up)) @ w_down
