"""Mixture-of-experts MLP with expert parallelism (Switch/Mixtral-style).

Expert parallelism is absent from the reference (SURVEY.md §2.6: "Expert
parallel (EP/MoE): absent").  TPU-first design: routing is a *dense*,
static-shape dispatch — top-k gating builds [tokens, experts, capacity]
one-hot dispatch/combine tensors and the expert FFNs run as one batched
einsum over the expert dimension.  Expert parameters carry the ``expert``
logical axis (sharded over the data axes by the default rule table,
ray_tpu/parallel/sharding.py), so under GSPMD the dispatch einsum lowers to
the expert all-to-all on ICI; no ragged host-side routing, everything stays
on the MXU with shapes known at compile time.

The router's load-balancing auxiliary loss (Switch Transformer eq. 4) is
exported via ``self.sow("intermediates", "moe_aux_loss", ...)``; the train
step collects and adds it (ray_tpu/train/step.py lm_loss_fn).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEMLP(nn.Module):
    """Drop-in SwiGLU MLP with ``n_experts`` experts and top-k routing.

    Input/output: [B, S, d_model].  Tokens overflowing an expert's capacity
    ``ceil(top_k * S * capacity_factor / n_experts)`` are dropped (their
    residual stream passes through unchanged), the standard static-shape
    TPU formulation.
    """

    n_experts: int
    d_ff: int
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    router_jitter: float = 0.0
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, s, d = x.shape
        e, k = self.n_experts, self.top_k
        capacity = max(int(k * s * self.capacity_factor / e), 1)
        capacity = min(capacity, s * k)

        router = nn.DenseGeneral(
            e, axis=-1, use_bias=False, name="router",
            dtype=jnp.float32, param_dtype=self.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", None)))
        logits = router(x.astype(jnp.float32))          # [B, S, E]
        if (self.router_jitter > 0.0 and not self.is_initializing()
                and self.has_rng("router")):
            # jitter only when the caller provides a "router" rng stream
            # (the default train step passes none — jitter then degrades to
            # deterministic routing instead of raising inside jit)
            noise = jax.random.uniform(
                self.make_rng("router"), logits.shape,
                minval=1.0 - self.router_jitter, maxval=1.0 + self.router_jitter)
            logits = logits * noise
        probs = jax.nn.softmax(logits, axis=-1)
        gate_vals, expert_idx = jax.lax.top_k(probs, k)   # [B, S, K]
        gate_vals = gate_vals / jnp.clip(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

        # Position of each (token, slot) in its expert's queue, in
        # slot-major order so a token's first choice wins capacity first.
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # [B,S,K,E]
        slot_major = onehot.transpose(0, 2, 1, 3).reshape(b, k * s, e)
        pos = jnp.cumsum(slot_major, axis=1) - 1.0                 # [B,KS,E]
        pos = (pos * slot_major).sum(-1).reshape(b, k, s).transpose(0, 2, 1)
        pos = pos.astype(jnp.int32)
        within_cap = pos < capacity                                # [B, S, K]

        keep = onehot * within_cap[..., None]                      # [B,S,K,E]
        pos_onehot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)
        # dispatch: [B, S, E, C]; combine adds the gate weights.
        dispatch = jnp.einsum("bske,bskc->bsec", keep, pos_onehot)
        combine = jnp.einsum("bsk,bske,bskc->bsec",
                             gate_vals, keep, pos_onehot)

        w_gate = self.param(
            "w_gate", nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("expert", "expert_in", "expert_mlp")),
            (e, d, self.d_ff), self.param_dtype)
        w_up = self.param(
            "w_up", nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("expert", "expert_in", "expert_mlp")),
            (e, d, self.d_ff), self.param_dtype)
        w_down = self.param(
            "w_down", nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("expert", "expert_mlp", "expert_in")),
            (e, self.d_ff, d), self.param_dtype)

        expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch.astype(self.dtype),
                               x.astype(self.dtype))
        gate_h = jnp.einsum("ebcd,edf->ebcf", expert_in,
                            w_gate.astype(self.dtype))
        up_h = jnp.einsum("ebcd,edf->ebcf", expert_in,
                          w_up.astype(self.dtype))
        expert_out = jnp.einsum("ebcf,efd->ebcd", nn.silu(gate_h) * up_h,
                                w_down.astype(self.dtype))
        y = jnp.einsum("bsec,ebcd->bsd", combine.astype(self.dtype),
                       expert_out)

        # Switch load-balancing loss: E * sum_e f_e * P_e, where f_e is the
        # fraction of tokens whose top-1 choice is e and P_e the mean router
        # probability for e.
        top1 = jax.nn.one_hot(expert_idx[..., 0], e, dtype=jnp.float32)
        f = jnp.mean(top1, axis=(0, 1))
        p = jnp.mean(probs, axis=(0, 1))
        aux = self.aux_loss_coef * e * jnp.sum(f * p)
        self.sow("intermediates", "moe_aux_loss", aux)
        return y.astype(x.dtype)
