"""Paged (block) KV-cache attention for continuous-batching decode.

The reference has no paged KV — it serves LLMs by scaling whole replicas
and batching requests (`python/ray/serve/batching.py`); its KV layout is
whatever the user's model framework allocates.  Our continuous-batching
engine (serve/llm_engine.py) originally gave every decode slot a dense
``[max_seq_len]`` cache row, so every decode step read the full row span
from HBM — serving short chats with a long cache burned bandwidth
linearly in ``max_seq_len``, and slot count was capped by
``slots * max_seq`` HBM reservation.

Paged layout instead pools KV in fixed-size pages shared by all slots:

  kv_pages:     [num_pages, kv_heads, page_size, 2*head_dim]  (per layer,
                K in [..., :head_dim], V in [..., head_dim:])
  block_tables: [rows, max_pages_per_seq] int32  (logical -> physical)

A sequence at position ``p`` occupies ``ceil((p+1)/page_size)`` pages.
The layout is dictated by TPU tiling: Mosaic DMAs slice memrefs in
(8, 128) tiles, so the page's minor dim must be a multiple of 128 —
``2*head_dim`` is exactly that for the common head_dims (64, 128, 256),
and fusing K and V makes a page one DMA instead of two.  kv_heads sits
outside (page_size, 2*head_dim) so per-head views are tile-aligned.

Two implementations:

  - ``paged_attention_xla`` — gather the table span, mask by length,
    dense attention.  Runs on every backend (the CPU test oracle and
    fallback).  It reads the whole (static) table span, so its HBM win
    comes from sizing ``max_pages_per_seq`` to the workload.
  - ``paged_attention_tpu`` — Pallas kernel: grid over rows, per-row
    ``fori_loop`` DMAs ONLY the row's occupied pages HBM->VMEM
    (double-buffered) with flash-style online softmax.  HBM traffic per
    decode step scales with actual context length — the property the
    dense row layout can't have.

``paged_attention`` dispatches by backend.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import xla_attention


def paged_attention_xla(q: jax.Array, kv_pages: jax.Array,
                        block_tables: jax.Array, lengths: jax.Array, *,
                        sm_scale: Optional[float] = None) -> jax.Array:
    """Gather-based paged decode attention (one query token per row).

    q:            [rows, heads, head_dim]
    kv_pages:     [num_pages, kv_heads, page_size, 2*head_dim]
    block_tables: [rows, max_pages] physical page ids, position-ordered
    lengths:      [rows] number of valid positions (current pos + 1)
    returns       [rows, heads, head_dim]
    """
    rows, _, hd = q.shape
    _, kvh, ps, _ = kv_pages.shape
    # [rows, mp, kvh, ps, 2hd] -> [rows, mp*ps, kvh, 2hd] position-major
    kv = jnp.moveaxis(kv_pages[block_tables], 2, 3
                      ).reshape(rows, -1, kvh, 2 * hd)
    span = kv.shape[1]
    mask = jnp.arange(span)[None, :] < lengths[:, None]
    out = xla_attention(q[:, None], kv[..., :hd], kv[..., hd:],
                        causal=False, mask=mask, sm_scale=sm_scale)
    return out[:, 0]


def _tpu_kernel(q2: jax.Array, kv_pages: jax.Array,
                block_tables: jax.Array, lengths: jax.Array,
                sm_scale: float) -> jax.Array:
    """Pallas TPU decode kernel: per-row loop over occupied pages only.

    ``q2`` is the query padded to [rows, heads, 2*head_dim] (zeros in
    the V half) so every buffer's minor dim is lane-aligned; the zero
    half makes q2 . kv_page contract to K-only scores, and p . kv_page
    leaves the real output in the V half of the accumulator — no
    sub-tile slicing anywhere in the kernel.  The row's page count
    (ceil(length/page_size)) is a traced ``fori_loop`` bound, so pages
    past the row's context are never DMA'd.  In-kernel math stays 2-D
    per kv head (Mosaic rejects batched dot_generals).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows, heads, hd2 = q2.shape
    num_pages, kvh, ps, _ = kv_pages.shape
    g = heads // kvh

    def kernel(tables_ref, len_ref, q_ref, kv_ref, out_ref,
               kvbuf, acc_ref, m_ref, l_ref, sems):
        r = pl.program_id(0)
        length = len_ref[r]
        n_pg = pl.cdiv(length, ps)

        def get_dma(slot, i):
            return pltpu.make_async_copy(
                kv_ref.at[tables_ref[r, i]], kvbuf.at[slot],
                sems.at[slot])

        @pl.when(n_pg > 0)
        def _():
            get_dma(0, 0).start()

        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)
        qv = q_ref[0].astype(jnp.float32) * sm_scale      # [heads, 2hd]

        def body(i, _):
            slot = i % 2

            @pl.when(i + 1 < n_pg)
            def _():
                get_dma((i + 1) % 2, i + 1).start()

            get_dma(slot, i).wait()
            pos = i * ps + jax.lax.broadcasted_iota(jnp.int32, (g, ps), 1)
            valid = pos < length
            for h in range(kvh):                 # static per-head 2-D ops
                lo, hi = h * g, (h + 1) * g
                kv_h = kvbuf[slot, h].astype(jnp.float32)   # [ps, 2hd]
                # zero V-half of q2 -> K-only scores
                s = jax.lax.dot_general(
                    qv[lo:hi], kv_h, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)     # [g, ps]
                s = jnp.where(valid, s, -1e30)
                m_prev = m_ref[lo:hi]                       # [g, 1]
                m_new = jnp.maximum(
                    m_prev, jnp.max(s, axis=1, keepdims=True))
                p = jnp.exp(s - m_new)
                alpha = jnp.exp(m_prev - m_new)
                l_ref[lo:hi] = (l_ref[lo:hi] * alpha
                                + jnp.sum(p, axis=1, keepdims=True))
                # [g, 2hd]: K-half is junk, V-half is the real p @ V
                pv = jax.lax.dot_general(
                    p, kv_h, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                acc_ref[lo:hi] = acc_ref[lo:hi] * alpha + pv
                m_ref[lo:hi] = m_new
            return 0

        jax.lax.fori_loop(0, n_pg, body, 0)
        norm = jnp.maximum(l_ref[:], 1e-30)               # [heads, 1]
        out_ref[0] = (acc_ref[:] / norm).astype(out_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                 # block_tables, lengths
        grid=(rows,),
        in_specs=[
            pl.BlockSpec((1, heads, hd2), lambda r, *_: (r, 0, 0),
                         memory_space=pltpu.VMEM),         # q2
            pl.BlockSpec(memory_space=pltpu.ANY),          # kv_pages (HBM)
        ],
        out_specs=pl.BlockSpec((1, heads, hd2), lambda r, *_: (r, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, kvh, ps, hd2), kv_pages.dtype),  # double-buffer
            pltpu.VMEM((heads, hd2), jnp.float32),          # acc
            pltpu.VMEM((heads, 1), jnp.float32),            # running max
            pltpu.VMEM((heads, 1), jnp.float32),            # running sum
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rows, heads, hd2), q2.dtype),
    )(block_tables, lengths, q2, kv_pages)


def paged_attention_tpu(q, kv_pages, block_tables, lengths, *,
                        sm_scale: Optional[float] = None) -> jax.Array:
    hd = q.shape[-1]
    scale = sm_scale if sm_scale is not None else hd ** -0.5
    q2 = jnp.concatenate([q, jnp.zeros_like(q)], axis=-1)
    out2 = _tpu_kernel(q2, kv_pages, block_tables,
                       lengths.astype(jnp.int32), scale)
    return out2[..., hd:]       # V half holds the attention output


@functools.cache
def _default_impl() -> str:
    try:
        return ("tpu" if jax.devices()[0].platform == "tpu" else "xla")
    except (RuntimeError, IndexError):
        return "xla"


def paged_attention(q, kv_pages, block_tables, lengths, *,
                    sm_scale: Optional[float] = None,
                    impl: str = "auto") -> jax.Array:
    """Backend-dispatched paged decode attention (see module docstring).

    ``RAY_TPU_PAGED_ATTENTION_IMPL=xla|tpu`` overrides the dispatch —
    the on-chip engine-machinery tests force ``xla`` so they can demand
    BIT-exact equality with lone dense generation (the Pallas kernel's
    page-wise online softmax is numerically equivalent but not bitwise,
    so greedy decode can tie-flip vs the dense oracle)."""
    import os
    if impl == "auto":
        impl = os.environ.get("RAY_TPU_PAGED_ATTENTION_IMPL", "auto")
    if impl == "auto":
        impl = _default_impl()
        if kv_pages.shape[-1] % 128:
            # Mosaic DMA slices must be lane-aligned: 2*head_dim below
            # 128 (test-size models) can't use the kernel
            impl = "xla"
    fn = paged_attention_tpu if impl == "tpu" else paged_attention_xla
    return fn(q, kv_pages, block_tables, lengths, sm_scale=sm_scale)
