"""Multi-head attention with selectable implementation.

``impl``:
  - ``"xla"``    — einsum attention; runs everywhere, materializes [Sq, Sk].
  - ``"flash"``  — Pallas TPU flash kernel (ray_tpu/ops/flash_attention.py);
                   O(S) memory, fused online softmax on the MXU.
  - ``"splash"`` — JAX's public tuned TPU kernel (comparison impl; the
    in-tree flash kernel measured faster at head_dim 64).
  - ``"auto"``   — flash on TPU backends, xla elsewhere.

Layout convention throughout the framework: ``q``: [batch, q_len, heads,
head_dim]; ``k``/``v``: [batch, kv_len, kv_heads, head_dim] with grouped-query
attention when ``kv_heads < heads``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except (RuntimeError, IndexError):
        return False


def repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, KvH, D] -> [B, S, KvH*n_rep, D] for grouped-query attention."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)
                            ).reshape(b, s, h * n_rep, d)


def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                  causal: bool = True, sm_scale: Optional[float] = None,
                  q_offset: int = 0,
                  mask: Optional[jax.Array] = None) -> jax.Array:
    """Reference einsum attention (fp32 logits/softmax, input-dtype output).

    ``q_offset``: global position of q[0] relative to k[0] — used by the ring
    attention fallback and by decode (q_len==1 at position offset).
    ``mask``: optional key-padding mask [B, Kv] (True = attend) or an
    additive/boolean [B, 1|H, Q, Kv] mask (encoders: BERT/T5 padding).
    """
    *_, q_len, heads, head_dim = q.shape
    kv_len, kv_heads = k.shape[-3], k.shape[-2]
    if kv_heads != heads:
        k = repeat_kv(k, heads // kv_heads)
        v = repeat_kv(v, heads // kv_heads)
    scale = sm_scale if sm_scale is not None else head_dim ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = jnp.arange(q_len)[:, None] + q_offset
        k_pos = jnp.arange(kv_len)[None, :]
        logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
    if mask is not None:
        if mask.ndim == 2:                      # [B, Kv] key padding
            # 0/1 integer padding masks are boolean in intent — coerce,
            # else they'd fall into the additive branch and mask nothing.
            # A float 2-D mask is ambiguous (additive -1e9 convention
            # would be silently inverted): refuse it loudly.
            if jnp.issubdtype(mask.dtype, jnp.floating):
                raise ValueError(
                    "2-D attention masks must be bool/int key-padding "
                    "masks (True/1 = attend); pass additive float masks "
                    "as [B, 1|H, Q, Kv]")
            mask = mask.astype(jnp.bool_)[:, None, None, :]
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, NEG_INF)
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)


def attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
              causal: bool = True, sm_scale: Optional[float] = None,
              impl: str = "auto",
              mask: Optional[jax.Array] = None) -> jax.Array:
    """Public fused attention entry point (see module docstring)."""
    if impl == "auto":
        impl = "flash" if _on_tpu() else "xla"
    if impl in ("flash", "splash") and mask is not None:
        impl = "xla"       # the Pallas kernels have no padding-mask path
    if impl in ("flash", "splash"):
        heads, kv_heads = q.shape[-2], k.shape[-2]
        if kv_heads != heads:
            k = repeat_kv(k, heads // kv_heads)
            v = repeat_kv(v, heads // kv_heads)
        if impl == "flash":
            from ray_tpu.ops.flash_attention import flash_attention
            return flash_attention(q, k, v, causal=causal,
                                   sm_scale=sm_scale)
        # JAX's tuned public TPU kernel, kept as a comparison impl (the
        # in-tree flash kernel measured faster at head_dim 64 — bench.py)
        from ray_tpu.ops.splash import splash_attention
        return splash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    if impl == "xla":
        return xla_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                             mask=mask)
    raise ValueError(f"unknown attention impl: {impl!r}")
