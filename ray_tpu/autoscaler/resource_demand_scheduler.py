"""Demand→node-type binpacking (analog of
/root/reference/python/ray/autoscaler/_private/resource_demand_scheduler.py:103
``ResourceDemandScheduler.get_nodes_to_launch`` / ``_resource_based_utilization_scorer``).

Strategy: first-fit the queued demand onto the free capacity of existing
nodes; for the residual, greedily pick the node type with the best
utilization score per launch unit until the demand is covered or caps are
hit. A TPU pod-slice type scores with its whole-slice resources so a demand
for 32 chips maps to one v4-32 unit, not 8 loose hosts.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ray_tpu.autoscaler.config import AutoscalerConfig, NodeTypeConfig


def _fits(free: Dict[str, float], need: Dict[str, float]) -> bool:
    return all(free.get(r, 0.0) >= v for r, v in need.items())


def _consume(free: Dict[str, float], need: Dict[str, float]) -> None:
    for r, v in need.items():
        free[r] = free.get(r, 0.0) - v


def binpack_residual(free_caps: List[Dict[str, float]],
                     demands: List[Dict[str, float]]
                     ) -> List[Dict[str, float]]:
    """First-fit demands onto free capacities; return the unfit residual."""
    caps = [dict(c) for c in free_caps]
    residual = []
    for need in demands:
        placed = False
        for cap in caps:
            if _fits(cap, need):
                _consume(cap, need)
                placed = True
                break
        if not placed:
            residual.append(need)
    return residual


def _utilization_score(nt: NodeTypeConfig,
                       demands: List[Dict[str, float]]
                       ) -> Optional[Tuple[int, int, float]]:
    """(-wasted resource kinds, num demands that fit, mean utilization).

    Leading term steers CPU-only demand away from accelerator hosts: a type
    whose TPU/GPU would sit entirely unused scores below a plain CPU type
    (same idea as the reference's _resource_based_utilization_scorer
    matching-resource-types term). None if no demand fits.
    """
    cap = dict(nt.total_resources)
    fit = 0
    for need in demands:
        if _fits(cap, need):
            _consume(cap, need)
            fit += 1
    if fit == 0:
        return None
    total = nt.total_resources
    used_frac = [1.0 - cap[r] / total[r] for r in total if total[r] > 0]
    mean_util = sum(used_frac) / max(len(used_frac), 1)
    wasted = sum(1 for r in total if total[r] > 0 and cap[r] == total[r])
    return (-wasted, fit, mean_util)


class ResourceDemandScheduler:
    def __init__(self, config: AutoscalerConfig):
        self.config = config

    def get_nodes_to_launch(
            self,
            demands: List[Dict[str, float]],
            free_caps: List[Dict[str, float]],
            current_by_type: Dict[str, int],
    ) -> Dict[str, int]:
        """Decide launches: cover min_workers, then binpack residual demand.

        current_by_type counts non-terminated launch units per node type
        (pending launches included, so repeated calls are idempotent).
        """
        to_launch: Dict[str, int] = {}
        counts = dict(current_by_type)
        total_units = sum(counts.values())

        def can_launch(nt: NodeTypeConfig) -> bool:
            return (counts.get(nt.name, 0) < nt.max_workers
                    and total_units < self.config.max_workers)

        # 1. honor per-type min_workers
        planned_caps = list(free_caps)
        for nt in self.config.node_types.values():
            while counts.get(nt.name, 0) < nt.min_workers and \
                    total_units < self.config.max_workers:
                to_launch[nt.name] = to_launch.get(nt.name, 0) + 1
                counts[nt.name] = counts.get(nt.name, 0) + 1
                total_units += 1
                planned_caps.append(dict(nt.total_resources))

        # 2. binpack queued demand onto existing + planned capacity
        residual = binpack_residual(planned_caps, demands)

        # 3. greedily launch the best-scoring type for the residual
        while residual:
            best: Optional[Tuple[Tuple[int, float], NodeTypeConfig]] = None
            for nt in self.config.node_types.values():
                if not can_launch(nt):
                    continue
                score = _utilization_score(nt, residual)
                if score is None:
                    continue
                if best is None or score > best[0]:
                    best = (score, nt)
            if best is None:
                break  # infeasible residual (report upward, don't spin)
            nt = best[1]
            to_launch[nt.name] = to_launch.get(nt.name, 0) + 1
            counts[nt.name] = counts.get(nt.name, 0) + 1
            total_units += 1
            residual = binpack_residual([dict(nt.total_resources)], residual)
        return to_launch
