"""TPU pod-slice node provider.

The reference ships cloud providers (aws/gcp/azure,
/root/reference/python/ray/autoscaler/_private/providers.py); the TPU-native
equivalent provisions *TPU pod slices* on GCE. A slice (``v4-32`` = 4 hosts x
4 chips) is atomic: one ``create_node`` call requests the whole slice via
``gcloud compute tpus tpu-vm create --accelerator-type=...`` and every host
runs a raylet that labels itself with the slice name.

Real gcloud calls only happen when the environment has the CLI and the
provider config sets ``dry_run: false``; tests use ``dry_run: true`` which
records the calls without executing them (zero-egress environments).
"""

from __future__ import annotations

import shutil
import subprocess
import threading
from typing import Any, Dict, List

from ray_tpu.autoscaler.node_provider import NodeProvider, NodeRecord

# accelerator type -> (hosts, chips/host); the autoscaler cross-checks the
# node type's hosts_per_node against this table when it can
SLICE_TOPOLOGY = {
    "v4-8": (1, 4), "v4-16": (2, 4), "v4-32": (4, 4), "v4-64": (8, 4),
    "v5p-8": (1, 4), "v5p-16": (2, 4), "v5p-32": (4, 4),
    "v5litepod-4": (1, 4), "v5litepod-8": (2, 4),
    "v6e-4": (1, 4), "v6e-8": (2, 4), "v6e-16": (4, 4),
}


class TpuPodSliceProvider(NodeProvider):
    def __init__(self, provider_config: Dict[str, Any],
                 cluster_name: str = "default", **_):
        super().__init__(provider_config, cluster_name)
        self.project = provider_config.get("project")
        self.zone = provider_config.get("zone", "us-central2-b")
        self.dry_run = bool(provider_config.get("dry_run", True))
        self._nodes: Dict[str, NodeRecord] = {}
        self._next = 0
        self._lock = threading.Lock()
        self.calls: List[List[str]] = []  # recorded gcloud invocations

    def _gcloud(self, args: List[str]) -> None:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm"] + args + [
            "--zone", self.zone]
        if self.project:
            cmd += ["--project", self.project]
        self.calls.append(cmd)
        if self.dry_run:
            return
        if shutil.which("gcloud") is None:
            raise RuntimeError("gcloud CLI not available")
        subprocess.run(cmd, check=True, capture_output=True)

    def non_terminated_nodes(self) -> List[NodeRecord]:
        with self._lock:
            return [n for n in self._nodes.values()
                    if n.state != "terminated"]

    def create_node(self, node_type, node_config, resources, hosts,
                    labels) -> NodeRecord:
        accel = node_config.get("accelerator_type", node_type)
        topo = SLICE_TOPOLOGY.get(accel)
        if topo and topo[0] != hosts:
            raise ValueError(
                f"{accel} has {topo[0]} hosts but node type declares "
                f"hosts_per_node={hosts}")
        with self._lock:
            name = f"{self.cluster_name}-{node_type}-{self._next}"
            self._next += 1
        self._gcloud([
            "create", name, "--accelerator-type", accel,
            "--version", node_config.get("runtime_version",
                                         "tpu-ubuntu2204-base"),
        ])
        rec = NodeRecord(node_id=name, node_type=node_type,
                         state="running" if self.dry_run else "pending",
                         tags={"hosts": str(hosts), "accelerator": accel})
        with self._lock:
            self._nodes[name] = rec
        return rec

    def terminate_node(self, node_id: str) -> None:
        self._gcloud(["delete", node_id, "--quiet"])
        with self._lock:
            if node_id in self._nodes:
                self._nodes[node_id].state = "terminated"
