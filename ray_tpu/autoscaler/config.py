"""Autoscaler cluster config (analog of the reference's cluster YAML +
ray-schema.json validation, /root/reference/python/ray/autoscaler/ray-schema.json).

A config is a plain dict (or YAML file) of the shape::

    cluster_name: demo
    max_workers: 8
    idle_timeout_s: 300
    provider: {type: fake, ...}
    available_node_types:
      cpu-worker:
        resources: {CPU: 4}
        min_workers: 0
        max_workers: 8
      tpu-v4-32:
        resources: {TPU: 4, CPU: 8}   # per host
        hosts_per_node: 4             # slice = 4 hosts, atomic
        min_workers: 0
        max_workers: 2
    head_node_type: cpu-worker
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class NodeTypeConfig:
    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 2 ** 30
    # TPU pod slices: how many hosts one launched "node" expands into.
    # All hosts of a slice are created/terminated together (atomic).
    hosts_per_node: int = 1
    labels: Dict[str, str] = field(default_factory=dict)
    node_config: Dict[str, Any] = field(default_factory=dict)

    @property
    def total_resources(self) -> Dict[str, float]:
        """Aggregate resources of one launch unit (whole slice)."""
        return {r: v * self.hosts_per_node for r, v in self.resources.items()}


@dataclass
class AutoscalerConfig:
    cluster_name: str = "default"
    max_workers: int = 8
    idle_timeout_s: float = 300.0
    upscaling_speed: float = 1.0
    provider: Dict[str, Any] = field(default_factory=lambda: {"type": "fake"})
    node_types: Dict[str, NodeTypeConfig] = field(default_factory=dict)
    head_node_type: Optional[str] = None

    def validate(self) -> None:
        if self.max_workers < 0:
            raise ValueError("max_workers must be >= 0")
        for nt in self.node_types.values():
            if nt.min_workers > nt.max_workers:
                raise ValueError(
                    f"node type {nt.name}: min_workers > max_workers")
            if nt.hosts_per_node < 1:
                raise ValueError(f"node type {nt.name}: hosts_per_node < 1")
            if not nt.resources:
                raise ValueError(f"node type {nt.name}: empty resources")
        if self.head_node_type and self.head_node_type not in self.node_types:
            raise ValueError(f"unknown head_node_type {self.head_node_type}")


def load_config(source: Any) -> AutoscalerConfig:
    """Build an AutoscalerConfig from a dict or a YAML file path."""
    if isinstance(source, AutoscalerConfig):
        source.validate()
        return source
    if isinstance(source, str):
        import yaml
        with open(source) as f:
            source = yaml.safe_load(f)
    if not isinstance(source, dict):
        raise TypeError(f"config must be dict/path, got {type(source)}")
    types = {}
    for name, spec in (source.get("available_node_types") or {}).items():
        types[name] = NodeTypeConfig(
            name=name,
            resources=dict(spec.get("resources", {})),
            min_workers=int(spec.get("min_workers", 0)),
            max_workers=int(spec.get("max_workers", 2 ** 30)),
            hosts_per_node=int(spec.get("hosts_per_node", 1)),
            labels=dict(spec.get("labels", {})),
            node_config=dict(spec.get("node_config", {})),
        )
    cfg = AutoscalerConfig(
        cluster_name=source.get("cluster_name", "default"),
        max_workers=int(source.get("max_workers", 8)),
        idle_timeout_s=float(
            source.get("idle_timeout_s",
                       60.0 * source.get("idle_timeout_minutes", 5))),
        upscaling_speed=float(source.get("upscaling_speed", 1.0)),
        provider=dict(source.get("provider", {"type": "fake"})),
        node_types=types,
        head_node_type=source.get("head_node_type"),
    )
    cfg.validate()
    return cfg
