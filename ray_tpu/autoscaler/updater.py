"""NodeUpdater: bootstrap one cluster host from bare VM to running raylet.

Analog of /root/reference/python/ray/autoscaler/_private/updater.py
(``NodeUpdater.run`` → wait-ready → rsync file mounts → initialization /
setup / start commands).  Differences by design: no rsync binary
dependency (file mounts copy through the CommandRunner), and the start
command may report the session dir back ("session: <path>") which the
updater records so ``ray-tpu down`` can stop exactly that session on
shared hosts (the local-provider e2e seam).
"""

from __future__ import annotations

import logging
import re
import threading
from typing import Dict, List, Optional

from ray_tpu.autoscaler.command_runner import CommandRunnerInterface

logger = logging.getLogger(__name__)


class NodeUpdaterError(RuntimeError):
    pass


class NodeUpdater:
    def __init__(self, node_id: str, runner: CommandRunnerInterface, *,
                 file_mounts: Optional[Dict[str, str]] = None,
                 initialization_commands: Optional[List[str]] = None,
                 setup_commands: Optional[List[str]] = None,
                 start_commands: Optional[List[str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 ready_command: Optional[str] = None,
                 ready_timeout: float = 300.0):
        self.node_id = node_id
        self.runner = runner
        self.file_mounts = dict(file_mounts or {})
        self.initialization_commands = list(initialization_commands or [])
        self.setup_commands = list(setup_commands or [])
        self.start_commands = list(start_commands or [])
        self.env = dict(env or {})
        self.ready_command = ready_command
        self.ready_timeout = ready_timeout
        self.status = "pending"     # pending|waiting-ready|syncing|
        #                             setting-up|starting|up-to-date|failed
        self.error: Optional[str] = None
        self.session_dir: Optional[str] = None   # parsed from start output
        self.output: List[str] = []
        self._thread: Optional[threading.Thread] = None

    # -- phases ------------------------------------------------------------
    def _wait_ready(self) -> None:
        """Until the node answers a trivial command (VM boot / sshd up)."""
        import time
        self.status = "waiting-ready"
        cmd = self.ready_command or "uptime"
        deadline = time.monotonic() + self.ready_timeout
        last = ""
        while time.monotonic() < deadline:
            rc, out = self.runner.run(cmd, timeout=30.0)
            if rc == 0:
                return
            last = out
            time.sleep(2.0)
        raise NodeUpdaterError(
            f"node {self.node_id} never became reachable: {last}")

    def _sync_files(self) -> None:
        self.status = "syncing"
        for remote, local in self.file_mounts.items():
            self.runner.put_file(local, remote)

    def _run_commands(self, commands: List[str], phase: str) -> None:
        self.status = phase
        for cmd in commands:
            rc, out = self.runner.run(cmd, env=self.env)
            self.output.append(out)
            if rc != 0:
                raise NodeUpdaterError(
                    f"node {self.node_id} {phase} command failed "
                    f"(rc={rc}): {cmd}\n{out[-2000:]}")
            m = re.search(r"session: (\S+)", out)
            if m:
                self.session_dir = m.group(1).rstrip(")")

    def update(self) -> None:
        try:
            self._wait_ready()
            self._sync_files()
            self._run_commands(self.initialization_commands, "initializing")
            self._run_commands(self.setup_commands, "setting-up")
            self._run_commands(self.start_commands, "starting")
            self.status = "up-to-date"
        except Exception as e:
            self.status = "failed"
            self.error = str(e)
            logger.error("updater for %s failed: %s", self.node_id, e)
            raise

    # -- threading (reference updaters run as one thread per node) ---------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._update_quiet,
                                        daemon=True)
        self._thread.start()

    def _update_quiet(self) -> None:
        try:
            self.update()
        except Exception:
            pass  # status/error carry the outcome

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)
