"""Cluster launcher: ``ray-tpu up/down/attach/exec/submit`` from a YAML.

Analog of /root/reference/python/ray/scripts/scripts.py:1161 (``ray up``),
autoscaler/_private/commands.py (create_or_update_cluster / teardown /
exec / attach / rsync) and the ray-schema.json cluster YAML.  The
operator story it completes: the TpuPodSliceProvider can create slices,
this module installs and starts raylets on them.

Layout of a cluster YAML (see examples/cluster.yaml):

    cluster_name: demo
    provider: {type: local|tpu|fake, zone: ..., project: ..., dry_run: ...}
    auth: {ssh_user: ..., ssh_private_key: ...}
    available_node_types:
      head: {resources: {CPU: 4}, hosts_per_node: 1,
             min_workers: 0, max_workers: 0}
      v4_32: {node_config: {accelerator_type: v4-32},
              resources: {CPU: 8, TPU: 4}, hosts_per_node: 4,
              min_workers: 1, max_workers: 4}
    head_node_type: head
    file_mounts: {remote_path: local_path}
    initialization_commands: [...]
    setup_commands: [...]            # + head_/worker_ variants
    head_start_ray_commands: ["... start --head --port={port}"]
    worker_start_ray_commands: ["... start --address={head_address}"]

Cross-invocation state (which nodes exist, the head address, per-node
session dirs) persists in ``~/.ray_tpu/clusters/<name>.json`` (override
dir via RAY_TPU_CLUSTER_STATE_DIR) so ``down``/``exec``/``submit`` work
from a fresh process, the same way the reference keeps cluster state
under ``~/.ray``.
"""

from __future__ import annotations

import copy
import json
import os
import socket
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.autoscaler.command_runner import (CommandRunnerInterface,
                                               LocalCommandRunner,
                                               SSHCommandRunner,
                                               TpuVmCommandRunner)
from ray_tpu.autoscaler.node_provider import get_node_provider
from ray_tpu.autoscaler.updater import NodeUpdater

DEFAULT_HEAD_PORT = 6380


class ClusterConfigError(ValueError):
    pass


# ----------------------------------------------------------------- config
_TOP_DEFAULTS: Dict[str, Any] = {
    "max_workers": 8,
    "auth": {},
    "file_mounts": {},
    "initialization_commands": [],
    "setup_commands": [],
    "head_setup_commands": [],
    "worker_setup_commands": [],
    "head_start_ray_commands": [],
    "worker_start_ray_commands": [],
    "stop_ray_commands": [],
    "env": {},     # exported into every launcher-run command on every node
    "python": "python3",   # interpreter on REMOTE nodes (local uses sys.executable)
}
_NODE_TYPE_DEFAULTS: Dict[str, Any] = {
    "node_config": {},
    "resources": {},
    "hosts_per_node": 1,
    "min_workers": 0,
    "max_workers": 1,
}


def load_cluster_config(path: str) -> Dict[str, Any]:
    import yaml
    with open(path) as f:
        cfg = yaml.safe_load(f)
    return validate_cluster_config(cfg)


def validate_cluster_config(cfg: Dict[str, Any]) -> Dict[str, Any]:
    """Schema check + defaults (reference ray-schema.json / prepare_config).
    Raises ClusterConfigError with a field-level message on problems."""
    if not isinstance(cfg, dict):
        raise ClusterConfigError("cluster config must be a mapping")
    cfg = copy.deepcopy(cfg)
    for field in ("cluster_name", "provider", "available_node_types",
                  "head_node_type"):
        if field not in cfg:
            raise ClusterConfigError(f"missing required field {field!r}")
    if not isinstance(cfg["provider"], dict) or "type" not in cfg["provider"]:
        raise ClusterConfigError("provider must be a mapping with a 'type'")
    for k, v in _TOP_DEFAULTS.items():
        cfg.setdefault(k, copy.deepcopy(v))
    types = cfg["available_node_types"]
    if not isinstance(types, dict) or not types:
        raise ClusterConfigError("available_node_types must be a non-empty "
                                 "mapping")
    for name, nt in types.items():
        if not isinstance(nt, dict):
            raise ClusterConfigError(f"node type {name!r} must be a mapping")
        for k, v in _NODE_TYPE_DEFAULTS.items():
            nt.setdefault(k, copy.deepcopy(v))
        if nt["min_workers"] > nt["max_workers"]:
            raise ClusterConfigError(
                f"node type {name!r}: min_workers > max_workers")
    head = cfg["head_node_type"]
    if head not in types:
        raise ClusterConfigError(
            f"head_node_type {head!r} not in available_node_types "
            f"({sorted(types)})")
    unknown_cmds = [k for k in cfg if k.endswith("_commands")
                    and k not in _TOP_DEFAULTS]
    if unknown_cmds:
        raise ClusterConfigError(f"unknown command sections: {unknown_cmds}")
    return cfg


# ------------------------------------------------------------ local state
def _state_dir() -> str:
    d = os.environ.get("RAY_TPU_CLUSTER_STATE_DIR") or \
        os.path.expanduser("~/.ray_tpu/clusters")
    os.makedirs(d, exist_ok=True)
    return d


def _state_path(cluster_name: str) -> str:
    return os.path.join(_state_dir(), f"{cluster_name}.json")


def load_cluster_state(cluster_name: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_state_path(cluster_name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _save_cluster_state(state: Dict[str, Any]) -> None:
    path = _state_path(state["cluster_name"])
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=1)
    os.replace(tmp, path)


def _delete_cluster_state(cluster_name: str) -> None:
    try:
        os.unlink(_state_path(cluster_name))
    except FileNotFoundError:
        pass


# ---------------------------------------------------------------- runners
def _make_runner(cfg: Dict[str, Any], node: Dict[str, Any],
                 worker_index: int = 0, *,
                 dry_run: bool = False) -> CommandRunnerInterface:
    """Runner for host ``worker_index`` of one launch unit."""
    ptype = cfg["provider"]["type"]
    dry = dry_run or bool(cfg["provider"].get("dry_run"))
    if ptype in ("tpu", "gce-tpu"):
        return TpuVmCommandRunner(
            node["node_id"], worker_index,
            zone=cfg["provider"].get("zone", "us-central2-b"),
            project=cfg["provider"].get("project"), dry_run=dry)
    ip = node.get("ip", "127.0.0.1")
    if ip in ("127.0.0.1", "localhost"):
        return LocalCommandRunner(dry_run=dry)
    auth = cfg.get("auth", {})
    return SSHCommandRunner(ip, ssh_user=auth.get("ssh_user", "ubuntu"),
                            ssh_key=auth.get("ssh_private_key"),
                            dry_run=dry)


def _fill(commands: List[str], subs: Dict[str, str]) -> List[str]:
    out = []
    for c in commands:
        for k, v in subs.items():
            c = c.replace("{" + k + "}", str(v))
        out.append(c)
    return out


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# --------------------------------------------------------------------- up
def create_or_update_cluster(config_path: str, *, dry_run: bool = False,
                             no_start_workers: bool = False,
                             _print=print) -> Dict[str, Any]:
    """``ray-tpu up``: create the head launch unit, bootstrap it, then
    bring up every node type's min_workers.  Returns the cluster state.

    ``dry_run`` forces every provider call and command into record-only
    mode and prints the plan instead of executing it."""
    cfg = load_cluster_config(config_path)
    name = cfg["cluster_name"]
    provider_cfg = dict(cfg["provider"])
    if dry_run:
        provider_cfg["dry_run"] = True
    provider = get_node_provider(provider_cfg, name)
    types = cfg["available_node_types"]
    head_type = cfg["head_node_type"]
    ht = types[head_type]

    state: Dict[str, Any] = {
        "cluster_name": name, "config_path": os.path.abspath(config_path),
        "provider": provider_cfg, "head": None, "workers": [],
        "created_at": time.time(),
    }

    # -- head ---------------------------------------------------------------
    _print(f"[{name}] launching head node ({head_type})...")
    head_rec = provider.create_node(head_type, ht["node_config"],
                                    ht["resources"], ht["hosts_per_node"],
                                    {"ray-cluster-name": name,
                                     "ray-node-kind": "head"})
    head_ip = head_rec.tags.get("ip", head_rec.node_id)
    if cfg["provider"].get("head_port"):
        port = int(cfg["provider"]["head_port"])
    elif head_ip in ("127.0.0.1", "localhost"):
        port = _free_port()   # shared machine: avoid collisions
    else:
        port = DEFAULT_HEAD_PORT
    head_address = f"{head_ip}:{port}"
    subs = {"port": port, "head_address": head_address}

    head_node = {"node_id": head_rec.node_id, "ip": head_ip,
                 "node_type": head_type, "session_dirs": []}
    runner = _make_runner(cfg, head_node, 0, dry_run=dry_run)
    upd = NodeUpdater(
        head_rec.node_id, runner,
        file_mounts=cfg["file_mounts"],
        initialization_commands=_fill(cfg["initialization_commands"], subs),
        setup_commands=_fill(cfg["setup_commands"]
                             + cfg["head_setup_commands"], subs),
        start_commands=_fill(cfg["head_start_ray_commands"], subs),
        env={**cfg["env"], "RAY_TPU_HEAD_ADDRESS": head_address})
    state["head"] = head_node
    state["head_address"] = head_address
    if not dry_run:
        # persist before bootstrapping: a failure anywhere below must
        # leave `ray-tpu down` a teardown path to the created nodes
        _save_cluster_state(state)
    try:
        upd.update()
    except Exception:
        if not dry_run:
            _save_cluster_state(state)
        raise
    if upd.session_dir:
        head_node["session_dirs"].append(upd.session_dir)
    if not dry_run:
        _save_cluster_state(state)
    runners = [(head_rec.node_id, 0, runner)]

    # -- workers ------------------------------------------------------------
    updaters: List[NodeUpdater] = []
    if not no_start_workers:
        for tname, nt in types.items():
            if tname == head_type:
                continue
            for _ in range(nt["min_workers"]):
                rec = provider.create_node(
                    tname, nt["node_config"], nt["resources"],
                    nt["hosts_per_node"],
                    {"ray-cluster-name": name, "ray-node-kind": "worker"})
                wnode = {"node_id": rec.node_id,
                         "ip": rec.tags.get("ip", rec.node_id),
                         "node_type": tname,
                         "hosts": nt["hosts_per_node"],
                         "session_dirs": []}
                for host_i in range(nt["hosts_per_node"]):
                    wrunner = _make_runner(cfg, wnode, host_i,
                                           dry_run=dry_run)
                    wupd = NodeUpdater(
                        f"{rec.node_id}#{host_i}", wrunner,
                        file_mounts=cfg["file_mounts"],
                        initialization_commands=_fill(
                            cfg["initialization_commands"], subs),
                        setup_commands=_fill(
                            cfg["setup_commands"]
                            + cfg["worker_setup_commands"], subs),
                        start_commands=_fill(
                            cfg["worker_start_ray_commands"], subs),
                        env={**cfg["env"],
                             "RAY_TPU_HEAD_ADDRESS": head_address})
                    wupd.start()   # one thread per host, like the reference
                    updaters.append(wupd)
                    runners.append((rec.node_id, host_i, wrunner))
                state["workers"].append(wnode)
                if not dry_run:
                    _save_cluster_state(state)  # nodes exist: make down work
        failed = None
        for wupd, wnode in zip(
                updaters,
                [w for w in state["workers"]
                 for _ in range(w["hosts"])]):
            wupd.join()
            if wupd.status == "failed" and failed is None:
                failed = f"worker bootstrap failed on {wupd.node_id}: " \
                         f"{wupd.error}"
            if wupd.session_dir:
                wnode["session_dirs"].append(wupd.session_dir)
        if not dry_run:
            _save_cluster_state(state)  # record every session dir started
        if failed is not None:
            raise RuntimeError(
                failed + f"\n(tear down with: ray-tpu down {config_path})")

    if dry_run:
        _print(f"[{name}] DRY RUN — planned operations:")
        for call in getattr(provider, "calls", []):
            _print("  provider: " + " ".join(call))
        for nid, host_i, r in runners:
            for call in getattr(r, "calls", []):
                _print(f"  {nid}#{host_i}: {call}")
        return state

    _save_cluster_state(state)
    _print(f"[{name}] head up at {head_address}; "
           f"{len(state['workers'])} worker launch unit(s)")
    _print(f"  attach:  ray-tpu attach {config_path}")
    _print(f"  submit:  ray-tpu submit {config_path} your_script.py")
    _print(f"  python:  ray_tpu.init(address=\"{head_address}\")")
    return state


# ------------------------------------------------------------------- down
def teardown_cluster(config_path_or_name: str, *,
                     _print=print) -> None:
    """``ray-tpu down``: stop every node's session, terminate provider
    nodes, drop the state file."""
    if os.path.exists(config_path_or_name):
        cfg = load_cluster_config(config_path_or_name)
        name = cfg["cluster_name"]
    else:
        cfg = None
        name = config_path_or_name
    state = load_cluster_state(name)
    if state is None:
        _print(f"[{name}] no recorded cluster state; nothing to tear down")
        return
    if cfg is None and state.get("config_path") and \
            os.path.exists(state["config_path"]):
        cfg = load_cluster_config(state["config_path"])
    if cfg is None:
        raise ClusterConfigError(
            f"cluster config for {name!r} not found; pass the YAML path")

    stop_cmds = cfg.get("stop_ray_commands") or []
    nodes = ([state["head"]] if state.get("head") else []) + \
        state.get("workers", [])
    for node in nodes:
        hosts = node.get("hosts", 1)
        for host_i in range(hosts):
            runner = _make_runner(cfg, node, host_i)
            cmds = list(stop_cmds)
            # stop exactly the sessions this launch created (shared-host
            # local provider: other clusters' sessions must survive)
            for sess in node.get("session_dirs", []):
                cmds.append(
                    f"{_python_for(cfg, node)} -m ray_tpu.scripts stop "
                    f"--session-dir {sess}")
            for cmd in cmds:
                rc, out = runner.run(cmd, timeout=60.0, env=cfg["env"])
                if rc != 0:
                    _print(f"  warning: stop on {node['node_id']}#{host_i} "
                           f"rc={rc}")

    provider = get_node_provider(dict(state["provider"]), name)
    for node in nodes:
        try:
            provider.terminate_node(node["node_id"])
        except Exception as e:
            _print(f"  warning: terminate {node['node_id']}: {e}")
    _delete_cluster_state(name)
    _print(f"[{name}] torn down ({len(nodes)} launch unit(s))")


# ----------------------------------------------------------- exec / attach
def _python_for(cfg: Dict[str, Any], node: Dict[str, Any]) -> str:
    """Interpreter to invoke on this node: the local runner shares our
    environment (sys.executable); remote hosts use the YAML `python` key."""
    import sys
    if cfg["provider"]["type"] not in ("tpu", "gce-tpu") and             node.get("ip", "") in ("127.0.0.1", "localhost"):
        return sys.executable
    return cfg.get("python", "python3")


def _head_runner(cfg: Dict[str, Any],
                 state: Dict[str, Any]) -> CommandRunnerInterface:
    return _make_runner(cfg, state["head"], 0)


def _require_state(config_path: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    cfg = load_cluster_config(config_path)
    state = load_cluster_state(cfg["cluster_name"])
    if state is None:
        raise RuntimeError(
            f"cluster {cfg['cluster_name']!r} is not up "
            f"(no state file); run: ray-tpu up {config_path}")
    return cfg, state


def exec_cluster(config_path: str, command: str, *,
                 _print=print) -> Tuple[int, str]:
    """``ray-tpu exec``: run a shell command on the head node with
    RAY_TPU_ADDRESS pointing at the cluster."""
    cfg, state = _require_state(config_path)
    runner = _head_runner(cfg, state)
    rc, out = runner.run(
        command, env={**cfg["env"],
                      "RAY_TPU_ADDRESS": state["head_address"]})
    if out:
        _print(out.rstrip())
    return rc, out


def attach_cluster(config_path: str, *, _print=print) -> str:
    """``ray-tpu attach``: interactive shell on the head node (prints the
    command; execs it when stdin is a tty)."""
    import sys
    cfg, state = _require_state(config_path)
    runner = _head_runner(cfg, state)
    shell = runner.remote_shell_command()
    _print(f"[{cfg['cluster_name']}] head shell: {shell}")
    if sys.stdin.isatty() and not isinstance(runner, LocalCommandRunner):
        os.execvp("sh", ["sh", "-c", shell])
    return shell


def submit_job(config_path: str, script: str,
               script_args: Optional[List[str]] = None, *,
               _print=print) -> Tuple[int, str]:
    """``ray-tpu submit``: copy a driver script to the head node and run
    it against the cluster (reference scripts.py submit)."""
    cfg, state = _require_state(config_path)
    runner = _head_runner(cfg, state)
    remote_path = f"/tmp/ray_tpu_submit_{int(time.time()*1000)}_" \
                  f"{os.path.basename(script)}"
    runner.put_file(script, remote_path)
    import shlex
    args = " ".join(shlex.quote(a) for a in (script_args or []))
    cmd = (f"{_python_for(cfg, state['head'])} "
           f"{shlex.quote(remote_path)} {args}").rstrip()
    rc, out = runner.run(
        cmd, timeout=3600.0,
        env={**cfg["env"], "RAY_TPU_ADDRESS": state["head_address"]})
    if out:
        _print(out.rstrip())
    return rc, out
