"""Batching node provider: operator-reconciled scaling (kuberay analog).

Analog of /root/reference/python/ray/autoscaler/batching_node_provider.py
(``BatchingNodeProvider``, ``ScaleRequest``) — the integration style the
reference uses for kuberay, where the autoscaler cannot create VMs
directly but instead patches one declarative *scale request* (a CRD in
k8s) that an external operator reconciles:

* reads of cluster state batch into one ``get_node_data()`` snapshot per
  autoscaler cycle;
* mutations (create_node/terminate_node) only edit an in-memory
  ``ScaleRequest``; the next cycle submits it as ONE
  ``submit_scale_request`` patch — never N API calls for N nodes.

No k8s client exists in hermetic TPU images, so the concrete backend here
is ``InProcessOperator``: a reconcile loop over the submitted spec that
stands in for the kuberay operator (and doubles as the test seam, like
the reference's fake_multinode provider does for the VM providers).  A
real k8s backend only needs get_node_data/submit_scale_request over the
RayCluster CRD.  Launch units stay slice-atomic: one worker of a TPU
pod-slice type means one whole slice.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Set

from ray_tpu.autoscaler.node_provider import NodeProvider, NodeRecord


@dataclass
class ScaleRequest:
    """One declarative scaling patch (reference ScaleRequest,
    batching_node_provider.py:26): desired worker count per type plus the
    specific workers to delete when scaling down."""
    desired_num_workers: Dict[str, int] = field(default_factory=dict)
    workers_to_delete: Set[str] = field(default_factory=set)


class BatchingNodeProvider(NodeProvider):
    """Base class batching all reads/mutations per autoscaler cycle.

    Subclasses implement ``get_node_data`` and ``submit_scale_request``.
    """

    def __init__(self, provider_config: Dict[str, Any], cluster_name: str):
        super().__init__(provider_config, cluster_name)
        self._lock = threading.Lock()
        self.scale_request = ScaleRequest()
        self._scale_change_needed = False
        self._node_data: Dict[str, NodeRecord] = {}

    # ------------------------------------------------------------- backend
    def get_node_data(self) -> Dict[str, NodeRecord]:
        raise NotImplementedError

    def submit_scale_request(self, scale_request: ScaleRequest) -> None:
        raise NotImplementedError

    # ------------------------------------------- NodeProvider surface
    def non_terminated_nodes(self) -> List[NodeRecord]:
        with self._lock:
            if self._scale_change_needed:
                # one batched patch for everything the previous cycle
                # decided, however many nodes it touched
                self.submit_scale_request(self.scale_request)
                self._scale_change_needed = False
            self._node_data = self.get_node_data()
            # rebase the request on observed state (reference semantics,
            # batching_node_provider.py:119) — but deletes the operator
            # has NOT applied yet must survive the rebase, and their
            # lame-duck nodes must not count toward desired capacity, or
            # new demand during the reconciliation window double-counts
            # them (phantom nodes -> scale thrash)
            still_deleting = {w for w in self.scale_request.workers_to_delete
                              if w in self._node_data}
            counts: Dict[str, int] = {}
            for node_id, rec in self._node_data.items():
                if node_id in still_deleting:
                    continue
                counts[rec.node_type] = counts.get(rec.node_type, 0) + 1
            self.scale_request = ScaleRequest(
                desired_num_workers=counts,
                workers_to_delete=still_deleting)
            return list(self._node_data.values())

    def create_node(self, node_type: str, node_config: Dict[str, Any],
                    resources: Dict[str, float], hosts: int,
                    labels: Dict[str, str]) -> NodeRecord:
        with self._lock:
            cur = self.scale_request.desired_num_workers.get(node_type, 0)
            self.scale_request.desired_num_workers[node_type] = cur + 1
            self._scale_change_needed = True
            # a placeholder record: the operator materializes the real
            # node asynchronously; the autoscaler sees it via the next
            # cycle's node data
            return NodeRecord(node_id=f"pending-{node_type}-{cur}",
                              node_type=node_type, state="pending",
                              tags=dict(labels))

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            rec = self._node_data.get(node_id)
            if rec is None:
                return
            n = self.scale_request.desired_num_workers.get(rec.node_type, 0)
            self.scale_request.desired_num_workers[rec.node_type] = \
                max(0, n - 1)
            self.scale_request.workers_to_delete.add(node_id)
            self._scale_change_needed = True

    @property
    def safe_to_scale(self) -> bool:
        """False while a previous delete is still being reconciled —
        scaling decisions against half-applied state double-delete
        (reference safe_to_scale, batching_node_provider.py)."""
        with self._lock:
            return not any(w in self._node_data
                           for w in self.scale_request.workers_to_delete)


class InProcessOperator:
    """Stand-in for the kuberay operator: holds the last submitted spec
    and reconciles actual nodes toward it on a background thread."""

    def __init__(self, spawn_host, reconcile_interval_s: float = 0.05):
        # spawn_host(node_type) -> NodeRecord with live raylet(s);
        # in tests this is cluster_utils.Cluster.add_node glue
        self._spawn_host = spawn_host
        self._lock = threading.Lock()
        self._spec: Dict[str, int] = {}
        self._deletes: Set[str] = set()
        self._nodes: Dict[str, NodeRecord] = {}
        self._patches = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._reconcile_loop, args=(reconcile_interval_s,),
            daemon=True)
        self._thread.start()

    # ------------------------------------------------------ operator API
    def patch(self, scale_request: ScaleRequest) -> None:
        with self._lock:
            self._patches += 1
            self._spec = dict(scale_request.desired_num_workers)
            self._deletes |= set(scale_request.workers_to_delete)

    def nodes(self) -> Dict[str, NodeRecord]:
        with self._lock:
            return dict(self._nodes)

    @property
    def patch_count(self) -> int:
        with self._lock:
            return self._patches

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    # --------------------------------------------------------- reconcile
    def _reconcile_loop(self, interval: float) -> None:
        seq = 0
        while not self._stop.wait(interval):
            with self._lock:
                deletes = [d for d in self._deletes if d in self._nodes]
                spec = dict(self._spec)
            for node_id in deletes:
                with self._lock:
                    rec = self._nodes.pop(node_id, None)
                    self._deletes.discard(node_id)
                if rec is not None and rec.tags.get("_terminate"):
                    rec.tags["_terminate"]()  # test-glue teardown hook
            with self._lock:
                counts: Dict[str, int] = {}
                for rec in self._nodes.values():
                    counts[rec.node_type] = \
                        counts.get(rec.node_type, 0) + 1
            for node_type, want in spec.items():
                have = counts.get(node_type, 0)
                for _ in range(want - have):
                    try:
                        rec = self._spawn_host(node_type)
                    except Exception:
                        break  # next tick retries
                    rec.node_id = rec.node_id or f"op-{node_type}-{seq}"
                    seq += 1
                    rec.state = "running"
                    with self._lock:
                        self._nodes[rec.node_id] = rec


class KubeRayStyleProvider(BatchingNodeProvider):
    """BatchingNodeProvider over an InProcessOperator — the complete
    kuberay integration shape minus the k8s transport."""

    def __init__(self, provider_config: Dict[str, Any], cluster_name: str):
        super().__init__(provider_config, cluster_name)
        self.operator: InProcessOperator = provider_config["operator"]

    def get_node_data(self) -> Dict[str, NodeRecord]:
        return self.operator.nodes()

    def submit_scale_request(self, scale_request: ScaleRequest) -> None:
        self.operator.patch(scale_request)

    def shutdown(self) -> None:
        self.operator.stop()


def _register() -> None:
    from ray_tpu.autoscaler.node_provider import register_node_provider
    register_node_provider(
        "kuberay", lambda cfg, name, **kw: KubeRayStyleProvider(cfg, name))


_register()
