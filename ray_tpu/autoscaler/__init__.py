"""Cluster autoscaler: demand-driven node launch/termination.

TPU-native analog of the reference autoscaler
(/root/reference/python/ray/autoscaler/_private/autoscaler.py:167
``StandardAutoscaler``): the head-side Monitor polls the GCS for per-node
availability and queued resource demand, binpacks the demand onto node
*types*, and asks a pluggable NodeProvider to launch/terminate nodes.

The TPU-specific twist (SURVEY.md §2.5): a TPU pod slice (e.g. ``v4-32``)
is an *atomic* scaling unit — all its hosts come up and go down together —
so node types may declare ``hosts_per_node > 1`` and the scheduler treats
the whole slice as one launchable unit.
"""

from ray_tpu.autoscaler.config import (AutoscalerConfig, NodeTypeConfig,
                                       load_config)
from ray_tpu.autoscaler.load_metrics import LoadMetrics
from ray_tpu.autoscaler.node_provider import (NodeProvider, NodeRecord,
                                              register_node_provider,
                                              get_node_provider)
from ray_tpu.autoscaler.resource_demand_scheduler import (
    ResourceDemandScheduler, binpack_residual)
from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.monitor import Monitor

__all__ = [
    "AutoscalerConfig", "NodeTypeConfig", "load_config", "LoadMetrics",
    "NodeProvider", "NodeRecord", "register_node_provider",
    "get_node_provider", "ResourceDemandScheduler", "binpack_residual",
    "StandardAutoscaler", "Monitor",
]
