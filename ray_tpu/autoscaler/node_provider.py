"""Pluggable node providers (analog of
/root/reference/python/ray/autoscaler/node_provider.py:13 ``NodeProvider``).

A provider owns the cloud-side lifecycle of worker nodes. One *node* here is
one launch unit: for a TPU pod-slice type it expands to ``hosts_per_node``
raylet hosts that are created and destroyed together.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


@dataclass
class NodeRecord:
    node_id: str                     # provider-side id (one launch unit)
    node_type: str
    state: str = "pending"           # pending | running | terminated
    tags: Dict[str, str] = field(default_factory=dict)
    # raylet node ids (hex) of the hosts backing this launch unit, once up
    raylet_ids: List[str] = field(default_factory=list)


class NodeProvider:
    """Abstract provider interface."""

    def __init__(self, provider_config: Dict[str, Any], cluster_name: str):
        self.provider_config = provider_config
        self.cluster_name = cluster_name

    def non_terminated_nodes(self) -> List[NodeRecord]:
        raise NotImplementedError

    def create_node(self, node_type: str, node_config: Dict[str, Any],
                    resources: Dict[str, float], hosts: int,
                    labels: Dict[str, str]) -> NodeRecord:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


_PROVIDERS: Dict[str, Callable[..., NodeProvider]] = {}


def register_node_provider(name: str,
                           factory: Callable[..., NodeProvider]) -> None:
    _PROVIDERS[name] = factory


def get_node_provider(provider_config: Dict[str, Any],
                      cluster_name: str, **kwargs) -> NodeProvider:
    ptype = provider_config.get("type", "fake")
    if ptype not in _PROVIDERS:
        # lazy-register built-ins
        if ptype == "fake":
            from ray_tpu.autoscaler.fake_provider import FakeMultiNodeProvider
            register_node_provider("fake", FakeMultiNodeProvider)
        elif ptype in ("tpu", "gce-tpu"):
            from ray_tpu.autoscaler.tpu_provider import TpuPodSliceProvider
            register_node_provider(ptype, TpuPodSliceProvider)
        else:
            raise ValueError(f"unknown node provider type: {ptype}")
    return _PROVIDERS[ptype](provider_config, cluster_name, **kwargs)


class InMemoryNodeProvider(NodeProvider):
    """Bookkeeping-only provider for unit tests: nodes are records, nothing
    is launched. ``mark_running`` simulates cloud boot completion."""

    def __init__(self, provider_config: Dict[str, Any],
                 cluster_name: str = "default"):
        super().__init__(provider_config, cluster_name)
        self._nodes: Dict[str, NodeRecord] = {}
        self._next = 0
        self._lock = threading.Lock()

    def non_terminated_nodes(self) -> List[NodeRecord]:
        with self._lock:
            return [n for n in self._nodes.values()
                    if n.state != "terminated"]

    def create_node(self, node_type, node_config, resources, hosts,
                    labels) -> NodeRecord:
        with self._lock:
            nid = f"mem-{self._next}"
            self._next += 1
            rec = NodeRecord(node_id=nid, node_type=node_type,
                             tags={"hosts": str(hosts)})
            self._nodes[nid] = rec
            return rec

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            if node_id in self._nodes:
                self._nodes[node_id].state = "terminated"

    def mark_running(self, node_id: str,
                     raylet_ids: Optional[List[str]] = None) -> None:
        with self._lock:
            rec = self._nodes[node_id]
            rec.state = "running"
            rec.raylet_ids = raylet_ids or []


class LocalNodeProvider(InMemoryNodeProvider):
    """Launch units are sessions on this machine (reference 'local'
    provider, autoscaler/_private/local/node_provider.py): the cluster
    launcher's LocalCommandRunner starts a real raylet per node via
    ``ray-tpu start``, so a laptop hosts an honest multi-daemon cluster."""

    def create_node(self, node_type, node_config, resources, hosts,
                    labels) -> NodeRecord:
        rec = super().create_node(node_type, node_config, resources,
                                  hosts, labels)
        rec.tags["ip"] = "127.0.0.1"
        rec.state = "running"
        return rec


register_node_provider("mem", InMemoryNodeProvider)
register_node_provider("local", LocalNodeProvider)
