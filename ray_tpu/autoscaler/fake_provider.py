"""Fake multi-node provider: launches real raylet subprocesses locally.

Analog of /root/reference/python/ray/autoscaler/_private/fake_multi_node/
(node_provider.py) — lets tests run the *real* autoscaler loop against
simulated nodes on one machine (SURVEY.md §4 tier 3,
test_autoscaler_fake_multinode.py). A launch unit with ``hosts`` > 1 spawns
that many raylets (a simulated pod slice) which live and die together.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List

from ray_tpu.autoscaler.node_provider import NodeProvider, NodeRecord


class FakeMultiNodeProvider(NodeProvider):
    def __init__(self, provider_config: Dict[str, Any],
                 cluster_name: str = "default", *,
                 gcs_address=None, session_dir=None):
        super().__init__(provider_config, cluster_name)
        self.gcs_address = tuple(gcs_address or
                                 provider_config["gcs_address"])
        self.session_dir = session_dir or provider_config["session_dir"]
        self.object_store_memory = int(provider_config.get(
            "object_store_memory", 64 * 1024 * 1024))
        self._nodes: Dict[str, NodeRecord] = {}
        self._procs: Dict[str, List[subprocess.Popen]] = {}
        self._next = 0
        self._lock = threading.Lock()

    def non_terminated_nodes(self) -> List[NodeRecord]:
        with self._lock:
            out = []
            for nid, rec in self._nodes.items():
                if rec.state == "terminated":
                    continue
                procs = self._procs.get(nid, [])
                if rec.state == "pending" and procs and \
                        all(p.poll() is None for p in procs):
                    # consider running once every host process is up; the
                    # raylets register themselves with the GCS on boot
                    rec.state = "running"
                if procs and any(p.poll() is not None for p in procs):
                    # a host died: the slice is gone as a unit
                    self._terminate_locked(nid)
                    continue
                out.append(rec)
            return out

    def create_node(self, node_type, node_config, resources, hosts,
                    labels) -> NodeRecord:
        from ray_tpu.runtime.node import _spawn
        with self._lock:
            nid = f"fake-{self._next}"
            self._next += 1
            procs = []
            raylet_ids = []
            for h in range(hosts):
                addr_file = (f"{self.session_dir}/autoscaled_{nid}_{h}_"
                             f"{int(time.time() * 1e6)}.json")
                node_labels = dict(labels)
                node_labels.update({
                    "autoscaler-node-id": nid,
                    "node-type": node_type,
                    "host-index": str(h),
                })
                cmd = [sys.executable, "-m", "ray_tpu.runtime.raylet",
                       "--gcs-host", self.gcs_address[0],
                       "--gcs-port", str(self.gcs_address[1]),
                       "--session-dir", self.session_dir,
                       "--address-file", addr_file,
                       "--object-store-memory",
                       str(self.object_store_memory),
                       "--resources", json.dumps(resources),
                       "--labels", json.dumps(node_labels)]
                procs.append(_spawn(cmd, self.session_dir,
                                    f"autoscaled_{nid}_{h}"))
            rec = NodeRecord(node_id=nid, node_type=node_type,
                             tags={"hosts": str(hosts)},
                             raylet_ids=raylet_ids)
            self._nodes[nid] = rec
            self._procs[nid] = procs
            return rec

    def _terminate_locked(self, node_id: str) -> None:
        rec = self._nodes.get(node_id)
        if rec is None:
            return
        rec.state = "terminated"
        for p in self._procs.pop(node_id, []):
            if p.poll() is None:
                p.terminate()

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            self._terminate_locked(node_id)

    def inject_preemption(self, node_id: str, grace_s: float = 5.0,
                          graceful: bool = True) -> list:
        """Chaos seam: simulate a spot preemption of one launch unit
        (docs/fault_tolerance.md).  With ``graceful`` the provider
        issues drain_node for every raylet of the unit (the preemption
        NOTICE) and hard-kills the host processes after ``grace_s``;
        ungraceful kills immediately.  Returns the drained raylet node
        hexes."""
        from ray_tpu.runtime.gcs import GcsClient
        drained = []
        gcs = GcsClient(self.gcs_address)
        try:
            members = [n for n in gcs.call("list_nodes", timeout=10)
                       if n.get("alive") and (n.get("labels") or {})
                       .get("autoscaler-node-id") == node_id]
            if graceful:
                for n in members:
                    try:
                        gcs.call("drain_node",
                                 {"node_id": n["node_id"],
                                  "grace_s": grace_s,
                                  "reason": "spot preemption notice"},
                                 timeout=10)
                        drained.append(n["node_id"])
                    except Exception:
                        pass
        finally:
            gcs.close()

        def _kill():
            with self._lock:
                for p in self._procs.get(node_id, []):
                    if p.poll() is None:
                        p.kill()
            # the record itself flips to terminated on the next
            # non_terminated_nodes() scan (dead host => dead slice)
        if graceful and grace_s > 0:
            t = threading.Timer(grace_s, _kill)
            t.daemon = True
            t.start()
        else:
            _kill()
        return drained

    def shutdown(self) -> None:
        with self._lock:
            for nid in list(self._nodes):
                self._terminate_locked(nid)
