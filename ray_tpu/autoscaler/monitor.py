"""Autoscaler Monitor: the head-side polling loop.

Analog of /root/reference/python/ray/autoscaler/_private/monitor.py:126 —
polls the GCS for the cluster snapshot, feeds LoadMetrics into
StandardAutoscaler.update, and publishes a status blob into the GCS KV for
``ray status`` to read.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Optional

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.config import load_config
from ray_tpu.autoscaler.load_metrics import LoadMetrics
from ray_tpu.autoscaler.node_provider import get_node_provider

STATUS_KEY = "__autoscaler_status"


class Monitor:
    def __init__(self, gcs_address, config: Any, *,
                 session_dir: Optional[str] = None,
                 poll_period_s: float = 1.0):
        from ray_tpu.runtime.gcs import GcsClient
        self.config = load_config(config)
        self.gcs = GcsClient(tuple(gcs_address), connect_retry=True)
        provider_kwargs = {}
        if self.config.provider.get("type", "fake") == "fake":
            provider_kwargs = {"gcs_address": tuple(gcs_address),
                               "session_dir": session_dir}
        self.provider = get_node_provider(self.config.provider,
                                          self.config.cluster_name,
                                          **provider_kwargs)
        self.autoscaler = StandardAutoscaler(self.config, self.provider)
        self.poll_period_s = poll_period_s
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run_once(self) -> dict:
        nodes = self.gcs.call("list_nodes")
        lm = LoadMetrics.from_gcs_snapshot(nodes)
        status = self.autoscaler.update(lm)
        status["time"] = time.time()
        try:
            self.gcs.kv_put(STATUS_KEY, json.dumps(status).encode())
        except Exception:
            pass
        return status

    def start(self) -> None:
        def loop():
            while not self._stopped.wait(self.poll_period_s):
                try:
                    self.run_once()
                except (ConnectionError, OSError):
                    return  # GCS gone; monitor dies with the head
                except Exception:  # autoscaler must never crash the head
                    import logging
                    logging.getLogger(__name__).exception(
                        "autoscaler update failed")
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()
        if self._thread:
            self._thread.join(timeout=5)
        self.provider.shutdown()
        self.gcs.close()
