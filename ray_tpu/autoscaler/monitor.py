"""Autoscaler Monitor: the head-side polling loop.

Analog of /root/reference/python/ray/autoscaler/_private/monitor.py:126 —
polls the GCS for the cluster snapshot, feeds LoadMetrics into
StandardAutoscaler.update, and publishes a status blob into the GCS KV for
``ray status`` to read.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Optional

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.config import load_config
from ray_tpu.autoscaler.load_metrics import LoadMetrics
from ray_tpu.autoscaler.node_provider import get_node_provider

STATUS_KEY = "__autoscaler_status"


class Monitor:
    def __init__(self, gcs_address, config: Any, *,
                 session_dir: Optional[str] = None,
                 poll_period_s: float = 1.0):
        from ray_tpu.runtime.gcs import GcsClient
        self.config = load_config(config)
        self.gcs = GcsClient(tuple(gcs_address), connect_retry=True)
        provider_kwargs = {}
        if self.config.provider.get("type", "fake") == "fake":
            provider_kwargs = {"gcs_address": tuple(gcs_address),
                               "session_dir": session_dir}
        self.provider = get_node_provider(self.config.provider,
                                          self.config.cluster_name,
                                          **provider_kwargs)
        self.autoscaler = StandardAutoscaler(self.config, self.provider)
        self.poll_period_s = poll_period_s
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # event-driven replacement (docs/fault_tolerance.md): launch
        # units we already replaced after a preemption event, and units
        # the autoscaler itself terminated (whose NODE_DEAD events must
        # NOT trigger a replacement — that would undo every idle
        # termination)
        self._replaced_units: set = set()
        self._self_terminated: set = set()
        # event cursor: only preemptions newer than this monitor's
        # start are actionable — a restarted monitor must not replay
        # the retained table (whose NODE_DEAD rows include units the
        # previous monitor idle-terminated) into a launch storm
        self._events_since = time.time()

    def run_once(self) -> dict:
        nodes = self.gcs.call("list_nodes")
        lm = LoadMetrics.from_gcs_snapshot(nodes)
        status = self.autoscaler.update(lm)
        self._self_terminated.update(status.get("terminated", ()))
        status["preemption_replacements"] = \
            self._consume_preemption_events(nodes)
        status["time"] = time.time()
        try:
            self.gcs.kv_put(STATUS_KEY, json.dumps(status).encode())
        except Exception:
            pass
        return status

    # ------------------------------------------- event-driven replacement
    def _consume_preemption_events(self, nodes) -> list:
        """Consume NODE_PREEMPTING/NODE_DEAD events (the event plane,
        not polling) and request a slice-atomic replacement unit
        through the provider: a preemption NOTICE launches the
        replacement while the doomed slice is still draining, so the
        replacement overlaps the grace window instead of following the
        death (docs/fault_tolerance.md)."""
        if not getattr(self.provider, "safe_to_scale", True):
            # operator-reconciled provider mid-apply (the autoscaler.py
            # gate): defer — nothing is marked replaced, so the events
            # stay actionable next tick
            return []
        try:
            events = self.gcs.call(
                "list_cluster_events",
                {"min_severity": "WARNING", "limit": 200}, timeout=5)
        except Exception:
            return []
        by_id = {n["node_id"]: n for n in nodes}
        launched = []
        for ev in events or ():
            if ev.get("type") not in ("NODE_PREEMPTING", "NODE_DEAD"):
                continue
            if ev.get("ts", 0) < self._events_since:
                continue
            node = by_id.get(ev.get("node_id"))
            if node is None:
                continue
            labels = node.get("labels") or {}
            unit = labels.get("autoscaler-node-id")
            node_type = labels.get("node-type")
            if not unit or not node_type:
                continue    # head node or externally managed
            if unit in self._replaced_units or \
                    unit in self._self_terminated:
                continue
            rec_id = self._launch_replacement(node_type)
            self._replaced_units.add(unit)   # one replacement per unit,
            # even when the launch was refused (at max_workers the
            # normal demand loop takes over; re-launching every tick
            # would stampede the provider)
            if rec_id is not None:
                launched.append(rec_id)
        return launched

    def _launch_replacement(self, node_type: str) -> Optional[str]:
        nt = self.config.node_types.get(node_type)
        if nt is None:
            return None
        live = sum(1 for rec in self.provider.non_terminated_nodes()
                   if rec.node_type == node_type)
        if live >= nt.max_workers:
            return None
        rec = self.provider.create_node(node_type, nt.node_config,
                                        nt.resources, nt.hosts_per_node,
                                        nt.labels)
        return rec.node_id

    def start(self) -> None:
        def loop():
            while not self._stopped.wait(self.poll_period_s):
                try:
                    self.run_once()
                except (ConnectionError, OSError):
                    return  # GCS gone; monitor dies with the head
                except Exception:  # autoscaler must never crash the head
                    import logging
                    logging.getLogger(__name__).exception(
                        "autoscaler update failed")
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()
        if self._thread:
            self._thread.join(timeout=5)
        self.provider.shutdown()
        self.gcs.close()
