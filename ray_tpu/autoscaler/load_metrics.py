"""LoadMetrics: the autoscaler's view of cluster utilization + demand.

Analog of /root/reference/python/ray/autoscaler/_private/load_metrics.py:65 —
but fed from our GCS ``list_nodes`` snapshot (each node carries ``available``,
``load`` demand shapes, and ``idle_s`` from its raylet heartbeats) instead of
parsed heartbeat protos.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class NodeView:
    node_id: str                       # raylet node id (hex)
    resources: Dict[str, float]
    available: Dict[str, float]
    labels: Dict[str, str]
    alive: bool
    idle_s: float


@dataclass
class LoadMetrics:
    nodes: List[NodeView] = field(default_factory=list)
    # flattened queued demand: one resource-dict per queued lease request
    pending_demand: List[Dict[str, float]] = field(default_factory=list)

    @classmethod
    def from_gcs_snapshot(cls, nodes: List[dict]) -> "LoadMetrics":
        views, demand = [], []
        for n in nodes:
            views.append(NodeView(
                node_id=n["node_id"],
                resources=dict(n.get("resources", {})),
                available=dict(n.get("available", {})),
                labels=dict(n.get("labels", {})),
                alive=bool(n.get("alive")),
                idle_s=float(n.get("idle_s", 0.0)),
            ))
            for entry in n.get("load", []):
                demand.extend([dict(entry["shape"])] * int(entry["count"]))
        return cls(nodes=views, pending_demand=demand)

    def alive_nodes(self) -> List[NodeView]:
        return [n for n in self.nodes if n.alive]

    def summary(self) -> dict:
        total: Dict[str, float] = {}
        avail: Dict[str, float] = {}
        for n in self.alive_nodes():
            for r, v in n.resources.items():
                total[r] = total.get(r, 0) + v
            for r, v in n.available.items():
                avail[r] = avail.get(r, 0) + v
        return {"total": total, "available": avail,
                "pending_demand": len(self.pending_demand),
                "num_nodes": len(self.alive_nodes())}
