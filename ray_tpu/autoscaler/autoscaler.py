"""StandardAutoscaler: one reconcile step per ``update()`` call.

Analog of /root/reference/python/ray/autoscaler/_private/autoscaler.py:167
(``StandardAutoscaler.update`` :358): terminate idle/over-cap nodes, honor
min_workers, binpack queued demand into node-type launches.
"""

from __future__ import annotations

import logging
import math
import time
from typing import Dict, List, Optional

from ray_tpu.autoscaler.config import AutoscalerConfig
from ray_tpu.autoscaler.load_metrics import LoadMetrics
from ray_tpu.autoscaler.node_provider import NodeProvider, NodeRecord
from ray_tpu.autoscaler.resource_demand_scheduler import (
    ResourceDemandScheduler)

logger = logging.getLogger(__name__)


class StandardAutoscaler:
    def __init__(self, config: AutoscalerConfig, provider: NodeProvider):
        config.validate()
        self.config = config
        self.provider = provider
        self.scheduler = ResourceDemandScheduler(config)
        self._launch_times: Dict[str, float] = {}  # provider node id -> t
        self.last_status: dict = {}

    # ------------------------------------------------------------------ util
    def _nodes_by_type(self, records: List[NodeRecord]) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for rec in records:
            counts[rec.node_type] = counts.get(rec.node_type, 0) + 1
        return counts

    def _record_for(self, records: List[NodeRecord],
                    view_labels: Dict[str, str]) -> Optional[NodeRecord]:
        nid = view_labels.get("autoscaler-node-id")
        for rec in records:
            if rec.node_id == nid:
                return rec
        return None

    # ---------------------------------------------------------------- update
    def update(self, lm: LoadMetrics) -> dict:
        records = self.provider.non_terminated_nodes()

        # operator-reconciled providers (batching/kuberay style) expose
        # safe_to_scale=False while a submitted delete is still being
        # applied: deciding against half-applied state double-counts the
        # lame-duck nodes (reference kuberay autoscaler gate)
        if not getattr(self.provider, "safe_to_scale", True):
            self.last_status = {
                "nodes": {rec.node_id: rec.node_type for rec in records},
                "launched": [], "terminated": [],
                "pending_demand": len(lm.pending_demand),
                "usage": lm.summary(),
                "waiting": "provider reconciling previous scale request",
            }
            return self.last_status

        # 1. idle termination: every host of a launch unit must be idle past
        #    the timeout (slice-atomic: one busy host keeps the slice)
        idle_by_unit: Dict[str, List[float]] = {}
        for view in lm.alive_nodes():
            rec = self._record_for(records, view.labels)
            if rec is None:
                continue  # head node or externally-managed
            idle_by_unit.setdefault(rec.node_id, []).append(view.idle_s)
        counts = self._nodes_by_type(records)
        terminated = []
        for rec in list(records):
            idles = idle_by_unit.get(rec.node_id)
            if rec.state != "running" or not idles:
                continue
            nt = self.config.node_types.get(rec.node_type)
            if nt and counts.get(rec.node_type, 0) <= nt.min_workers:
                continue
            if min(idles) > self.config.idle_timeout_s:
                logger.info("terminating idle node %s (%s)", rec.node_id,
                            rec.node_type)
                self.provider.terminate_node(rec.node_id)
                counts[rec.node_type] -= 1
                records.remove(rec)
                terminated.append(rec.node_id)

        # 2. launches: free capacity = available of alive autoscaled nodes +
        #    head; launch units for min_workers + residual queued demand.
        #    Nodes terminated in step 1 must not absorb demand (lm was
        #    snapshotted before the termination).
        gone = set(terminated)
        free_caps = [dict(v.available) for v in lm.alive_nodes()
                     if v.labels.get("autoscaler-node-id") not in gone]
        # in-flight launches (units not yet registered with the GCS) count
        # with their full capacity so repeated updates are idempotent
        registered = set(idle_by_unit)
        for rec in records:
            if rec.node_id not in registered:
                nt = self.config.node_types.get(rec.node_type)
                if nt is not None:
                    free_caps.append(dict(nt.total_resources))
        to_launch = self.scheduler.get_nodes_to_launch(
            [dict(d) for d in lm.pending_demand], free_caps,
            self._nodes_by_type(records))
        # upscaling_speed bounds launches per tick as a multiple of the
        # current cluster size (reference autoscaler semantics): at least 1,
        # so a cold cluster can always start
        num_pending = sum(1 for r in records if r.state == "pending")
        allowance = max(1, math.ceil(
            self.config.upscaling_speed * max(1, len(records)))) - num_pending
        launched = []
        for type_name, count in to_launch.items():
            nt = self.config.node_types[type_name]
            for _ in range(count):
                if allowance <= 0:
                    break
                rec = self.provider.create_node(
                    type_name, nt.node_config, nt.resources,
                    nt.hosts_per_node, nt.labels)
                self._launch_times[rec.node_id] = time.time()
                launched.append(rec.node_id)
                allowance -= 1

        self.last_status = {
            "nodes": {rec.node_id: rec.node_type for rec in records},
            "launched": launched,
            "terminated": terminated,
            "pending_demand": len(lm.pending_demand),
            "usage": lm.summary(),
        }
        return self.last_status
