"""Command runners: how the cluster launcher executes commands on nodes.

Analog of /root/reference/python/ray/autoscaler/command_runner.py:7
(``CommandRunnerInterface``) and _private/command_runner.py:159
(``SSHCommandRunner``).  TPU-native addition: ``TpuVmCommandRunner`` drives
``gcloud compute tpus tpu-vm ssh --worker=N`` — a pod slice is N hosts
behind one instance name, so one launch unit fans out to per-worker
runners rather than per-IP SSH sessions.

Every runner supports ``dry_run``: commands are recorded (and printed via
``plan()``) instead of executed, which is both the zero-egress test seam
and the ``ray-tpu up --dry-run`` plan printer.
"""

from __future__ import annotations

import shlex
import shutil
import subprocess
from typing import Dict, List, Optional, Tuple


class CommandRunnerInterface:
    """Run shell commands / copy files on one cluster host."""

    def run(self, cmd: str, *, timeout: float = 300.0,
            env: Optional[Dict[str, str]] = None) -> Tuple[int, str]:
        """-> (returncode, combined output)."""
        raise NotImplementedError

    def put_file(self, local_path: str, remote_path: str) -> None:
        raise NotImplementedError

    def remote_shell_command(self) -> str:
        """The interactive shell invocation `ray-tpu attach` should exec."""
        raise NotImplementedError


class LocalCommandRunner(CommandRunnerInterface):
    """Runs on this host (reference LocalProvider path); the e2e seam for
    launcher tests — 'nodes' are sessions on the local machine."""

    def __init__(self, *, dry_run: bool = False,
                 log_prefix: str = ""):
        self.dry_run = dry_run
        self.log_prefix = log_prefix
        self.calls: List[str] = []

    def run(self, cmd: str, *, timeout: float = 300.0,
            env: Optional[Dict[str, str]] = None) -> Tuple[int, str]:
        self.calls.append(cmd)
        if self.dry_run:
            return 0, ""
        import os
        full_env = dict(os.environ)
        if env:
            full_env.update(env)
        try:
            proc = subprocess.run(
                ["bash", "-lc", cmd], capture_output=True, text=True,
                timeout=timeout, env=full_env)
        except subprocess.TimeoutExpired as e:
            return 124, (e.output or "") + f"\n[timeout after {timeout}s]"
        return proc.returncode, (proc.stdout or "") + (proc.stderr or "")

    def put_file(self, local_path: str, remote_path: str) -> None:
        self.calls.append(f"cp {local_path} {remote_path}")
        if self.dry_run:
            return
        import os
        os.makedirs(os.path.dirname(remote_path) or ".", exist_ok=True)
        shutil.copyfile(local_path, remote_path)

    def remote_shell_command(self) -> str:
        return "bash"


class SSHCommandRunner(CommandRunnerInterface):
    """Plain SSH to one IP (reference SSHCommandRunner,
    _private/command_runner.py:159): StrictHostKeyChecking off,
    ControlMaster reuse left to the user's ssh config."""

    def __init__(self, node_ip: str, ssh_user: str = "ubuntu",
                 ssh_key: Optional[str] = None, *, dry_run: bool = False):
        self.node_ip = node_ip
        self.ssh_user = ssh_user
        self.ssh_key = ssh_key
        self.dry_run = dry_run
        self.calls: List[str] = []

    def _base(self, interactive: bool = False) -> List[str]:
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no",
               "-o", "UserKnownHostsFile=/dev/null",
               "-o", "LogLevel=ERROR"]
        if interactive:
            cmd.append("-tt")
        if self.ssh_key:
            cmd += ["-i", self.ssh_key]
        cmd.append(f"{self.ssh_user}@{self.node_ip}")
        return cmd

    def run(self, cmd: str, *, timeout: float = 300.0,
            env: Optional[Dict[str, str]] = None) -> Tuple[int, str]:
        if env:
            exports = " ".join(f"{k}={shlex.quote(v)}"
                               for k, v in env.items())
            cmd = f"export {exports}; {cmd}"
        full = self._base() + [cmd]
        self.calls.append(shlex.join(full))
        if self.dry_run:
            return 0, ""
        try:
            proc = subprocess.run(full, capture_output=True, text=True,
                                  timeout=timeout)
        except subprocess.TimeoutExpired as e:
            return 124, (e.output or "") + f"\n[timeout after {timeout}s]"
        return proc.returncode, (proc.stdout or "") + (proc.stderr or "")

    def put_file(self, local_path: str, remote_path: str) -> None:
        scp = ["scp", "-o", "StrictHostKeyChecking=no",
               "-o", "UserKnownHostsFile=/dev/null", "-o", "LogLevel=ERROR"]
        if self.ssh_key:
            scp += ["-i", self.ssh_key]
        scp += [local_path, f"{self.ssh_user}@{self.node_ip}:{remote_path}"]
        self.calls.append(shlex.join(scp))
        if self.dry_run:
            return
        subprocess.run(scp, check=True, capture_output=True)

    def remote_shell_command(self) -> str:
        return shlex.join(self._base(interactive=True))


class TpuVmCommandRunner(CommandRunnerInterface):
    """``gcloud compute tpus tpu-vm ssh <instance> --worker=N`` — the only
    supported path onto TPU pod-slice hosts (no raw IPs; gcloud tunnels
    IAP/OS-login).  One runner per (slice instance, worker index)."""

    def __init__(self, instance: str, worker: int, *, zone: str,
                 project: Optional[str] = None, dry_run: bool = True):
        self.instance = instance
        self.worker = worker
        self.zone = zone
        self.project = project
        self.dry_run = dry_run
        self.calls: List[str] = []

    def _gcloud(self, verb: str, extra: List[str]) -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", verb, self.instance,
               f"--worker={self.worker}", f"--zone={self.zone}"]
        if self.project:
            cmd.append(f"--project={self.project}")
        return cmd + extra

    def run(self, cmd: str, *, timeout: float = 300.0,
            env: Optional[Dict[str, str]] = None) -> Tuple[int, str]:
        if env:
            exports = " ".join(f"{k}={shlex.quote(v)}"
                               for k, v in env.items())
            cmd = f"export {exports}; {cmd}"
        full = self._gcloud("ssh", [f"--command={cmd}"])
        self.calls.append(shlex.join(full))
        if self.dry_run:
            return 0, ""
        if shutil.which("gcloud") is None:
            raise RuntimeError("gcloud CLI not available")
        try:
            proc = subprocess.run(full, capture_output=True, text=True,
                                  timeout=timeout)
        except subprocess.TimeoutExpired as e:
            return 124, (e.output or "") + f"\n[timeout after {timeout}s]"
        return proc.returncode, (proc.stdout or "") + (proc.stderr or "")

    def put_file(self, local_path: str, remote_path: str) -> None:
        full = self._gcloud("scp", [local_path,
                                    f"{self.instance}:{remote_path}"])
        self.calls.append(shlex.join(full))
        if self.dry_run:
            return
        subprocess.run(full, check=True, capture_output=True)

    def remote_shell_command(self) -> str:
        return shlex.join(self._gcloud("ssh", []))
