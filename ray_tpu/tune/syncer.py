"""Experiment/checkpoint sync between the local staging dir and a storage URI.

Analog of /root/reference/python/ray/tune/syncer.py:185 (``Syncer`` with
``sync_up``/``sync_down``/``sync_period`` throttling) over the pluggable
storage seam (``ray_tpu/_private/storage.py``) instead of pyarrow
filesystems: ``RunConfig(storage_path="mock://...")`` stages the experiment
locally and mirrors it under the URI; ``Tuner.restore(uri)`` downloads the
mirror and resumes.

Uploads mirror the whole experiment directory; experiments here are
checkpoint+JSON sized (the heavy model state lives in orbax shards the
trainer manages), so rsync-style deltas are not worth the bookkeeping.
"""
from __future__ import annotations

import time
from typing import Optional

from ray_tpu._private import storage


class Syncer:
    def __init__(self, local_dir: str, remote_uri: str,
                 sync_period: float = 5.0):
        self.local_dir = local_dir
        self.remote_uri = remote_uri
        self.sync_period = sync_period
        self._last_sync = 0.0

    def sync_up(self, force: bool = False) -> bool:
        """Throttled mirror of the experiment dir to the URI; ``force``
        bypasses the period (used at experiment end)."""
        now = time.monotonic()
        if not force and now - self._last_sync < self.sync_period:
            return False
        storage.upload_dir(self.local_dir, self.remote_uri)
        self._last_sync = now
        return True

    def sync_down(self) -> int:
        return storage.download_dir(self.remote_uri, self.local_dir)


def resolve_storage(storage_path: str, name: str,
                    staging_root: str) -> tuple:
    """-> (local experiment dir, remote URI or None). A URI storage_path
    stages locally and syncs; a plain path is used directly. A fresh run
    starts from a clean staging dir — leftovers from a previous same-named
    run would otherwise be mirrored into the new experiment's URI."""
    import os
    import shutil
    if storage.is_uri(storage_path):
        local = os.path.join(staging_root, name)
        shutil.rmtree(local, ignore_errors=True)
        return local, storage.join_uri(storage_path, name)
    return os.path.join(storage_path, name), None
