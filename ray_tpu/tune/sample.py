"""Search-space primitives: the ``tune.uniform``/``grid_search`` vocabulary.

Analog of /root/reference/python/ray/tune/search/sample.py (Domain classes)
and variant_generator's grid handling.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float, base: float = 10.0):
        import math
        if low <= 0 or high <= 0:
            raise ValueError("loguniform bounds must be positive")
        self.low, self.high, self.base = low, high, base
        self._log = (math.log(low, base), math.log(high, base))

    def sample(self, rng):
        return self.base ** rng.uniform(*self._log)


class QUniform(Domain):
    def __init__(self, low: float, high: float, q: float):
        self.low, self.high, self.q = low, high, q

    def sample(self, rng):
        v = rng.uniform(self.low, self.high)
        return round(round(v / self.q) * self.q, 10)


class Randint(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class RandN(Domain):
    def __init__(self, mean: float = 0.0, sd: float = 1.0):
        self.mean, self.sd = mean, sd

    def sample(self, rng):
        return rng.gauss(self.mean, self.sd)


class SampleFrom(Domain):
    """Defer to a callable of the (partially resolved) config."""

    def __init__(self, fn: Callable[[Dict[str, Any]], Any]):
        self.fn = fn


class GridSearch:
    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


# -- public constructors (ray.tune parity names) ----------------------------

def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float, base: float = 10.0) -> LogUniform:
    return LogUniform(low, high, base)


def quniform(low: float, high: float, q: float) -> QUniform:
    return QUniform(low, high, q)


def randint(low: int, high: int) -> Randint:
    return Randint(low, high)


def randn(mean: float = 0.0, sd: float = 1.0) -> RandN:
    return RandN(mean, sd)


def choice(categories: Sequence[Any]) -> Choice:
    return Choice(categories)


def sample_from(fn: Callable[[Dict[str, Any]], Any]) -> SampleFrom:
    return SampleFrom(fn)


def grid_search(values: Sequence[Any]) -> Dict[str, List[Any]]:
    return {"grid_search": list(values)}


def _is_grid(v: Any) -> bool:
    return (isinstance(v, GridSearch)
            or (isinstance(v, dict) and set(v.keys()) == {"grid_search"}))


def _grid_values(v: Any) -> List[Any]:
    return v.values if isinstance(v, GridSearch) else list(v["grid_search"])


def generate_variants(space: Dict[str, Any],
                      rng: Optional[random.Random] = None,
                      num_samples: int = 1) -> List[Dict[str, Any]]:
    """Expand grid axes (cartesian product) × num_samples random draws.

    Nested dicts are traversed; Domain leaves are sampled per variant;
    SampleFrom leaves resolve last against the flat config.
    """
    rng = rng or random.Random()

    grid_paths: List[Any] = []

    def collect(prefix, node):
        for k, v in node.items():
            path = prefix + (k,)
            if _is_grid(v):
                grid_paths.append((path, _grid_values(v)))
            elif isinstance(v, dict) and not _is_grid(v):
                collect(path, v)

    collect((), space)

    import itertools
    grid_combos = [()]
    if grid_paths:
        grid_combos = list(itertools.product(
            *[[(p, val) for val in vals] for p, vals in grid_paths]))

    def resolve(node, assignments, config_root):
        out = {}
        deferred = []
        for k, v in node.items():
            if _is_grid(v):
                out[k] = assignments[id(node)][k]
            elif isinstance(v, dict):
                out[k] = resolve(v, assignments, config_root)
            elif isinstance(v, Domain) and not isinstance(v, SampleFrom):
                out[k] = v.sample(rng)
            elif isinstance(v, SampleFrom):
                deferred.append((k, v))
            else:
                out[k] = v
        for k, v in deferred:
            out[k] = v.fn(out)
        return out

    variants = []
    for _ in range(num_samples):
        for combo in grid_combos:
            # map node-path assignments for this combo
            assign: Dict[str, Any] = {}

            def set_path(root, path, value):
                node = root
                for p in path[:-1]:
                    node = node[p]
                return node, path[-1], value

            # build an assignment lookup keyed by node identity
            per_node: Dict[int, Dict[str, Any]] = {}
            for path, value in combo:
                node = space
                for p in path[:-1]:
                    node = node[p]
                per_node.setdefault(id(node), {})[path[-1]] = value
            variants.append(resolve(space, per_node, space))
    return variants
