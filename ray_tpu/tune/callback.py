"""Tune callbacks: experiment-loop hooks + logger callbacks.

Analog of /root/reference/python/ray/tune/callback.py (Callback) and
tune/logger/ (JsonLoggerCallback json.py, CSVLoggerCallback csv.py,
TBXLoggerCallback tensorboardx.py — gated here on tensorboardX being
installed). Instances go in ``RunConfig(callbacks=[...])``; the
TrialRunner invokes every hook.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, List, Optional


class Callback:
    def on_trial_start(self, iteration: int, trials: List[Any],
                       trial: Any) -> None:
        pass

    def on_trial_result(self, iteration: int, trials: List[Any],
                        trial: Any, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, iteration: int, trials: List[Any],
                          trial: Any) -> None:
        pass

    def on_trial_error(self, iteration: int, trials: List[Any],
                       trial: Any) -> None:
        pass

    def on_experiment_end(self, trials: List[Any]) -> None:
        pass


class JsonLoggerCallback(Callback):
    """Per-trial newline-JSON result logs (reference tune/logger/json.py
    writes the same ``result.json`` convention the runner itself keeps;
    this callback lets users direct a second copy elsewhere)."""

    def __init__(self, dirpath: Optional[str] = None,
                 filename: str = "results.json"):
        self.dirpath = dirpath
        self.filename = filename

    def _path(self, trial) -> str:
        base = self.dirpath or trial.logdir
        os.makedirs(base, exist_ok=True)
        return os.path.join(base, f"{trial.trial_id}_{self.filename}" if
                            self.dirpath else self.filename)

    def on_trial_result(self, iteration, trials, trial, result):
        with open(self._path(trial), "a") as f:
            f.write(json.dumps(result, default=str) + "\n")


class CSVLoggerCallback(Callback):
    """Per-trial CSV progress (reference tune/logger/csv.py)."""

    def __init__(self, filename: str = "progress.csv"):
        self.filename = filename
        self._fields: Dict[str, List[str]] = {}

    def on_trial_result(self, iteration, trials, trial, result):
        flat = {k: v for k, v in result.items()
                if isinstance(v, (int, float, str, bool))}
        path = os.path.join(trial.logdir, self.filename)
        if trial.trial_id not in self._fields:
            self._fields[trial.trial_id] = sorted(flat.keys())
            with open(path, "w", newline="") as f:
                csv.DictWriter(f, self._fields[trial.trial_id]).writeheader()
        with open(path, "a", newline="") as f:
            csv.DictWriter(f, self._fields[trial.trial_id],
                           extrasaction="ignore").writerow(flat)


class TBXLoggerCallback(Callback):
    """TensorBoard scalars via tensorboardX when available (reference
    tune/logger/tensorboardx.py); silently inert otherwise (the image has
    no tensorboardX — documented gating, not a stub crash)."""

    def __init__(self):
        try:
            from tensorboardX import SummaryWriter
            self._writer_cls = SummaryWriter
        except ImportError:
            self._writer_cls = None
        self._writers: Dict[str, Any] = {}

    @property
    def available(self) -> bool:
        return self._writer_cls is not None

    def on_trial_result(self, iteration, trials, trial, result):
        if self._writer_cls is None:
            return
        w = self._writers.get(trial.trial_id)
        if w is None:
            w = self._writer_cls(logdir=trial.logdir)
            self._writers[trial.trial_id] = w
        step = result.get("training_iteration", iteration)
        for k, v in result.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                w.add_scalar(k, v, step)

    def on_trial_complete(self, iteration, trials, trial):
        w = self._writers.pop(trial.trial_id, None)
        if w is not None:
            w.close()

    def on_experiment_end(self, trials):
        for w in self._writers.values():
            w.close()
        self._writers.clear()
