"""Tuner + TrialRunner: the experiment event loop.

Analog of /root/reference/python/ray/tune/tuner.py:249 (Tuner.fit) and
tune/execution/trial_runner.py:320/962 (TrialRunner.step): trials run as
actors (the Train worker actor doubles as the function-trainable runner),
the runner polls results, consults the scheduler (ASHA/PBT/median) for
stop/exploit decisions and the searcher for new configs, and persists
per-trial JSONL + experiment CSV.
"""

from __future__ import annotations

import csv
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig
from ray_tpu.air.result import Result
from ray_tpu.tune.sample import generate_variants  # noqa: F401
from ray_tpu.tune.schedulers import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search import (BasicVariantGenerator, ConcurrencyLimiter,
                                 Searcher)
from ray_tpu.tune.trial import (ERROR, PAUSED, PENDING, RUNNING, TERMINATED,
                                Trial)


class TuneError(RuntimeError):
    pass


class TuneConfig:
    def __init__(self, *, metric: Optional[str] = None, mode: str = "max",
                 num_samples: int = 1,
                 max_concurrent_trials: Optional[int] = None,
                 search_alg: Optional[Searcher] = None,
                 scheduler: Optional[TrialScheduler] = None,
                 trial_resources: Optional[Dict[str, float]] = None,
                 seed: Optional[int] = None):
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode
        self.num_samples = num_samples
        self.max_concurrent_trials = max_concurrent_trials
        self.search_alg = search_alg
        self.scheduler = scheduler
        self.trial_resources = trial_resources
        self.seed = seed


class ResultGrid:
    def __init__(self, results: List[Result], trials: List[Trial],
                 metric: Optional[str], mode: str):
        self._results = results
        self._trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    @property
    def errors(self) -> List[Exception]:
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise TuneError("no metric given to get_best_result")
        scored = [r for r in self._results if metric in (r.metrics or {})]
        if not scored:
            raise TuneError(f"no trial reported metric {metric!r}")
        return (max if mode == "max" else min)(
            scored, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        try:
            import pandas as pd
        except ImportError:
            return None
        return pd.DataFrame([r.metrics for r in self._results])


class TrialRunner:
    """Drives all trials of one experiment to completion."""

    def __init__(self, trainable: Callable, param_space: Dict[str, Any],
                 tune_config: TuneConfig, run_config: RunConfig,
                 restore_path: Optional[str] = None,
                 resume_errored: bool = False):
        import tempfile
        import ray_tpu
        from ray_tpu.tune.syncer import Syncer, resolve_storage
        self.trainable = trainable
        self.tune_config = tune_config
        self.run_config = run_config
        self._resume_errored = resume_errored
        staging = os.path.join(tempfile.gettempdir(), "ray_tpu_tune_staging")
        if restore_path is not None:
            self._init_restore(restore_path, staging)
        else:
            name = run_config.name \
                or f"tune_{time.strftime('%Y%m%d_%H%M%S')}"
            self.experiment_dir, self._sync_uri = resolve_storage(
                run_config.storage_path, name, staging)
        os.makedirs(self.experiment_dir, exist_ok=True)
        self._syncer = Syncer(self.experiment_dir, self._sync_uri) \
            if self._sync_uri else None

        self.searcher = tune_config.search_alg or BasicVariantGenerator(
            param_space, num_samples=tune_config.num_samples,
            seed=tune_config.seed)
        self.searcher.set_search_properties(
            tune_config.metric, tune_config.mode, param_space)
        self.scheduler = tune_config.scheduler or FIFOScheduler()
        self.scheduler.set_search_properties(
            tune_config.metric, tune_config.mode)

        if isinstance(self.searcher, BasicVariantGenerator):
            self._target_trials = self.searcher.total_trials
        else:
            self._target_trials = tune_config.num_samples
        self.trials: List[Trial] = []
        self._suggest_exhausted = False

        if tune_config.max_concurrent_trials:
            self.max_concurrent = tune_config.max_concurrent_trials
        else:
            try:
                cpus = ray_tpu.cluster_resources().get("CPU", 2.0)
            except Exception:
                cpus = 2.0
            per_trial = (tune_config.trial_resources or {}).get("CPU", 1.0)
            self.max_concurrent = max(1, int(cpus // max(per_trial, 0.5)))

        self._csv_path = os.path.join(self.experiment_dir, "progress.csv")
        self._csv_fields: Optional[List[str]] = None
        self.callbacks = list(run_config.callbacks or [])
        self._iteration = 0
        if restore_path is not None:
            self._apply_restore_state()

    # -- experiment persistence / restore ---------------------------------
    # (reference tune resume: experiment_state-*.json written by the
    # TrialRunner checkpointer, trial_runner.py:962 checkpoint(); here one
    # experiment_state.json + per-trial checkpoint.pkl, synced via Syncer)
    def _init_restore(self, restore_path: str, staging: str) -> None:
        from ray_tpu._private import storage as _storage
        if _storage.is_uri(restore_path):
            import shutil
            name = restore_path.rstrip("/").rsplit("/", 1)[-1]
            self.experiment_dir = os.path.join(staging, name)
            self._sync_uri = restore_path
            # the mirror is the source of truth: stale staging files from
            # a crashed run (written after its last sync) must not merge
            # with the older synced state
            shutil.rmtree(self.experiment_dir, ignore_errors=True)
            _storage.download_dir(restore_path, self.experiment_dir)
        else:
            self.experiment_dir = restore_path
            self._sync_uri = None
        state_path = os.path.join(self.experiment_dir,
                                  "experiment_state.json")
        if not os.path.exists(state_path):
            raise TuneError(f"no experiment_state.json under "
                            f"{restore_path!r}; nothing to restore")
        with open(state_path) as f:
            self._restore_state = json.load(f)

    def _apply_restore_state(self) -> None:
        state = self._restore_state
        # append to the prior run's progress.csv instead of truncating it
        if os.path.exists(self._csv_path):
            with open(self._csv_path) as f:
                header = f.readline().strip()
            if header:
                self._csv_fields = header.split(",")
        for ts in state.get("trials", []):
            t = Trial(ts["config"], self.experiment_dir,
                      resources=self.tune_config.trial_resources,
                      trial_id=ts["trial_id"])
            t.last_result = ts.get("last_result", {})
            if t.last_result:
                t.results.append(t.last_result)
            t.num_failures = ts.get("num_failures", 0)
            t.error = ts.get("error")
            status = ts["status"]
            # a trial that was mid-flight resumes from its checkpoint
            terminal = (TERMINATED,) if self._resume_errored \
                else (TERMINATED, ERROR)
            t.status = status if status in terminal else PENDING
            if status == ERROR and t.status == PENDING:
                t.error = None
                t.num_failures = 0
            ckpt_path = os.path.join(t.logdir, "checkpoint.pkl")
            if os.path.exists(ckpt_path):
                with open(ckpt_path, "rb") as f:
                    t.checkpoint = Checkpoint.from_bytes(f.read())
            self.trials.append(t)
            # deterministic searchers re-derive their sequence: advance
            # them past configs already handed out before the restart
            try:
                self.searcher.advance_restored(t.trial_id,
                                               t.status == PENDING)
            except Exception:
                pass
            self.scheduler.on_trial_add(self, t)
            if t.status in (TERMINATED, ERROR):
                self.searcher.on_trial_complete(
                    t.trial_id, t.last_result or None,
                    error=t.status == ERROR)
        self._iteration = state.get("iteration", 0)

    def _save_experiment_state(self) -> None:
        trials = []
        for t in self.trials:
            trials.append({
                "trial_id": t.trial_id, "config": t.config,
                "status": t.status, "last_result": t.last_result,
                "num_failures": t.num_failures, "error": t.error,
            })
            if t.checkpoint is not None \
                    and getattr(t, "_saved_ckpt", None) is not t.checkpoint:
                try:
                    blob = t.checkpoint.to_bytes()
                except Exception:
                    continue
                tmp = os.path.join(t.logdir, ".checkpoint.tmp")
                with open(tmp, "wb") as f:
                    f.write(blob)
                os.replace(tmp, os.path.join(t.logdir, "checkpoint.pkl"))
                t._saved_ckpt = t.checkpoint
        state = {"name": os.path.basename(self.experiment_dir),
                 "iteration": self._iteration, "trials": trials}
        tmp = os.path.join(self.experiment_dir, ".experiment_state.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f, default=str)
        os.replace(tmp, os.path.join(self.experiment_dir,
                                     "experiment_state.json"))

    # -- trial lifecycle ---------------------------------------------------
    def _make_trial(self) -> Optional[Trial]:
        if len(self.trials) >= self._target_trials or self._suggest_exhausted:
            return None
        t = Trial({}, self.experiment_dir,
                  resources=self.tune_config.trial_resources)
        cfg = self.searcher.suggest(t.trial_id)
        if cfg is None:
            if not isinstance(self.searcher, ConcurrencyLimiter):
                self._suggest_exhausted = True
            return None
        t.config = cfg
        self.trials.append(t)
        self.scheduler.on_trial_add(self, t)
        return t

    def _start_trial(self, trial: Trial,
                     checkpoint: Optional[Checkpoint] = None) -> None:
        import ray_tpu
        from ray_tpu.train.worker_group import TrainWorker
        res = dict(trial.resources)
        cpus = res.pop("CPU", 1.0)
        tpus = res.pop("TPU", 0.0)
        cls = ray_tpu.remote(num_cpus=cpus, num_tpus=tpus,
                             resources=res or None)(TrainWorker)
        trial.actor = cls.remote(world_rank=0, world_size=1)
        trial.actor.start_training.remote(
            self.trainable, trial.config,
            trial_name=f"trial_{trial.trial_id}",
            trial_id=trial.trial_id, trial_dir=trial.logdir,
            experiment_name=os.path.basename(self.experiment_dir),
            checkpoint=checkpoint if checkpoint is not None
            else trial.checkpoint)
        trial.status = RUNNING
        for cb in self.callbacks:
            cb.on_trial_start(self._iteration, self.trials, trial)

    def _stop_trial(self, trial: Trial, status: str,
                    error: Optional[str] = None) -> None:
        import ray_tpu
        trial.status = status
        trial.error = error
        if trial.actor is not None:
            try:
                trial.actor.request_stop.remote()
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        done_result = trial.last_result if not error else None
        self.searcher.on_trial_complete(trial.trial_id, done_result,
                                        error=bool(error))
        self.scheduler.on_trial_complete(self, trial, done_result)
        for cb in self.callbacks:
            if error:
                cb.on_trial_error(self._iteration, self.trials, trial)
            else:
                cb.on_trial_complete(self._iteration, self.trials, trial)

    def request_exploit(self, trial: Trial, donor: Trial,
                        new_config: Dict[str, Any]) -> None:
        """PBT: restart ``trial`` from ``donor``'s checkpoint with mutated
        config at the next poll."""
        trial.pending_exploit = (donor.checkpoint, new_config)

    # -- event loop --------------------------------------------------------
    def step(self) -> bool:
        """One scheduling round; returns False when the experiment is done."""
        import ray_tpu

        # launch new/paused trials up to the concurrency cap; restored
        # PENDING trials (restart-from-checkpoint) go first
        live = [t for t in self.trials if t.status == RUNNING]
        while len(live) < self.max_concurrent:
            restored = next((t for t in self.trials
                             if t.status == PENDING and t.actor is None),
                            None)
            if restored is not None:
                self._start_trial(restored)
                live.append(restored)
                continue
            paused = self.scheduler.choose_trial_to_run(self)
            if paused is not None:
                self._start_trial(paused)
                live.append(paused)
                continue
            t = self._make_trial()
            if t is None:
                break
            self._start_trial(t)
            live.append(t)

        if not live:
            return any(t.status in (PENDING, PAUSED) for t in self.trials) \
                or (len(self.trials) < self._target_trials
                    and not self._suggest_exhausted)

        # poll every live trial
        for trial in live:
            try:
                item = ray_tpu.get(
                    trial.actor.next_result.remote(timeout=1.0),
                    timeout=60.0)
            except Exception as e:
                self._on_trial_error(trial, f"actor died: {e}")
                continue
            if item[0] == "timeout":
                pass
            elif item[0] == "error":
                self._on_trial_error(trial, item[1])
            elif item[0] == "done":
                self._stop_trial(trial, TERMINATED)
            elif item[0] == "result":
                self._on_trial_result(trial, item[1], item[2])
            # apply a pending PBT exploit outside of result handling so it
            # also covers trials that just crossed the interval
            if trial.status == RUNNING and trial.pending_exploit:
                donor_ckpt, new_cfg = trial.pending_exploit
                trial.pending_exploit = None
                import copy
                self._stop_trial_actor_only(trial)
                trial.config = new_cfg
                trial.checkpoint = donor_ckpt
                self._start_trial(trial, checkpoint=donor_ckpt)
        return True

    def _stop_trial_actor_only(self, trial: Trial) -> None:
        import ray_tpu
        if trial.actor is not None:
            try:
                trial.actor.request_stop.remote()
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None

    def _on_trial_result(self, trial: Trial, metrics: Dict[str, Any],
                         ckpt: Optional[Checkpoint]) -> None:
        metrics = dict(metrics)
        metrics["trial_id"] = trial.trial_id
        metrics["config"] = trial.config
        trial.last_result = metrics
        trial.results.append(metrics)
        if ckpt is not None:
            trial.checkpoint = ckpt
        self._log_result(trial, metrics)
        for cb in self.callbacks:
            cb.on_trial_result(self._iteration, self.trials, trial, metrics)
        self.searcher.on_trial_result(trial.trial_id, metrics)
        decision = self.scheduler.on_trial_result(self, trial, metrics)
        if self._hit_stop_criteria(metrics):
            decision = TrialScheduler.STOP
        if decision == TrialScheduler.STOP:
            self._stop_trial(trial, TERMINATED)
        elif decision == TrialScheduler.PAUSE:
            self._stop_trial_actor_only(trial)
            trial.status = PAUSED

    def _hit_stop_criteria(self, metrics: Dict[str, Any]) -> bool:
        stop = self.run_config.stop
        if not stop:
            return False
        return any(k in metrics and metrics[k] >= v for k, v in stop.items())

    def _on_trial_error(self, trial: Trial, err: str) -> None:
        trial.num_failures += 1
        max_failures = self.run_config.failure_config.max_failures
        if max_failures < 0 or trial.num_failures <= max_failures:
            self._stop_trial_actor_only(trial)
            trial.status = PENDING
            self._start_trial(trial)     # restart from last checkpoint
            trial.status = RUNNING
            return
        self._stop_trial(trial, ERROR, error=err)
        if self.run_config.failure_config.fail_fast:
            raise TuneError(f"trial {trial.trial_id} failed:\n{err}")

    # -- logging -----------------------------------------------------------
    def _log_result(self, trial: Trial, metrics: Dict[str, Any]) -> None:
        with open(os.path.join(trial.logdir, "result.json"), "a") as f:
            f.write(json.dumps(metrics, default=str) + "\n")
        flat = {k: v for k, v in metrics.items()
                if isinstance(v, (int, float, str, bool))}
        flat["trial_id"] = trial.trial_id
        if self._csv_fields is None:
            self._csv_fields = sorted(flat.keys())
            with open(self._csv_path, "w", newline="") as f:
                csv.DictWriter(f, self._csv_fields).writeheader()
        with open(self._csv_path, "a", newline="") as f:
            csv.DictWriter(f, self._csv_fields,
                           extrasaction="ignore").writerow(flat)

    # -- results -----------------------------------------------------------
    def run(self) -> List[Result]:
        while self.step():
            self._iteration += 1
            self._save_experiment_state()
            if self._syncer is not None:
                self._syncer.sync_up()
        self._save_experiment_state()
        if self._syncer is not None:
            self._syncer.sync_up(force=True)
        for cb in self.callbacks:
            cb.on_experiment_end(self.trials)
        out = []
        for t in self.trials:
            out.append(Result(
                metrics=t.last_result, checkpoint=t.checkpoint,
                error=TuneError(t.error) if t.error else None,
                log_dir=t.logdir))
        return out


class Tuner:
    """``Tuner(trainable, param_space=..., tune_config=..., run_config=...)
    .fit()`` (cf. reference tuner.py:249)."""

    def __init__(self, trainable: Callable, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        if hasattr(trainable, "as_trainable"):
            trainable = trainable.as_trainable()
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._restore_path: Optional[str] = None

    @classmethod
    def restore(cls, path: str, trainable: Callable, *,
                param_space: Optional[Dict[str, Any]] = None,
                tune_config: Optional[TuneConfig] = None,
                run_config: Optional[RunConfig] = None,
                resume_errored: bool = False) -> "Tuner":
        """Resume an experiment from a local dir or storage URI (reference
        tuner.py Tuner.restore): finished trials keep their results,
        interrupted ones restart from their last synced checkpoint;
        ``resume_errored`` also restarts trials that had failed."""
        tuner = cls(trainable, param_space=param_space,
                    tune_config=tune_config, run_config=run_config)
        tuner._restore_path = path
        tuner._resume_errored = resume_errored
        return tuner

    def fit(self) -> ResultGrid:
        runner = TrialRunner(self.trainable, self.param_space,
                             self.tune_config, self.run_config,
                             restore_path=self._restore_path,
                             resume_errored=getattr(
                                 self, "_resume_errored", False))
        results = runner.run()
        return ResultGrid(results, runner.trials,
                          self.tune_config.metric, self.tune_config.mode)
