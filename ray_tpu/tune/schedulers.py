"""Trial schedulers: early stopping / pausing / exploit-explore.

Analog of /root/reference/python/ray/tune/schedulers/
(ASHA async_hyperband.py, PBT pbt.py, MedianStoppingRule
median_stopping_rule.py, HyperBandScheduler hyperband.py).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional


class TrialScheduler:
    CONTINUE = "CONTINUE"
    PAUSE = "PAUSE"
    STOP = "STOP"

    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str]) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def on_trial_add(self, runner, trial) -> None:
        """Called when the runner creates a trial (before it starts)."""

    def on_trial_result(self, runner, trial,
                        result: Dict[str, Any]) -> str:
        return self.CONTINUE

    def on_trial_complete(self, runner, trial,
                          result: Optional[Dict[str, Any]]) -> None:
        pass

    def choose_trial_to_run(self, runner):
        for t in runner.trials:
            if t.status == "PAUSED":
                return t
        return None


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving (cf. reference async_hyperband.py).

    At each rung (time_attr crossing ``grace_period * reduction_factor^k``),
    a trial is stopped unless its metric is in the top ``1/reduction_factor``
    of completed rung entries.
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung milestone -> list of recorded metric values
        self._rungs: Dict[float, List[float]] = {}
        self._milestones = []
        t = grace_period
        while t < max_t:
            self._milestones.append(t)
            t = math.ceil(t * reduction_factor)

    def on_trial_result(self, runner, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        value = result.get(self.metric)
        if value is None:
            return self.CONTINUE
        if t >= self.max_t:
            return self.STOP
        decision = self.CONTINUE
        for milestone in self._milestones:
            if t < milestone:
                break
            rung = self._rungs.setdefault(milestone, [])
            key = (trial.trial_id, milestone)
            if key in getattr(trial, "_asha_recorded", set()):
                continue
            trial._asha_recorded = getattr(trial, "_asha_recorded", set())
            trial._asha_recorded.add(key)
            rung.append(value)
            if len(rung) >= self.rf:
                cutoff = self._cutoff(rung)
                keep = value >= cutoff if self.mode == "max" \
                    else value <= cutoff
                if not keep:
                    decision = self.STOP
        return decision

    def _cutoff(self, rung: List[float]) -> float:
        ordered = sorted(rung, reverse=self.mode == "max")
        k = max(1, int(len(ordered) / self.rf))
        return ordered[k - 1]


class _Bracket:
    """One successive-halving bracket: members climb rung milestones; at
    each full rung the top 1/eta are promoted, the rest stopped."""

    def __init__(self, milestones: List[int], eta: float):
        self.milestones = milestones
        self.eta = eta
        self.members: List[str] = []                # trial ids
        self.rung_of: Dict[str, int] = {}           # trial id -> rung idx
        self.recorded: Dict[int, Dict[str, float]] = {}  # rung -> id -> val
        self.done: set = set()                      # ids out of the bracket
        self.promoted: set = set()                  # ids cleared to resume
        self.closed = False                         # no new members
        self.completed: set = set()                 # rungs already promoted

    def add(self, trial_id: str) -> None:
        self.members.append(trial_id)
        self.rung_of[trial_id] = 0

    def pending(self, rung: int) -> List[str]:
        rec = self.recorded.get(rung, {})
        return [m for m in self.members
                if m not in rec and m not in self.done
                and self.rung_of.get(m, 0) == rung]

    def record(self, trial_id: str, rung: int, value: float,
               mode: str) -> Optional[List[str]]:
        """Record a rung entry, then try to complete the rung."""
        self.recorded.setdefault(rung, {})[trial_id] = value
        return self.maybe_complete(rung, mode)

    def maybe_complete(self, rung: int, mode: str) -> Optional[List[str]]:
        """Promote the rung's top 1/eta exactly once, when every live
        member has recorded it. Single path for both the result and the
        early-completion (trial left the bracket) triggers."""
        rec = self.recorded.get(rung, {})
        if rung in self.completed or not rec or self.pending(rung):
            return None
        self.completed.add(rung)
        self.closed = True
        ordered = sorted(rec, key=rec.get, reverse=mode == "max")
        k = max(1, int(math.ceil(len(ordered) / self.eta)))
        winners = [m for m in ordered[:k] if m not in self.done]
        for m in rec:
            if m in winners:
                self.rung_of[m] = rung + 1
                self.promoted.add(m)
            elif m not in self.done:
                self.done.add(m)
        return winners


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand / successive halving (cf. reference
    tune/schedulers/hyperband.py; with TuneBOHB as the searcher this is
    the reference's BOHB pairing, HyperBandForBOHB).

    Trials join the open bracket until its first rung completes. At each
    milestone (grace * eta^k) a trial pauses; when every live bracket
    member has reported the rung, the top 1/eta resume and the rest stop.
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 81, grace_period: int = 1,
                 reduction_factor: float = 3):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        self.milestones: List[int] = []
        t = grace_period
        while t < max_t:
            self.milestones.append(int(t))
            t = math.ceil(t * reduction_factor)
        self.brackets: List[_Bracket] = []
        self._bracket_of: Dict[str, _Bracket] = {}

    def on_trial_add(self, runner, trial) -> None:
        self._assign(trial.trial_id)

    def _assign(self, trial_id: str) -> _Bracket:
        b = self._bracket_of.get(trial_id)
        if b is not None:
            return b
        for b in self.brackets:
            if not b.closed:
                break
        else:
            b = _Bracket(self.milestones, self.eta)
            self.brackets.append(b)
        b.add(trial_id)
        self._bracket_of[trial_id] = b
        return b

    def on_trial_result(self, runner, trial, result) -> str:
        value = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if value is None:
            return self.CONTINUE
        if t >= self.max_t:
            return self.STOP
        b = self._assign(trial.trial_id)
        rung = b.rung_of.get(trial.trial_id, 0)
        if rung >= len(b.milestones):
            return self.CONTINUE
        if t < b.milestones[rung]:
            return self.CONTINUE
        winners = b.record(trial.trial_id, rung, value, self.mode)
        if winners is None:
            return self.PAUSE          # wait for bracket peers
        # rung complete: this trial either advances now or stops now; its
        # paused peers are resolved in choose_trial_to_run
        if trial.trial_id in b.promoted:
            b.promoted.discard(trial.trial_id)
            return self.CONTINUE
        return self.STOP

    def on_trial_complete(self, runner, trial, result) -> None:
        b = self._bracket_of.get(trial.trial_id)
        if b is None:
            return
        b.done.add(trial.trial_id)
        b.promoted.discard(trial.trial_id)
        # the departure may complete the current rung for the others
        b.maybe_complete(b.rung_of.get(trial.trial_id, 0), self.mode)

    def choose_trial_to_run(self, runner):
        for t in runner.trials:
            if t.status != "PAUSED":
                continue
            b = self._bracket_of.get(t.trial_id)
            if b is None:
                return t
            if t.trial_id in b.promoted:
                b.promoted.discard(t.trial_id)
                return t
            if t.trial_id in b.done:
                # lost its rung while paused: terminate instead of resume
                runner._stop_trial(t, "TERMINATED")
        return None


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-average is below the median of other
    trials' averages at the same step (cf. reference
    median_stopping_rule.py)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 5, min_samples_required: int = 3):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._histories: Dict[str, List[float]] = {}

    def on_trial_result(self, runner, trial, result) -> str:
        value = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if value is None:
            return self.CONTINUE
        hist = self._histories.setdefault(trial.trial_id, [])
        hist.append(value)
        if t < self.grace_period:
            return self.CONTINUE
        means = [sum(h) / len(h) for tid, h in self._histories.items()
                 if tid != trial.trial_id and h]
        if len(means) < self.min_samples:
            return self.CONTINUE
        means.sort()
        median = means[len(means) // 2]
        mean = sum(hist) / len(hist)
        worse = mean < median if self.mode == "max" else mean > median
        return self.STOP if worse else self.CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (cf. reference pbt.py): at each ``perturbation_interval``, a
    bottom-quantile trial exploits a top-quantile trial's checkpoint+config
    and explores by resampling/perturbing hyperparams. The runner applies
    the returned exploit decision (restore checkpoint, swap config).
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, float] = {}
        self._scores: Dict[str, float] = {}

    def on_trial_result(self, runner, trial, result) -> str:
        value = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if value is None:
            return self.CONTINUE
        self._scores[trial.trial_id] = value
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval:
            return self.CONTINUE
        self._last_perturb[trial.trial_id] = t
        live = [tr for tr in runner.trials
                if tr.trial_id in self._scores
                and tr.status in ("RUNNING", "PAUSED")]
        if len(live) < 2:
            return self.CONTINUE
        ordered = sorted(live, key=lambda tr: self._scores[tr.trial_id],
                         reverse=self.mode == "max")
        k = max(1, int(len(ordered) * self.quantile))
        top, bottom = ordered[:k], ordered[-k:]
        if trial in bottom and trial not in top:
            donor = self._rng.choice(top)
            if donor.checkpoint is not None:
                new_cfg = self._explore(dict(donor.config))
                runner.request_exploit(trial, donor, new_cfg)
        return self.CONTINUE

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.sample import Domain
        for key, spec in self.mutations.items():
            if self._rng.random() < self.resample_p or key not in config:
                if isinstance(spec, Domain):
                    config[key] = spec.sample(self._rng)
                elif isinstance(spec, list):
                    config[key] = self._rng.choice(spec)
                elif callable(spec):
                    config[key] = spec()
            elif isinstance(config[key], (int, float)):
                factor = self._rng.choice([0.8, 1.2])
                config[key] = type(config[key])(config[key] * factor)
        return config
