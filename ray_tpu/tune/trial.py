"""Trial: one parameterized run of a trainable.

Analog of /root/reference/python/ray/tune/experiment/trial.py.
"""

from __future__ import annotations

import os
import uuid
from typing import Any, Dict, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


class Trial:
    def __init__(self, config: Dict[str, Any], experiment_dir: str,
                 resources: Optional[Dict[str, float]] = None,
                 trial_id: Optional[str] = None):
        self.trial_id = trial_id or uuid.uuid4().hex[:8]
        self.config = config
        self.resources = dict(resources or {"CPU": 1.0})
        self.status = PENDING
        self.actor = None                      # TrainWorker handle
        self.last_result: Dict[str, Any] = {}
        self.results: list = []
        self.checkpoint = None                 # latest air.Checkpoint
        self.error: Optional[str] = None
        self.num_failures = 0
        self.logdir = os.path.join(experiment_dir, f"trial_{self.trial_id}")
        os.makedirs(self.logdir, exist_ok=True)
        # PBT exploit request: (donor_checkpoint, new_config) to apply
        self.pending_exploit = None

    @property
    def iteration(self) -> int:
        return self.last_result.get("training_iteration", 0)

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status}, it={self.iteration})"
