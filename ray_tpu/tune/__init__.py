"""ray_tpu.tune: hyperparameter search over trial actors.

Analog of /root/reference/python/ray/tune (SURVEY.md §2.4): Tuner.fit →
TrialRunner event loop → trial actors; searchers + schedulers (ASHA, PBT,
median stopping); JSONL/CSV logging; checkpoint-aware exploit/restore.
"""

from ray_tpu.air.result import Result  # noqa: F401
from ray_tpu.tune.sample import (choice, grid_search, loguniform,  # noqa: F401
                                 quniform, randint, randn, sample_from,
                                 uniform)
from ray_tpu.tune.schedulers import (ASHAScheduler,  # noqa: F401
                                     FIFOScheduler, MedianStoppingRule,
                                     PopulationBasedTraining,
                                     TrialScheduler)
from ray_tpu.tune.search import (BasicVariantGenerator,  # noqa: F401
                                 ConcurrencyLimiter, HyperOptStyleSearch,
                                 RandomSearch, Searcher)
from ray_tpu.tune.trial import Trial  # noqa: F401
from ray_tpu.tune.tuner import (ResultGrid, TuneConfig, TuneError,  # noqa: F401
                                Tuner)

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "TuneError", "Trial",
    "uniform", "loguniform", "quniform", "randint", "randn", "choice",
    "sample_from", "grid_search",
    "Searcher", "BasicVariantGenerator", "RandomSearch",
    "ConcurrencyLimiter", "HyperOptStyleSearch",
    "TrialScheduler", "FIFOScheduler", "ASHAScheduler",
    "MedianStoppingRule", "PopulationBasedTraining",
    "Result",
]
