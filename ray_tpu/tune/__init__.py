"""ray_tpu.tune: hyperparameter search over trial actors.

Analog of /root/reference/python/ray/tune (SURVEY.md §2.4): Tuner.fit →
TrialRunner event loop → trial actors; searchers (random, grid, TPE/BOHB)
+ schedulers (ASHA, HyperBand, PBT, median stopping); JSONL/CSV/TBX
logging callbacks; checkpoint-aware exploit/restore; ExperimentAnalysis.
"""

from ray_tpu.air.result import Result  # noqa: F401
from ray_tpu.tune.analysis import ExperimentAnalysis  # noqa: F401
from ray_tpu.tune.callback import (Callback, CSVLoggerCallback,  # noqa: F401
                                   JsonLoggerCallback, TBXLoggerCallback)
from ray_tpu.tune.sample import (choice, grid_search, loguniform,  # noqa: F401
                                 quniform, randint, randn, sample_from,
                                 uniform)
from ray_tpu.tune.schedulers import (ASHAScheduler,  # noqa: F401
                                     FIFOScheduler, HyperBandScheduler,
                                     MedianStoppingRule,
                                     PopulationBasedTraining,
                                     TrialScheduler)
from ray_tpu.tune.search import (BasicVariantGenerator,  # noqa: F401
                                 ConcurrencyLimiter, HyperOptStyleSearch,
                                 RandomSearch, Searcher, TPESearcher,
                                 TuneBOHB)
from ray_tpu.tune.trial import Trial  # noqa: F401
from ray_tpu.tune.tuner import (ResultGrid, TuneConfig, TuneError,  # noqa: F401
                                Tuner)

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "TuneError", "Trial",
    "uniform", "loguniform", "quniform", "randint", "randn", "choice",
    "sample_from", "grid_search",
    "Searcher", "BasicVariantGenerator", "RandomSearch",
    "ConcurrencyLimiter", "HyperOptStyleSearch", "TPESearcher", "TuneBOHB",
    "TrialScheduler", "FIFOScheduler", "ASHAScheduler",
    "HyperBandScheduler", "MedianStoppingRule", "PopulationBasedTraining",
    "Callback", "JsonLoggerCallback", "CSVLoggerCallback",
    "TBXLoggerCallback", "ExperimentAnalysis",
    "Result",
]
