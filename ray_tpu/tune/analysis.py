"""ExperimentAnalysis: load + query a finished (or running) experiment dir.

Analog of /root/reference/python/ray/tune/analysis/experiment_analysis.py:
reads the per-trial ``result.json`` histories the runner writes and
answers best-config/best-checkpoint/dataframe queries, including for
experiments from an earlier process (restore-after-crash inspection).
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional


class ExperimentAnalysis:
    def __init__(self, experiment_dir: str,
                 default_metric: Optional[str] = None,
                 default_mode: str = "max"):
        self.experiment_dir = experiment_dir
        self.default_metric = default_metric
        self.default_mode = default_mode
        # trial_id -> list of result dicts (ordered)
        self.trial_dataframes: Dict[str, List[Dict[str, Any]]] = {}
        for path in sorted(glob.glob(
                os.path.join(experiment_dir, "trial_*", "result.json"))):
            trial_id = os.path.basename(os.path.dirname(path)) \
                .replace("trial_", "")
            rows = []
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        rows.append(self._coerce(json.loads(line)))
            if rows:
                self.trial_dataframes[trial_id] = rows

    _STRING_KEYS = frozenset({"trial_id", "experiment_tag", "logdir",
                              "date", "hostname", "node_ip"})

    @classmethod
    def _coerce(cls, row: Dict[str, Any]) -> Dict[str, Any]:
        """The runner serializes with default=str, so numpy/JAX scalars
        arrive as strings — parse numeric-looking strings back to float
        or metric comparisons would be lexicographic. Known string fields
        (a hex trial_id can be all digits, or parse as 1e45678) and
        non-finite parses are left alone."""
        import math
        out = {}
        for k, v in row.items():
            if isinstance(v, str) and k not in cls._STRING_KEYS:
                try:
                    f = float(v)
                    if math.isfinite(f):
                        v = f
                except ValueError:
                    pass
            out[k] = v
        return out

    @property
    def trial_ids(self) -> List[str]:
        return list(self.trial_dataframes)

    def _metric_mode(self, metric, mode):
        metric = metric or self.default_metric
        mode = mode or self.default_mode
        if metric is None:
            raise ValueError("metric is required (no default set)")
        return metric, mode

    def best_trial_id(self, metric: Optional[str] = None,
                      mode: Optional[str] = None) -> str:
        metric, mode = self._metric_mode(metric, mode)
        best_id, best_val = None, None
        for tid, rows in self.trial_dataframes.items():
            vals = [r[metric] for r in rows if metric in r]
            if not vals:
                continue
            v = max(vals) if mode == "max" else min(vals)
            if best_val is None or (v > best_val if mode == "max"
                                    else v < best_val):
                best_id, best_val = tid, v
        if best_id is None:
            raise ValueError(f"no trial reported metric {metric!r}")
        return best_id

    def get_best_config(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Dict[str, Any]:
        metric, mode = self._metric_mode(metric, mode)
        rows = self.trial_dataframes[self.best_trial_id(metric, mode)]
        # the config of the row that achieved the best value — under PBT
        # the trial's config mutates over time, so rows[-1] can be a
        # config that never produced the best metric
        scored = [r for r in rows if metric in r]
        best_row = (max if mode == "max" else min)(
            scored, key=lambda r: r[metric])
        return best_row.get("config", {})

    def get_last_results(self) -> Dict[str, Dict[str, Any]]:
        return {tid: rows[-1]
                for tid, rows in self.trial_dataframes.items()}

    def dataframe(self):
        try:
            import pandas as pd
        except ImportError:
            return None
        flat = []
        for tid, rows in self.trial_dataframes.items():
            for r in rows:
                flat.append({**{k: v for k, v in r.items()
                                if not isinstance(v, dict)},
                             "trial_id": tid})
        return pd.DataFrame(flat)
