"""Search algorithms: suggest configs for new trials.

Analog of /root/reference/python/ray/tune/search/ (BasicVariantGenerator
basic_variant.py, Searcher searcher.py, ConcurrencyLimiter).
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.sample import Domain, SampleFrom, generate_variants


class Searcher:
    """Suggest/observe interface (cf. reference search/searcher.py)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: Optional[str], mode: str,
                              config: Dict[str, Any]) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str,
                        result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass

    def advance_restored(self, trial_id: str, live: bool) -> None:
        """Experiment restore: advance a deterministic searcher past a
        config that was already handed out before the restart (the stored
        config is reused; the suggestion is discarded)."""
        self.suggest(trial_id)


class BasicVariantGenerator(Searcher):
    """Grid × random expansion of the param space, computed up front."""

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 num_samples: int = 1, seed: Optional[int] = None):
        super().__init__()
        self._space = space or {}
        self._num_samples = num_samples
        self._rng = random.Random(seed)
        self._variants: Optional[List[Dict[str, Any]]] = None
        self._idx = 0

    def set_search_properties(self, metric, mode, config) -> bool:
        if config:
            self._space = config
            self._variants = None
        return super().set_search_properties(metric, mode, config)

    def _ensure(self):
        if self._variants is None:
            self._variants = generate_variants(
                self._space, self._rng, self._num_samples)

    @property
    def total_trials(self) -> int:
        self._ensure()
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        self._ensure()
        if self._idx >= len(self._variants):
            return None
        cfg = self._variants[self._idx]
        self._idx += 1
        return cfg


class RandomSearch(Searcher):
    """Endless random sampling (``num_samples`` enforced by the Tuner)."""

    def __init__(self, space: Dict[str, Any], seed: Optional[int] = None):
        super().__init__()
        self._space = space
        self._rng = random.Random(seed)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        return generate_variants(self._space, self._rng, 1)[0]


class ConcurrencyLimiter(Searcher):
    """Cap in-flight suggestions (cf. reference ConcurrencyLimiter)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        return self.searcher.set_search_properties(metric, mode, config)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._live) >= self.max_concurrent:
            return None   # back off; runner retries later
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)

    def advance_restored(self, trial_id, live):
        # bypass the cap (restored trials already exist) but keep the
        # in-flight ledger honest for the ones about to run again
        self.searcher.advance_restored(trial_id, live)
        if live:
            self._live.add(trial_id)


def _gridless(space: Dict[str, Any]) -> Dict[str, Any]:
    """Replace grid_search leaves with Choice so per-trial sampling covers
    every grid value (grid expansion is a BasicVariant concept; model-based
    searchers draw one config at a time)."""
    from ray_tpu.tune.sample import Choice, _grid_values, _is_grid
    out = {}
    for k, v in space.items():
        if _is_grid(v):
            out[k] = Choice(_grid_values(v))
        elif isinstance(v, dict):
            out[k] = _gridless(v)
        else:
            out[k] = v
    return out


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (the algorithm behind the
    reference's hyperopt/optuna integrations — search/hyperopt/,
    search/optuna/ — implemented natively so no external package is
    needed). Observations are split into good/bad sets at quantile
    ``gamma``; numeric dims get Gaussian Parzen windows, categorical dims
    get smoothed count ratios; candidates maximize l(x)/g(x).
    """

    def __init__(self, space: Dict[str, Any], metric: Optional[str] = None,
                 mode: str = "max", n_initial: int = 10,
                 n_candidates: int = 24, gamma: float = 0.25,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self._space = _gridless(space)
        self._rng = random.Random(seed)
        self._n_initial = n_initial
        self._n_candidates = n_candidates
        self._gamma = gamma
        self._observations: List[Any] = []   # (config, score)
        self._pending: Dict[str, Dict[str, Any]] = {}

    def set_search_properties(self, metric, mode, config) -> bool:
        if config and not self._space:
            self._space = _gridless(config)
        return super().set_search_properties(metric, mode, config)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._observations) < self._n_initial:
            cfg = generate_variants(self._space, self._rng, 1)[0]
        else:
            # the good/bad split and per-key value lists depend only on
            # the observations: build once, score all candidates with it
            good, bad = self._split()
            values = {key: ([cfg.get(key) for cfg, _ in good],
                            [cfg.get(key) for cfg, _ in bad])
                      for key in self._space
                      if isinstance(self._space[key], Domain)}
            cands = [generate_variants(self._space, self._rng, 1)[0]
                     for _ in range(self._n_candidates)]
            cfg = max(cands, key=lambda c: self._ei_score(c, values))
        self._pending[trial_id] = cfg
        return cfg

    def _split(self):
        obs = sorted(self._observations, key=lambda o: o[1],
                     reverse=self.mode == "max")
        k = max(1, int(len(obs) * self._gamma))
        return obs[:k], obs[k:]

    def _ei_score(self, cand: Dict[str, Any],
                  values: Dict[str, Any]) -> float:
        """log l(x) - log g(x) under per-dimension Parzen estimators."""
        import math as _m

        def log_density(value, obs_values):
            nums = [v for v in obs_values
                    if isinstance(v, (int, float)) and not isinstance(v, bool)]
            if isinstance(value, (int, float)) and not isinstance(value, bool) \
                    and nums:
                lo, hi = min(nums), max(nums)
                bw = max((hi - lo) / max(len(nums) ** 0.5, 1.0),
                         abs(value) * 1e-3, 1e-12)
                dens = sum(_m.exp(-0.5 * ((value - m) / bw) ** 2)
                           for m in nums) / (len(nums) * bw)
                return _m.log(dens + 1e-300)
            # categorical: smoothed frequency
            count = sum(1 for v in obs_values if v == value)
            return _m.log((count + 1.0) / (len(obs_values) + 2.0))

        score = 0.0
        for key, (gv, bv) in values.items():
            if not gv or not bv:
                continue
            score += log_density(cand.get(key), gv) \
                - log_density(cand.get(key), bv)
        return score

    def on_trial_complete(self, trial_id, result=None, error=False):
        cfg = self._pending.pop(trial_id, None)
        if cfg is not None and result and self.metric in result and not error:
            self._observations.append((cfg, result[self.metric]))


# BOHB pairs the TPE model with the HyperBand scheduler
# (ray_tpu.tune.schedulers.HyperBandScheduler), mirroring the reference's
# TuneBOHB searcher + HyperBandForBOHB pairing (search/bohb/).
TuneBOHB = TPESearcher


class HyperOptStyleSearch(Searcher):
    """A dependency-free TPE-flavored searcher: explores randomly for
    ``n_initial`` trials, then samples candidates and picks the one closest
    (in normalized param space) to the best-quartile trials and farthest
    from the worst — a cheap stand-in for the reference's hyperopt/optuna
    integrations (which need external packages).
    """

    def __init__(self, space: Dict[str, Any], metric: str, mode: str = "max",
                 n_initial: int = 10, n_candidates: int = 24,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self._space = _gridless(space)
        self._rng = random.Random(seed)
        self._n_initial = n_initial
        self._n_candidates = n_candidates
        self._observations: List[Any] = []   # (config, score)
        self._pending: Dict[str, Dict[str, Any]] = {}

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._observations) < self._n_initial:
            cfg = generate_variants(self._space, self._rng, 1)[0]
        else:
            cands = [generate_variants(self._space, self._rng, 1)[0]
                     for _ in range(self._n_candidates)]
            cfg = max(cands, key=self._score_candidate)
        self._pending[trial_id] = cfg
        return cfg

    def _numeric_keys(self):
        return [k for k, v in self._space.items()
                if isinstance(v, Domain) and not isinstance(v, SampleFrom)]

    def _score_candidate(self, cand: Dict[str, Any]) -> float:
        obs = sorted(self._observations, key=lambda o: o[1],
                     reverse=self.mode == "max")
        k = max(1, len(obs) // 4)
        good, bad = obs[:k], obs[-k:]
        keys = self._numeric_keys()

        def dist(a, b):
            d = 0.0
            for key in keys:
                va, vb = a.get(key), b.get(key)
                if isinstance(va, (int, float)) and isinstance(vb,
                                                               (int, float)):
                    scale = abs(va) + abs(vb) + 1e-9
                    d += ((va - vb) / scale) ** 2
                elif va != vb:
                    d += 1.0
            return d ** 0.5

        good_d = min(dist(cand, g) for g, _ in good)
        bad_d = min(dist(cand, b) for b, _ in bad)
        return bad_d - good_d

    def on_trial_complete(self, trial_id, result=None, error=False):
        cfg = self._pending.pop(trial_id, None)
        if cfg is not None and result and self.metric in result \
                and not error:
            self._observations.append((cfg, result[self.metric]))
