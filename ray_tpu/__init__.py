"""ray_tpu: a TPU-native distributed computing framework.

Tasks/actors/objects core (C++ shared-memory store + Python control plane)
plus a TPU-first ML stack: GSPMD mesh parallelism, Pallas kernels, ring
attention, JaxTrainer, datasets, tuning, RL, and serving.
"""

from ray_tpu._private.config import CONFIG  # noqa: F401

# debug-mode lock-order sanitizer (docs/static_analysis.md): installed
# BEFORE the runtime modules import so their module-level locks are
# instrumented too; a no-op unless RAY_TPU_DEBUG_LOCKS / debug_locks is
# set (spawned daemons inherit the env and self-instrument here)
from ray_tpu._private.analysis import lock_sanitizer as _lock_sanitizer
_lock_sanitizer.maybe_install()

from ray_tpu.actor import get_actor, kill, method  # noqa: F401
from ray_tpu.api import (available_resources, cluster_resources, context,  # noqa: F401
                         get, get_runtime_context, init, is_initialized,
                         nodes, put, remote, shutdown, wait)
from ray_tpu.cross_language import (cpp_actor_class,  # noqa: F401
                                    cpp_function)
from ray_tpu.runtime.core_worker import (ObjectRef,  # noqa: F401
                                         ObjectRefGenerator,
                                         StreamingObjectRefGenerator)

__version__ = "0.1.0"

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "get_actor", "kill", "nodes", "cluster_resources",
    "available_resources", "context", "get_runtime_context", "ObjectRef",
    "ObjectRefGenerator", "StreamingObjectRefGenerator", "CONFIG",
    "cpp_function", "cpp_actor_class", "__version__",
]
