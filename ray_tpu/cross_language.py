"""Cross-language task invocation.

Analog of /root/reference/python/ray/cross_language.py (java_function :15,
java_actor_class :50), retargeted at this framework's native language:
``cpp_function("Name")`` returns a handle whose ``.remote(...)`` submits a
task with fn_key ``cpp:Name`` and ``language="cpp"`` — the raylet leases a
C++ worker (csrc/cpp_worker.cc) whose static registry resolves the name
(csrc/cpp_functions.h RAY_TPU_CPP_FUNCTION).

v1 scope, enforced at submit time where possible: positional by-value
primitive args (no ObjectRefs into cpp tasks), primitive results, fixed
num_returns (no "dynamic"), no cpp actors yet.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

_PRIMITIVES = (type(None), bool, int, float, str, bytes)


def _check_arg(a: Any) -> None:
    if isinstance(a, (list, tuple)):
        for x in a:
            _check_arg(x)
        return
    if isinstance(a, dict):
        for k, v in a.items():
            _check_arg(k)
            _check_arg(v)
        return
    if not isinstance(a, _PRIMITIVES):
        raise TypeError(
            f"cpp tasks take primitive by-value args; got {type(a).__name__}"
            " (ObjectRefs/arrays are not representable C++-side)")


class CppFunction:
    """Handle on a C++ function registered in the worker binary."""

    def __init__(self, name: str, *, num_returns: int = 1,
                 resources: Optional[Dict[str, float]] = None,
                 max_retries: int = 3):
        if not name or ":" in name:
            raise ValueError(f"bad cpp function name {name!r}")
        self._name = name
        self._num_returns = num_returns
        self._resources = dict(resources or {})
        self._max_retries = max_retries

    def options(self, *, num_returns: Optional[int] = None,
                resources: Optional[Dict[str, float]] = None,
                max_retries: Optional[int] = None) -> "CppFunction":
        return CppFunction(
            self._name,
            num_returns=self._num_returns if num_returns is None
            else num_returns,
            resources=self._resources if resources is None else resources,
            max_retries=self._max_retries if max_retries is None
            else max_retries)

    def remote(self, *args):
        import pickle

        from ray_tpu._private.config import CONFIG
        from ray_tpu.runtime.core_worker import get_global_worker
        for a in args:
            _check_arg(a)
            # any arg whose pickle exceeds the inline threshold would be
            # promoted to a store ObjectRef by _serialize_args — which a
            # cpp worker cannot resolve; reject at the submit site with
            # the real reason instead of a far-from-cause worker error
            if len(pickle.dumps(a, protocol=5)) > \
                    CONFIG.max_direct_call_args_bytes:
                raise ValueError(
                    "cpp task arg exceeds max_direct_call_args_bytes "
                    f"({CONFIG.max_direct_call_args_bytes}); it would be "
                    "promoted to a store object, which cpp tasks cannot "
                    "resolve yet")
        if not isinstance(self._num_returns, int):
            raise ValueError("cpp tasks need a fixed integer num_returns")
        worker = get_global_worker()
        refs = worker.submit_task(
            None, args, {},
            num_returns=self._num_returns,
            resources=self._resources,
            max_retries=self._max_retries,
            name=f"cpp:{self._name}",
            fn_key=f"cpp:{self._name}",
            language="cpp")
        if self._num_returns == 1:
            return refs[0]
        return refs


def cpp_function(name: str, **options) -> CppFunction:
    """Handle on the C++ task ``name`` (RAY_TPU_CPP_FUNCTION-registered
    in the worker binary — stock functions live in
    csrc/cpp_builtin_functions.cc)."""
    return CppFunction(name, **options)
