"""Cross-language task invocation.

Analog of /root/reference/python/ray/cross_language.py (java_function :15,
java_actor_class :50), retargeted at this framework's native language:
``cpp_function("Name")`` returns a handle whose ``.remote(...)`` submits a
task with fn_key ``cpp:Name`` and ``language="cpp"`` — the raylet leases a
C++ worker (csrc/cpp_worker.cc) whose static registry resolves the name
(csrc/cpp_functions.h RAY_TPU_CPP_FUNCTION).

v1 scope, enforced at submit time where possible: positional by-value
primitive args (no ObjectRefs into cpp tasks), primitive results, fixed
num_returns (no "dynamic"), no cpp actors yet.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

_PRIMITIVES = (type(None), bool, int, float, str, bytes)


def _check_arg(a: Any, depth: int = 0) -> None:
    from ray_tpu.runtime.core_worker import ObjectRef
    if isinstance(a, ObjectRef):
        if depth == 0:
            return  # resolved worker-side via the borrower protocol
        # nested refs pass through Python workers as live handles, but a
        # cpp worker would see an opaque marker it cannot resolve —
        # reject at the call site instead of corrupting silently
        raise TypeError(
            "ObjectRef args to cpp tasks must be top-level positional "
            "args (nested inside containers they are not resolvable "
            "C++-side)")
    if isinstance(a, (list, tuple)):
        for x in a:
            _check_arg(x, depth + 1)
        return
    if isinstance(a, dict):
        for k, v in a.items():
            _check_arg(k, depth + 1)
            _check_arg(v, depth + 1)
        return
    if not isinstance(a, _PRIMITIVES):
        raise TypeError(
            f"cpp tasks take primitive by-value args; got {type(a).__name__}"
            " (arrays and arbitrary objects are not representable "
            "C++-side; top-level ObjectRefs to primitive values are)")


def _guard_args(args) -> None:
    """Reject what the C++ side cannot receive: non-primitive values.
    ObjectRef args (explicit or from large-arg store promotion) are fine
    — the cpp worker fetches them through the owner/raylet like any
    borrower, provided the referenced VALUE is itself primitive."""
    for a in args:
        _check_arg(a)


class CppFunction:
    """Handle on a C++ function registered in the worker binary."""

    def __init__(self, name: str, *, num_returns: int = 1,
                 resources: Optional[Dict[str, float]] = None,
                 max_retries: int = 3):
        if not name or ":" in name:
            raise ValueError(f"bad cpp function name {name!r}")
        self._name = name
        self._num_returns = num_returns
        self._resources = dict(resources or {})
        self._max_retries = max_retries

    def options(self, *, num_returns: Optional[int] = None,
                resources: Optional[Dict[str, float]] = None,
                max_retries: Optional[int] = None) -> "CppFunction":
        return CppFunction(
            self._name,
            num_returns=self._num_returns if num_returns is None
            else num_returns,
            resources=self._resources if resources is None else resources,
            max_retries=self._max_retries if max_retries is None
            else max_retries)

    def remote(self, *args):
        from ray_tpu.runtime.core_worker import get_global_worker
        _guard_args(args)
        if not isinstance(self._num_returns, int):
            raise ValueError("cpp tasks need a fixed integer num_returns")
        worker = get_global_worker()
        refs = worker.submit_task(
            None, args, {},
            num_returns=self._num_returns,
            resources=self._resources,
            max_retries=self._max_retries,
            name=f"cpp:{self._name}",
            fn_key=f"cpp:{self._name}",
            language="cpp")
        if self._num_returns == 1:
            return refs[0]
        return refs


def cpp_function(name: str, **options) -> CppFunction:
    """Handle on the C++ task ``name`` (RAY_TPU_CPP_FUNCTION-registered
    in the worker binary — stock functions live in
    csrc/cpp_builtin_functions.cc)."""
    return CppFunction(name, **options)


class _CppMethod:
    def __init__(self, handle: "CppActorHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args):
        from ray_tpu.runtime.core_worker import get_global_worker
        _guard_args(args)
        refs = get_global_worker().submit_actor_task(
            self._handle._actor_id, self._method, args, {}, num_returns=1)
        return refs[0]


class CppActorHandle:
    """Handle on a live C++ actor; ``handle.method.remote(...)`` submits
    through the same ordered per-actor pipeline Python actors use (the
    worker executes in seq order).  Works with ``ray_tpu.kill``."""

    def __init__(self, actor_id):
        self._actor_id = actor_id

    def __getattr__(self, name: str) -> _CppMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return _CppMethod(self, name)

    def __repr__(self):
        return f"CppActorHandle({self._actor_id.hex()[:12]})"


class CppActorClass:
    """Class-side handle for a C++ actor registered with
    RAY_TPU_CPP_ACTOR in the worker binary."""

    def __init__(self, name: str, *,
                 resources: Optional[Dict[str, float]] = None,
                 max_restarts: int = 0,
                 actor_name: Optional[str] = None,
                 lifetime: Optional[str] = None):
        if not name or ":" in name:
            raise ValueError(f"bad cpp actor class name {name!r}")
        self._cls = name
        self._resources = dict(resources or {})
        self._max_restarts = max_restarts
        self._actor_name = actor_name
        self._lifetime = lifetime

    def options(self, *, resources: Optional[Dict[str, float]] = None,
                max_restarts: Optional[int] = None,
                name: Optional[str] = None,
                lifetime: Optional[str] = None) -> "CppActorClass":
        return CppActorClass(
            self._cls,
            resources=self._resources if resources is None else resources,
            max_restarts=self._max_restarts if max_restarts is None
            else max_restarts,
            actor_name=self._actor_name if name is None else name,
            lifetime=self._lifetime if lifetime is None else lifetime)

    def remote(self, *args) -> CppActorHandle:
        from ray_tpu.runtime.core_worker import get_global_worker
        _guard_args(args)
        actor_id = get_global_worker().create_actor(
            None, args, {},
            name=self._actor_name,
            detached=self._lifetime == "detached",
            max_restarts=self._max_restarts,
            resources=self._resources,
            cls_key=f"cpp:{self._cls}",
            language="cpp")
        return CppActorHandle(actor_id)


def cpp_actor_class(name: str, **options) -> CppActorClass:
    """Handle on the C++ actor class ``name`` (reference
    cross_language.py:50 java_actor_class analog; stock classes in
    csrc/cpp_builtin_functions.cc: Counter, Kv)."""
    return CppActorClass(name, **options)
