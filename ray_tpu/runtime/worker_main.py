"""Worker process: executes tasks/actor methods pushed by owners.

Analog of the reference's default_worker.py + task-execution path
(/root/reference/python/ray/_private/workers/default_worker.py;
execution callback `task_execution_handler` _raylet.pyx:1121; server-side
scheduling queues src/ray/core_worker/transport/*scheduling_queue*).

Execution model: one executor thread drains a FIFO of normal tasks (the
NormalSchedulingQueue analog); actor tasks carry sequence numbers and are
buffered until their turn (ActorSchedulingQueue analog) so actor state sees
calls in submission order.
"""

from __future__ import annotations

import argparse
import asyncio
import inspect
import os
import threading
import traceback
from collections import deque
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu import exceptions as exc
from ray_tpu._private import cluster_events as cev
from ray_tpu._private import rpc
from ray_tpu._private import runtime_metrics as rtm
from ray_tpu._private import serialization as ser
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from ray_tpu._private.logging_utils import get_logger, setup_component_logging
from ray_tpu.runtime import core_worker as cw

logger = get_logger("worker")

# executor-side telemetry (docs/observability.md)
_M_EXEC = rtm.histogram_family(
    "ray_tpu_task_exec_ms", "task/actor-method execution time (ms)",
    tag_key="func")
_M_CREDIT_WAIT = rtm.histogram(
    "ray_tpu_stream_credit_wait_ms",
    "time a streaming producer spent paused on backpressure credit")

# per-yield STREAM_ITEM instants are recorded into the task table only
# for the first N items of a stream: the timeline stays readable and one
# long stream can't flood the (bounded) per-task event list
_STREAM_EVENT_CAP = 256


def _probe_small(value, budget: int = 32768, depth: int = 0) -> int:
    """Cheap structural probe for the async-actor inline-return fast
    path: returns the remaining byte budget when ``value`` is a small
    JSON-ish object (None/bool/int/float/str/bytes and shallow
    list/tuple/dict of those — the shapes serve replies traffic in),
    or -1 when it is big, deep, or of any other type (numpy arrays,
    user classes: their pickle cost is unbounded, keep the executor).
    Costs ~1us for a typical serve reply dict."""
    if value is None or value is True or value is False:
        return budget - 8
    t = type(value)
    if t is int:
        # arbitrary-precision: charge real width or a 10**10000 would
        # defeat the budget (and the no-store_put-on-loop invariant)
        return budget - 16 - (value.bit_length() >> 3)
    if t is float:
        return budget - 16
    if t is str or t is bytes:
        n = len(value) + 8
        return budget - n if n < budget else -1
    if depth >= 4:
        return -1
    if t is list or t is tuple:
        for item in value:
            budget = _probe_small(item, budget - 8, depth + 1)
            if budget < 0:
                return -1
        return budget
    if t is dict:
        for k, v in value.items():
            budget = _probe_small(k, budget - 8, depth + 1)
            if budget < 0:
                return -1
            budget = _probe_small(v, budget, depth + 1)
            if budget < 0:
                return -1
        return budget
    return -1


class _StreamCancelled(Exception):
    """The owner cancelled the stream (consumer dropped the generator,
    or the owner process is gone): stop producing, finish cleanly."""


class _StreamSession:
    """Producer side of one num_returns="streaming" execution.

    Each yielded item is serialized (inline bytes under the inline-
    return threshold, else a shm primary copy + location) and pushed to
    the owner as a ``report_generator_item`` call on the pooled owner
    connection.  Backpressure: the owner withholds a report's reply
    until that item is consumed, and the session caps unacked reports
    at the spec's ``backpressure`` window — so at most that many
    unconsumed items are ever in flight, and the producing generator
    pauses (blocks in send()) until the consumer catches up."""

    def __init__(self, core, spec, inline_max: int):
        from ray_tpu.util.tracing import tracing_helper as trh
        self.core = core
        self.spec = spec
        self.task_id = TaskID(spec["task_id"])
        self.bp = int(spec.get("backpressure") or -1)
        self.conn = core._owner_conn(tuple(spec["owner_addr"]))
        self.inline_max = inline_max
        self.outstanding: "deque" = deque()
        self.index = 0
        # tracing (docs/observability.md): the session is constructed
        # inside the task's execution context — capture it here because
        # the async-actor variant's send() runs on an executor thread
        # where the ContextVar is absent.  Sampled streams record the
        # first N yields as instant spans and ride the context on each
        # report RPC so the owner-side handler joins the trace.
        self._trh = trh
        self._trace_ctx = trh.current_context()
        # _traced gates context propagation on EVERY report RPC;
        # _span_items only caps the per-yield marker spans — an
        # operator zeroing the marker knob must not silently cut the
        # owner side out of the trace
        self._traced = trh.ctx_sampled(self._trace_ctx)
        self._span_items = (CONFIG.trace_stream_span_items
                            if self._traced else 0)

    def send(self, value) -> None:
        self._wait_for_credit()
        head, views = ser.serialize(value)
        payload = {"task_id": self.spec["task_id"], "index": self.index}
        size = ser.serialized_size(head, views)
        if size <= self.inline_max:
            payload["data"] = ser.to_flat_bytes(head, views)
        else:
            oid = ObjectID.for_task_return(self.task_id, self.index + 1)
            self.core.store_put(oid, head, views)
            payload["location"] = self.core.node_id
            payload["size"] = size
        if self._traced:
            payload["_trace_ctx"] = self._trace_ctx
        try:
            fut = self.conn.call_async("report_generator_item", payload)
        except (ConnectionError, OSError):
            raise _StreamCancelled from None
        if self.index < _STREAM_EVENT_CAP:
            # per-yield instant for the timeline (ph="i" in Perfetto),
            # carrying the submitter's trace id so user spans, the task
            # span and its stream items correlate
            tc = self.spec.get("trace_ctx")
            self.core.events.record(
                self.task_id.hex(), "STREAM_ITEM",
                name=self.spec.get("name", ""), index=self.index,
                **({"trace_id": tc["trace_id"]} if tc else {}))
        if self.index < self._span_items:
            # per-yield marker span in the sampled trace: the pacing
            # shape of the stream's head, without a span per token
            self._trh.instant_span(
                f"yield[{self.index}]", "stream_item",
                ctx=self._trace_ctx, index=self.index, bytes=size)
        self.outstanding.append(fut)
        self.index += 1

    def _wait_for_credit(self) -> None:
        if self.bp > 0:
            # unacked window == unconsumed in-flight items: block here
            # until the consumer acks (pausing the user generator)
            if len(self.outstanding) >= self.bp:
                t0 = rtm.now()
                while len(self.outstanding) >= self.bp:
                    self._consume_reply(self.outstanding.popleft())
                _M_CREDIT_WAIT.observe_since(t0)
        else:
            # unbounded stream: just reap replies that already landed so
            # a long stream doesn't accumulate futures
            while self.outstanding and self.outstanding[0].done():
                self._consume_reply(self.outstanding.popleft())

    def _consume_reply(self, fut) -> None:
        try:
            reply = fut.result(None)
        except (ConnectionError, OSError, rpc.RpcError):
            # owner unreachable: nobody is listening to this stream
            raise _StreamCancelled from None
        if reply and reply.get("cancel"):
            raise _StreamCancelled

    def finish(self, cancelled: bool = False) -> dict:
        """Drain every outstanding report (so the owner has adopted all
        items before the completion sentinel lands), then build the task
        reply."""
        if cancelled:
            self.drain_quiet()
        else:
            try:
                while self.outstanding:
                    self._consume_reply(self.outstanding.popleft())
            except _StreamCancelled:
                cancelled = True
                self.drain_quiet()
        out = {"num_items": self.index}
        if cancelled:
            out["cancelled"] = True
        return {"results": [{"streaming": out}]}

    def drain_quiet(self) -> None:
        """Best-effort wait for in-flight reports (error/cancel paths):
        already-produced items should reach the owner before the task's
        terminal reply does, but nothing here may raise."""
        while self.outstanding:
            fut = self.outstanding.popleft()
            try:
                fut.result(30.0)
            except Exception:
                break


class _CompiledDagRunner:
    """Actor-side resident loop of one compiled DAG (docs/compiled_dag.md).

    Installed by the driver via ``__ray_dag_install__`` (an ordinary
    actor task over the pooled actor connection).  One daemon thread per
    (DAG, actor): each iteration it runs this actor's ops in the DAG's
    topological order — blocking read of every input channel, the bound
    method, one in-place write of the output channel — so repeated
    ``execute()`` calls cost ZERO task submissions here.  Error items
    forward downstream without executing the method; channel poisoning
    (teardown / worker death at the driver) unwinds the loop."""

    def __init__(self, worker: "WorkerProcess", payload: dict):
        from ray_tpu.experimental import channel as chan
        self.worker = worker
        self.core = worker.core
        self.dag_id = payload["dag_id"]
        self.name = payload.get("name", "dag")
        self.event_cap = int(payload.get("event_cap", 0))
        self.job_id = payload.get("job_id", "")
        self._chan_mod = chan
        self._stop = threading.Event()
        self._channels: Dict[bytes, Any] = {}
        self.ops = []
        try:
            for desc in payload["ops"]:
                bound = getattr(worker.actor_instance, desc["method"])
                self.ops.append({
                    "method": desc["method"],
                    "bound": bound,
                    "reads": [chan.ChannelReader(self._attach(r["id"]),
                                                 r["reader"])
                              for r in desc["reads"]],
                    "writer": chan.ChannelWriter(
                        self._attach(desc["out"]["id"])),
                    "args": desc["args"],
                    "kwargs": desc["kwargs"],
                })
        except BaseException:
            self._release()
            raise
        # threaded_ops (docs/compiled_dag.md): one resident thread PER OP
        # instead of one serial per-actor loop, so an actor appearing at
        # several pipeline depths (MPMD stage forward + backward) can
        # overlap execution indices — forward of microbatch t+1 proceeds
        # while backward of t still waits on its input channel.  Method
        # calls stay serialized through worker._method_mutex in _run_op;
        # only channel waits run concurrently.
        self.threaded = bool(payload.get("threaded_ops")) \
            and len(self.ops) > 1
        self._live_loops = len(self.ops) if self.threaded else 1
        self._live_lock = threading.Lock()
        if self.threaded:
            self._threads = [
                threading.Thread(
                    target=self._op_loop, args=(op,), daemon=True,
                    name=f"dag-loop-{self.dag_id[:8]}-op{i}")
                for i, op in enumerate(self.ops)]
            for t in self._threads:
                t.start()
        else:
            self._threads = [threading.Thread(
                target=self._loop, daemon=True,
                name=f"dag-loop-{self.dag_id[:8]}")]
            self._threads[0].start()
        if self.job_id:
            # a driver that dies without teardown() never poisons the
            # channels: on a detached actor this loop (and its channel
            # pins) would otherwise outlive the driver forever.  Watch
            # the driver's GCS job record and unwind when it finishes —
            # the channel waits honor _stop at every poison-check tick.
            self._watchdog = threading.Thread(
                target=self._watch_driver, daemon=True,
                name=f"dag-watch-{self.dag_id[:8]}")
            self._watchdog.start()

    _DRIVER_POLL_S = 10.0

    def _watch_driver(self) -> None:
        while not self._stop.wait(self._DRIVER_POLL_S):
            try:
                jobs = self.core.gcs.call("list_jobs", {}, timeout=5)
            except Exception:
                continue        # GCS hiccup: not a death verdict
            state = next((j.get("state") for j in jobs
                          if j.get("job_id") == self.job_id), None)
            if state is not None and state != "RUNNING":
                for ch in self._channels.values():
                    try:
                        ch.poison(self._chan_mod.POISON_WORKER_DIED)
                    except Exception:
                        pass
                self._stop.set()
                return

    def _attach(self, oid_bytes: bytes):
        ch = self._channels.get(oid_bytes)
        if ch is None:
            ch = self._chan_mod.Channel.attach(
                self.core.store, ObjectID(oid_bytes), timeout=10.0)
            self._channels[oid_bytes] = ch
        return ch

    def _release(self) -> None:
        for ch in self._channels.values():
            try:
                ch.close()
            except Exception:
                pass

    def _loop(self) -> None:
        from ray_tpu.exceptions import ChannelError
        idx = 0
        try:
            while not self._stop.is_set():
                for op in self.ops:
                    self._run_op(op, idx)
                idx += 1
        except ChannelError:
            pass        # poisoned (teardown / participant death): unwind
        except Exception:
            logger.exception("compiled DAG %s loop failed", self.dag_id[:8])
            self._poison_all()
        finally:
            self._loop_done()

    def _op_loop(self, op) -> None:
        """threaded_ops variant: one op, own execution-index counter.
        Per-channel FIFO order keeps indices aligned across threads."""
        from ray_tpu.exceptions import ChannelError
        idx = 0
        try:
            while not self._stop.is_set():
                self._run_op(op, idx)
                idx += 1
        except ChannelError:
            pass
        except Exception:
            logger.exception("compiled DAG %s op %s loop failed",
                             self.dag_id[:8], op["method"])
            self._poison_all()
        finally:
            self._loop_done()

    def _poison_all(self) -> None:
        # a loop dying with the actor still ALIVE is invisible to the
        # driver's liveness poll: poison every attached channel so
        # blocked peers unwind with DAGUnavailableError instead of
        # hanging forever
        for ch in self._channels.values():
            try:
                ch.poison(self._chan_mod.POISON_WORKER_DIED)
            except Exception:
                pass

    def _loop_done(self) -> None:
        """Last loop thread out releases the channel pins and
        self-removes; earlier exits only signal the others to stop."""
        self._stop.set()
        with self._live_lock:
            self._live_loops -= 1
            if self._live_loops > 0:
                return
        self._release()
        # self-remove so an unwound loop (driver death, poison, or
        # crash) doesn't leave a dead entry; _dag_teardown pops
        # before calling shutdown(), so this is a no-op there
        with self.worker._dag_lock:
            if self.worker._dag_runners.get(self.dag_id) is self:
                del self.worker._dag_runners[self.dag_id]

    def _record(self, idx: int, state: str, method: str, **extra) -> None:
        if idx >= self.event_cap:
            return
        from ray_tpu.dag.compiled_dag import _exec_task_id, _exec_trace_id
        self.core.events.record(
            _exec_task_id(self.dag_id, idx), state,
            name=f"dag:{self.name}:{method}",
            trace_id=_exec_trace_id(self.dag_id, idx), **extra)

    def _run_op(self, op, idx: int) -> None:
        chan = self._chan_mod
        raw = [r.read_raw(stop=self._stop) for r in op["reads"]]
        err_payload = next((p for p, f in raw if f & chan.FLAG_ERROR), None)
        if err_payload is not None:
            # an upstream stage failed this execution: forward ITS error
            # unchanged (mirrors the TaskError propagation semantics of
            # the classic task chain) and skip the method
            op["writer"].write_raw(err_payload, chan.FLAG_ERROR,
                                   stop=self._stop)
            return
        self._record(idx, "RUNNING", op["method"])
        t_exec = rtm.now()
        try:
            values = [ser.deserialize(p) for p, _f in raw]
            args = [values[d["i"]] if d["t"] == "read" else d["v"]
                    for d in op["args"]]
            kwargs = {k: (values[d["i"]] if d["t"] == "read" else d["v"])
                      for k, d in op["kwargs"].items()}
            aloop = self.worker._actor_event_loop
            if aloop is not None:
                # async actor: run the whole call on the actor's event
                # loop (awaiting coroutine results there), so DAG ops
                # interleave with classic calls under the actor's normal
                # asyncio serialization instead of racing them
                async def _call():
                    r = op["bound"](*args, **kwargs)
                    if inspect.isawaitable(r):
                        r = await r
                    return r

                result = asyncio.run_coroutine_threadsafe(
                    _call(), aloop).result()
            else:
                # sync actor: share the worker's method mutex with the
                # classic sequential path so actor state never sees two
                # concurrent method frames (threaded concurrency-group
                # actors already opted out of that guarantee)
                with self.worker._method_mutex:
                    result = op["bound"](*args, **kwargs)
                if inspect.isawaitable(result):
                    result = asyncio.run(result)
        except Exception as e:  # noqa: BLE001 - user errors cross the graph
            _M_EXEC.observe_since(op["method"], t_exec)
            err = e if isinstance(e, exc.TaskError) else exc.TaskError(
                op["method"], e, traceback.format_exc())
            head, views = ser.serialize(err, error_type=ser.ERROR_TASK)
            op["writer"].write_payload(head, views, flags=chan.FLAG_ERROR,
                                       stop=self._stop)
            self._record(idx, "FAILED", op["method"],
                         error_type=type(e).__name__)
            return
        _M_EXEC.observe_since(op["method"], t_exec)
        try:
            op["writer"].write(result, stop=self._stop)
        except exc.ChannelError:
            raise               # poison/teardown: unwind the loop
        except Exception as e:  # noqa: BLE001
            # a result that cannot be serialized (or exceeds the slot
            # capacity) must become an error ITEM, not kill the loop —
            # the driver is owed exactly one output per execution
            err = exc.TaskError(op["method"], e, traceback.format_exc())
            head, views = ser.serialize(err, error_type=ser.ERROR_TASK)
            op["writer"].write_payload(head, views, flags=chan.FLAG_ERROR,
                                       stop=self._stop)
            self._record(idx, "FAILED", op["method"],
                         error_type=type(e).__name__)
            return
        self._record(idx, "FINISHED", op["method"])

    def shutdown(self) -> None:
        """Teardown: the driver has already poisoned the channels, so a
        blocked read/write is waking up; stop, join, release pins."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._release()


class WorkerProcess:
    def __init__(self, args):
        self.worker_id = WorkerID.from_hex(args.worker_id)
        self.core = cw.CoreWorker(
            mode="worker",
            gcs_address=(args.gcs_host, args.gcs_port),
            raylet_address=(args.raylet_host, args.raylet_port),
            store_path=args.store_path,
            node_id=args.node_id,
            worker_id=self.worker_id,
            session_dir=args.session_dir,
        )
        cw.set_global_worker(self.core)

        # apply the runtime env (working_dir/py_modules/env_vars) BEFORE any
        # user code loads — cf. reference runtime-env agent setup happening
        # before the worker reports ready
        renv_blob = os.environ.get("RAY_TPU_RUNTIME_ENV")
        if renv_blob:
            import json
            from ray_tpu.runtime_env import setup_runtime_env
            desc = json.loads(renv_blob)
            setup_runtime_env(desc, self.core.gcs, args.session_dir)
            # nested tasks/actors submitted from this worker inherit the
            # same env (reference: job/parent runtime_env inheritance)
            self.core.job_runtime_env = desc
        # inline-return threshold, resolved once (a CONFIG attribute read
        # per returned value is measurable on the small-task hot path)
        inline_ret = CONFIG.rpc_inline_return_max_bytes
        self._inline_ret_max = (CONFIG.inline_object_max_bytes
                                if inline_ret < 0 else inline_ret)
        # actor state
        self.actor_instance: Any = None
        self.actor_id: Optional[str] = None
        self._actor_is_async = False
        self._actor_event_loop = None   # asyncio loop for async actors
        self._group_caps: Dict[str, int] = {}
        self._group_sems: Dict[str, Any] = {}   # async: per-group Semaphore
        self._group_pools: Optional[Dict[str, Any]] = None  # threaded
        # resident compiled-DAG loops installed on this actor
        # (docs/compiled_dag.md): dag_id -> _CompiledDagRunner
        self._dag_runners: Dict[str, _CompiledDagRunner] = {}
        self._dag_lock = threading.Lock()
        # serializes method frames between the classic sequential path
        # and resident DAG loop threads (RLock: a method that calls back
        # into itself via the same thread must not self-deadlock)
        self._method_mutex = threading.RLock()
        # per caller-stream ordered queues (ActorSchedulingQueue analog):
        # {stream_id: {"next": int, "buf": {seq: work}}}
        self._actor_streams: Dict[str, Dict[str, Any]] = {}
        self._actor_cv = threading.Condition()
        # normal-task FIFO
        self._queue: "list[tuple]" = []
        self._queue_cv = threading.Condition()
        self._exec_thread = threading.Thread(target=self._exec_loop,
                                             daemon=True)
        self._exec_thread.start()
        self._actor_thread = threading.Thread(target=self._actor_loop,
                                              daemon=True)
        self._actor_thread.start()

        # serve pushes from owners on the core worker's own server by
        # extending its dispatch
        self.core._extra_handler = self._handle
        core_handle = self.core._handle_rpc

        def dispatch(conn, method, payload):
            if method in ("push_task", "push_tasks", "actor_task",
                          "create_actor", "kill", "profile"):
                return self._handle(conn, method, payload)
            return core_handle(conn, method, payload)

        def fast(method, payload):
            # deferred-reply handlers that only buffer + notify: run them
            # inline on the reader thread (rpc.py fast path).  actor_task
            # never blocks (seq buffering; the actor loop replies).
            # push_tasks blocks only to resolve ObjectRef args — the
            # owner marks such specs (singleton frames, "_refs"), and
            # they take the pooled path so a slow dependency fetch can't
            # stall the connection's reader.
            if method == "actor_task":
                return True
            if method == "report_generator_item":
                # nested streaming: this worker owns a streaming task it
                # submitted; item adoption only buffers + notifies
                return True
            if method == "push_tasks":
                try:
                    return all(not s.get("_refs") for s in payload["specs"])
                except (TypeError, KeyError):
                    return False
            return False

        self.core._server.rebind(dispatch, fast_methods=fast)

        # register with the raylet; the raylet sends us requests
        # (create_actor, kill) back over this same duplex connection.
        # A worker must not outlive its raylet (fate-sharing, cf. reference
        # raylet-socket disconnect handling): exit when the conn drops.
        def _raylet_gone(_conn):
            import os
            logger.warning("raylet connection lost; worker exiting")
            os._exit(1)

        self.raylet_conn = rpc.connect((args.raylet_host, args.raylet_port),
                                       handler=dispatch,
                                       on_close=_raylet_gone)
        self.raylet_conn.call("register_worker", {
            "worker_id": args.worker_id,
            "address": list(self.core.address),
        })

    # ------------------------------------------------------------- dispatch
    def _handle(self, conn, method, p):
        if method == "push_tasks":
            return self._run_queued_batch(conn, p)
        if method == "push_task":
            return self._run_queued(p)
        if method == "actor_task":
            return self._run_actor_task(p)
        if method == "create_actor":
            return self._create_actor(p)
        if method == "kill":
            import os
            os._exit(1)
        if method == "profile":
            # on-demand flame sampling of this worker (reference
            # reporter_agent CPU profiling, reporter_agent.py:253).
            # With "device" set (gang profiling, `ray-tpu profile
            # --group --device`) the reply is the capture dict — a
            # jax.profiler device trace bracketing the host sampling
            # window when on TPU, a caveat string on CPU-only boxes.
            from ray_tpu._private.profiler import (profile_capture,
                                                   sample_folded)
            p = p or {}
            if "device" in p:
                return profile_capture(float(p.get("duration", 2.0)),
                                       device=bool(p.get("device")))
            return sample_folded(float(p.get("duration", 2.0)))
        if method == "dump_stacks":
            # instant per-thread stacks + short folded sample: a stalled
            # worker answers without gdb (`ray-tpu summary stacks`)
            from ray_tpu._private.profiler import dump_stacks, \
                sample_folded
            return {"threads": dump_stacks(),
                    "folded": sample_folded(
                        float((p or {}).get("duration", 0.2)))}
        raise rpc.RpcError(f"worker: unknown method {method}")

    # --------------------------------------------------------- normal tasks
    def _run_queued(self, spec) -> dict:
        """Enqueue and wait for completion on the executor thread, keeping
        per-worker execution strictly serial.

        ObjectRef args resolve HERE, on the push's own handler thread,
        BEFORE the FIFO: pipelined pushes ride independent dispatch
        threads, so push N+1 can reach the queue before push N.  If a
        task could enter the executor with unresolved deps, a reordered
        dependent (task2 queued ahead of the task1 it waits on) would
        block the single executor forever — a head-of-line deadlock
        found by the schedule fuzzer (tests/test_sched_fuzz.py)."""
        resolved = None
        try:
            resolved = self._resolve_args(spec["args"])
        except Exception as e:      # dep failed: report as task error
            return self._package_error(spec, e)
        done = threading.Event()
        out: dict = {}

        def cb(reply, err):
            if err is None:
                out["reply"] = reply
            else:
                out["raise"] = err
            done.set()

        with self._queue_cv:
            self._queue.append((spec, resolved, cb))
            self._queue_cv.notify()
        done.wait()
        if "raise" in out:
            raise out["raise"]
        return out["reply"]

    # raylint: disable=inline-handler-purity -- conditional fast method: the registration predicate routes ref-carrying specs (the only path into _resolve_args' blocking fetches) to the POOLED dispatcher; ref-free frames, the only ones dispatched inline, never leave the enqueue pass
    def _run_queued_batch(self, conn, p) -> "rpc.Deferred":
        """Batched ``push_tasks`` frame: enqueue every spec to the serial
        executor FIFO in frame order; the LAST completion resolves the
        deferred batch ack directly from the executor thread (no handler
        thread parked on the frame).  The owner guarantees only a
        singleton frame carries ObjectRef args
        (core_worker._drain_batch_locked), so the enqueue pass can never
        block on a result the frame itself is yet to produce.  For
        multi-spec frames each completion is ALSO streamed back
        immediately as a task_done push: a fast task batched behind a
        slow one resolves at its own finish time, not the frame's (the
        batch ack is the idempotent backstop for lost pushes)."""
        specs = p["specs"]
        if not specs:
            return {"results": []}   # nothing to defer on
        d = rpc.Deferred()
        state = {"left": len(specs), "results": [None] * len(specs)}
        lock = threading.Lock()
        stream = len(specs) > 1

        def finish(i, spec, res):
            if stream:
                try:
                    conn.push("task_done", {"task_id": spec["task_id"],
                                            "res": res})
                except Exception:
                    # dead socket, unpicklable/oversized payload, …: the
                    # batch ack is the authoritative backstop — a push
                    # failure must NEVER stop 'left' from reaching zero
                    # or the frame's Deferred ack (and the owner's lease
                    # loop with it) hangs forever
                    pass
            with lock:
                state["results"][i] = res
                state["left"] -= 1
                last = state["left"] == 0
            if last:
                d.resolve({"results": state["results"]})

        for i, spec in enumerate(specs):
            try:
                resolved = self._resolve_args(spec["args"])
            except Exception as e:      # dep failed: report as task error
                finish(i, spec, {"ok": self._package_error(spec, e)})
                continue

            def cb(reply, err, i=i, spec=spec):
                # non-Exception escapes (SystemExit, MemoryError) become
                # per-spec textual errors so the rest of the frame's acks
                # survive, mirroring the solo-push RemoteError path
                finish(i, spec, {"ok": reply} if err is None
                       else {"err": repr(err)})

            with self._queue_cv:
                self._queue.append((spec, resolved, cb))
                self._queue_cv.notify()
        return d

    def _exec_loop(self) -> None:
        while True:
            with self._queue_cv:
                while not self._queue:
                    self._queue_cv.wait()
                spec, resolved, cb = self._queue.pop(0)
            try:
                reply, err = self._execute(spec, resolved), None
            except BaseException as e:  # noqa: BLE001
                reply, err = None, e
            try:
                cb(reply, err)
            except Exception:
                logger.exception("task completion callback failed")

    def _resolve_args_inline_ok(self, blob: bytes):
        """Event-loop-safe arg resolution attempt for the async-actor
        hot path: small blobs with NO ObjectRef args unpickle inline —
        the two executor hops (resolve + package) cost more than a
        serve-sized payload's unpickle on this class of box (~40-150us
        each vs ~2-5us).  Returns (args, kwargs, []) or None when the
        blob is big or carries refs (whose _get_one may block on a
        store/remote fetch — those keep the executor path).

        Unpickling can run user ``__setstate__`` code on the loop, but
        that is not a new hazard for THIS actor class: async-actor
        methods themselves (sync ones included) already execute on the
        loop thread, so user code blocking it was always possible."""
        if len(blob) > 16384:
            return None
        args, kwargs = cloudpickle.loads(blob)
        if any(isinstance(a, cw.ObjectRef) for a in args) or \
                any(isinstance(v, cw.ObjectRef) for v in kwargs.values()):
            return None
        return args, kwargs, []

    def _resolve_args(self, blob: bytes) -> tuple:
        """Returns (args, kwargs, borrowed_oids); the caller must hand
        ``borrowed_oids`` to core.release_borrowed after execution so arg
        pins/caches don't accumulate in pooled workers."""
        args, kwargs = cloudpickle.loads(blob)
        borrowed = []
        resolved = []
        for a in args:
            if isinstance(a, cw.ObjectRef):
                borrowed.append(a.id)
                resolved.append(self.core._get_one(a, None))
            else:
                resolved.append(a)
        rkw = {}
        for k, v in kwargs.items():
            if isinstance(v, cw.ObjectRef):
                borrowed.append(v.id)
                rkw[k] = self.core._get_one(v, None)
            else:
                rkw[k] = v
        return tuple(resolved), rkw, borrowed

    def _execute(self, spec, resolved=None) -> dict:
        from ray_tpu.util.tracing import tracing_helper as trh
        from ray_tpu.util.tracing.tracing_helper import \
            propagate_trace_context
        fn = self.core.load_function(spec["fn_key"])
        self.core.current_task_id = TaskID(spec["task_id"])
        trace_ctx = spec.get("trace_ctx")
        self.core.events.record(TaskID(spec["task_id"]).hex(), "RUNNING",
                                name=spec.get("name", ""),
                                **({"trace_id": trace_ctx["trace_id"]}
                                   if trace_ctx else {}))
        # flight-recorder breadcrumb (ring_only: never shipped to the
        # GCS table — it lands in this worker's crash dossier instead)
        cev.emit(cev.TASK_RUNNING, spec.get("name", ""), ring_only=True,
                 task_id=TaskID(spec["task_id"]).hex())
        # execution span (docs/observability.md): when the submitter's
        # trace is sampled, this task's whole worker-side execution is
        # one span, child of the submitting span
        exec_span = trh.open_span(f"task:{spec.get('name', '')}", "task",
                                  ctx=trace_ctx)
        # join the submitter's trace: user spans inside the task nest
        # under the caller's span (auto span injection); nested
        # submissions become children of the execution span
        propagate_trace_context(exec_span.ctx() if exec_span is not None
                                else trace_ctx)
        borrowed = []
        t_exec = None
        err_type = None
        try:
            args, kwargs, borrowed = (resolved if resolved is not None
                                      else self._resolve_args(spec["args"]))
            t_exec = rtm.now()
            result = fn(*args, **kwargs)
            return self._package_results(spec, result)
        except Exception as e:  # noqa: BLE001 - user errors cross the wire
            err_type = type(e).__name__
            return self._package_error(spec, e)
        finally:
            # observed in the finally so the sample covers generator
            # tasks (fn() only CREATES the generator — the iteration
            # happens inside _package_results/_StreamSession) and
            # failed executions alike
            if t_exec is not None:
                _M_EXEC.observe_since(spec.get("name", ""), t_exec)
            if exec_span is not None:
                exec_span.end(trh.ERROR if err_type else trh.OK,
                              error_type=err_type,
                              task_id=TaskID(spec["task_id"]).hex())
            propagate_trace_context(None)
            self.core.release_borrowed(borrowed)

    def _package_error(self, spec, e: BaseException) -> dict:
        tb = traceback.format_exc()
        cev.emit(cev.TASK_FAILED,
                 f"{spec.get('name') or spec.get('method', '')}: "
                 f"{type(e).__name__}: {e}",
                 severity="WARNING", ring_only=True,
                 error_type=type(e).__name__)
        if isinstance(e, exc.TaskError):
            # an upstream dependency already failed: propagate ITS error
            # unchanged (re-wrapping nests quoted tracebacks
            # exponentially down a task chain; cf. Ray's RayTaskError
            # propagation semantics)
            err = e
        else:
            err = exc.TaskError(spec.get("name", ""), e, tb)
        head, views = ser.serialize(err, error_type=ser.ERROR_TASK)
        data = ser.to_flat_bytes(head, views)
        from ray_tpu.runtime.core_worker import num_return_slots
        return {"results": [{"data": data, "error": ser.ERROR_TASK}
                            for _ in range(
                                num_return_slots(spec["num_returns"]))]}

    def _package_results(self, spec, result) -> dict:
        n = spec["num_returns"]
        if n == "dynamic":
            return self._package_dynamic(spec, result)
        if n == "streaming":
            return self._package_streaming(spec, result)
        if n == 0:
            values = []
        elif n == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != n:
                return self._package_error(spec, ValueError(
                    f"task declared num_returns={n} but returned "
                    f"{len(values)} values"))
        results = []
        task_id = TaskID(spec["task_id"])
        for i, value in enumerate(values):
            head, views = ser.serialize(value)
            size = ser.serialized_size(head, views)
            if size <= self._inline_ret_max:
                results.append({"data": ser.to_flat_bytes(head, views)})
            else:
                oid = ObjectID.for_task_return(task_id, i)
                self.core.store_put(oid, head, views)
                # size feeds the owner's locality/prefetch lease hints
                results.append({"location": self.core.node_id,
                                "size": size})
        return {"results": results}

    def _package_dynamic(self, spec, result) -> dict:
        """num_returns="dynamic": each yielded item becomes its own object
        at return index j+1; the caller's slot-0 ref resolves to an
        ObjectRefGenerator over them (reference _raylet.pyx:169 semantics —
        the generator is consumed to completion, not streamed)."""
        try:
            iterator = iter(result)
        except TypeError:
            return self._package_error(spec, TypeError(
                'num_returns="dynamic" requires the task to return an '
                f"iterable, got {type(result).__name__}"))
        # user exceptions raised while iterating surface as themselves
        values = list(iterator)
        task_id = TaskID(spec["task_id"])
        subs = []
        for j, value in enumerate(values):
            head, views = ser.serialize(value)
            size = ser.serialized_size(head, views)
            if size <= self._inline_ret_max:
                subs.append({"data": ser.to_flat_bytes(head, views)})
            else:
                oid = ObjectID.for_task_return(task_id, j + 1)
                self.core.store_put(oid, head, views)
                subs.append({"location": self.core.node_id, "size": size})
        return {"results": [{"dynamic": subs}]}

    def _package_streaming(self, spec, result) -> dict:
        """num_returns="streaming": drive the user generator yield by
        yield, delivering each item to the owner as it is produced (see
        _StreamSession) instead of materializing the whole stream.  The
        task reply is just the completion sentinel."""
        try:
            iterator = iter(result)
        except TypeError:
            return self._package_error(spec, TypeError(
                'num_returns="streaming" requires the task to return an '
                f"iterable or generator, got {type(result).__name__}"))
        sess = _StreamSession(self.core, spec, self._inline_ret_max)
        try:
            for value in iterator:
                sess.send(value)
            return sess.finish()
        except _StreamCancelled:
            return sess.finish(cancelled=True)
        except Exception as e:  # noqa: BLE001 - user errors cross the wire
            # deliver already-reported items before the failure lands:
            # the consumer drains the arrived prefix, THEN raises
            sess.drain_quiet()
            return self._package_error(spec, e)

    async def _package_streaming_async(self, spec, agen) -> dict:
        """Async-generator variant (async actors): iteration interleaves
        on the event loop; each report (blocking RPC + possible
        backpressure wait) runs in the default executor so a paused
        stream never stalls the actor's loop."""
        import asyncio
        import functools
        loop = asyncio.get_running_loop()
        sess = _StreamSession(self.core, spec, self._inline_ret_max)
        try:
            async for value in agen:
                await loop.run_in_executor(None, sess.send, value)
            # finish() blocks on the tail reports' (possibly parked)
            # replies — keep that off the loop too
            return await loop.run_in_executor(None, sess.finish)
        except _StreamCancelled:
            return await loop.run_in_executor(
                None, functools.partial(sess.finish, cancelled=True))
        except Exception as e:  # noqa: BLE001
            await loop.run_in_executor(None, sess.drain_quiet)
            return self._package_error(spec, e)

    # --------------------------------------------------------------- actors
    def _create_actor(self, p) -> dict:
        import inspect

        creation = cloudpickle.loads(p["spec"])
        cls = self.core.load_function(creation["cls_key"])
        args, kwargs, _borrowed = self._resolve_args(creation["args"])
        self.actor_id = p["actor_id"]
        self.core.current_actor_id = p["actor_id"]  # get_runtime_context()
        groups = {str(g): int(c)
                  for g, c in (creation.get("concurrency_groups")
                               or {}).items()}
        self._actor_is_async = any(
            inspect.iscoroutinefunction(m)
            or inspect.isasyncgenfunction(m)
            for _n, m in inspect.getmembers(cls, callable))
        max_concurrency = creation.get("max_concurrency")
        if max_concurrency is None:
            # reference defaults: async actors allow 1000 concurrent
            # coroutines, sync actors are serial — but an EXPLICIT
            # max_concurrency=1 on an async actor is honored (the user
            # asked for serialized execution)
            max_concurrency = 1000 if self._actor_is_async else 1
        max_concurrency = int(max_concurrency)
        self._group_caps = {"_default": max_concurrency, **groups}
        if self._actor_is_async:
            # Async actor (cf. reference fiber.h + async actor event loop,
            # _raylet.pyx:1121): one asyncio loop owns all method
            # execution; up to the group's cap of coroutines interleave at
            # await points, sync methods block the loop (reference
            # semantics — actor state is only ever touched from this
            # thread).
            import asyncio
            self._actor_event_loop = asyncio.new_event_loop()
            threading.Thread(target=self._actor_event_loop.run_forever,
                             daemon=True,
                             name="actor-asyncio").start()
            self._group_sems = {g: asyncio.Semaphore(c)
                                for g, c in self._group_caps.items()}
        elif max_concurrency > 1 or groups:
            # Threaded actor (cf. reference ConcurrencyGroupManager /
            # BoundedExecutor, src/ray/core_worker/transport/
            # concurrency_group_manager.h): methods dispatch in submission
            # order but may execute concurrently, bounded per group.
            from concurrent.futures import ThreadPoolExecutor
            self._group_pools = {
                g: ThreadPoolExecutor(max_workers=c,
                                      thread_name_prefix=f"actor-{g}")
                for g, c in self._group_caps.items()}
        self.actor_instance = cls(*args, **kwargs)
        self.core.gcs.call("actor_ready", {
            "actor_id": p["actor_id"],
            "address": list(self.core.address)})
        logger.info("actor %s ready (%s)", p["actor_id"][:8],
                    type(self.actor_instance).__name__)
        return {"ok": True}

    def _run_actor_task(self, spec) -> "rpc.Deferred":
        """Buffer until this (stream, seq)'s turn; the actor thread that
        executes the call resolves the deferred reply directly — no
        handler thread parks per buffered seq, so deep pipelines hold no
        dispatch threads and the completion skips a wake hop."""
        d = rpc.Deferred()
        with self._actor_cv:
            stream = self._actor_streams.setdefault(
                spec.get("stream", ""), {"next": 0, "buf": {}})
            stream["buf"][spec["seq"]] = (spec, d)
            self._actor_cv.notify_all()
        return d

    def _next_actor_work(self):
        for stream in self._actor_streams.values():
            if stream["next"] in stream["buf"]:
                work = stream["buf"].pop(stream["next"])
                stream["next"] += 1
                return work
        return None

    def _actor_loop(self) -> None:
        while True:
            with self._actor_cv:
                work = self._next_actor_work()
                while work is None:
                    self._actor_cv.wait()
                    work = self._next_actor_work()
            spec, d = work
            if self._actor_event_loop is not None:
                self._dispatch_async(spec, d)
            elif self._group_pools is not None:
                try:
                    group = self._method_group(spec)
                except ValueError as e:
                    d.resolve(self._package_error(spec, e))
                    continue
                self._group_pools[group].submit(
                    self._run_actor_work, spec, d)
            else:
                self._run_actor_work(spec, d)

    def _method_group(self, spec) -> str:
        """Concurrency group for a call: per-call override, else the
        @method(concurrency_group=...) declaration, else the default.
        An undeclared group name is an error (reference semantics) — a
        silent fallback would void the cap the caller relied on."""
        g = spec.get("group")
        if not g and self.actor_instance is not None:
            m = getattr(type(self.actor_instance), spec.get("method", ""),
                        None)
            opts = getattr(m, "__ray_tpu_method_opts__", None) or {}
            g = opts.get("concurrency_group")
        if not g:
            return "_default"
        if g not in self._group_caps:
            raise ValueError(
                f"concurrency group {g!r} was not declared on this actor "
                f"(declared: {sorted(k for k in self._group_caps if k != '_default')})")
        return g

    def _run_actor_work(self, spec, d) -> None:
        try:
            d.resolve(self._execute_actor(spec))
        except BaseException as e:  # noqa: BLE001
            d.fail(e)

    def _dispatch_async(self, spec, d) -> None:
        """Schedule one call onto the actor's event loop; the dispatcher
        never blocks, so calls pipeline up to their group's semaphore."""
        import asyncio

        async def run():
            try:
                try:
                    sem = self._group_sems[self._method_group(spec)]
                except ValueError as e:
                    d.resolve(self._package_error(spec, e))
                    return
                async with sem:
                    d.resolve(await self._execute_actor_async(spec))
            except BaseException as e:  # noqa: BLE001
                d.fail(e)

        asyncio.run_coroutine_threadsafe(run(), self._actor_event_loop)

    def _begin_actor_call(self, spec):
        """Shared prologue of sync/async actor execution: liveness guard
        plus task bookkeeping (incl. joining the caller's trace).  Returns
        ``(error_reply_or_None, exec_span_or_None)`` — the error reply
        short-circuits the call; the span (opened only for sampled
        traces) is ended by the caller's finally."""
        from ray_tpu.util.tracing import tracing_helper as trh
        from ray_tpu.util.tracing.tracing_helper import \
            propagate_trace_context
        if self.actor_instance is None:
            return self._package_error(
                spec, exc.ActorDiedError("actor not initialized")), None
        self.core.current_task_id = TaskID(spec["task_id"])
        trace_ctx = spec.get("trace_ctx")
        self.core.events.record(TaskID(spec["task_id"]).hex(), "RUNNING",
                                name=spec.get("method", ""),
                                actor_id=spec.get("actor_id", ""),
                                **({"trace_id": trace_ctx["trace_id"]}
                                   if trace_ctx else {}))
        cev.emit(cev.TASK_RUNNING, spec.get("method", ""), ring_only=True,
                 task_id=TaskID(spec["task_id"]).hex(),
                 actor_id=spec.get("actor_id"))
        exec_span = trh.open_span(
            f"task:{spec.get('method', '')}", "actor_task", ctx=trace_ctx)
        propagate_trace_context(exec_span.ctx() if exec_span is not None
                                else trace_ctx)
        return None, exec_span

    async def _execute_actor_async(self, spec) -> dict:
        """Async-actor execution: coroutine methods await on the loop
        (interleaving with other calls of their group); sync methods run
        inline on the loop thread, so actor state is single-threaded.
        Arg resolution and result packaging do blocking IO (shm / RPC)
        and run in the default executor to keep the loop responsive —
        EXCEPT for the serve-shaped hot path (small ref-free args in,
        small JSON-ish result out), which stays inline: at serving QPS
        the two executor round-trips dominate a no-op request's replica
        cost (docs/rpc_fastpath.md inline-return note)."""
        import asyncio
        import functools

        from ray_tpu.util.tracing import tracing_helper as trh
        from ray_tpu.util.tracing.tracing_helper import \
            propagate_trace_context
        err, exec_span = self._begin_actor_call(spec)
        if err is not None:
            return err
        loop = asyncio.get_running_loop()
        borrowed = []
        t_exec = None
        err_type = None
        try:
            resolved = self._resolve_args_inline_ok(spec["args"])
            if resolved is None:
                resolved = await loop.run_in_executor(
                    None, self._resolve_args, spec["args"])
            args, kwargs, borrowed = resolved
            if spec["method"] == "__ray_terminate__":
                import os
                os._exit(0)
            dag_reply = self._maybe_dag_control(spec, args)
            if dag_reply is not None:
                return dag_reply
            import inspect
            method = getattr(self.actor_instance, spec["method"])
            t_exec = rtm.now()
            result = method(*args, **kwargs)
            if inspect.isawaitable(result):
                result = await result
            if spec["num_returns"] == "streaming" \
                    and inspect.isasyncgen(result):
                # async-generator streaming: iterate on the loop, report
                # off it (see _package_streaming_async)
                return await self._package_streaming_async(spec, result)
            if spec["num_returns"] == 1 and _probe_small(
                    result, min(32768, self._inline_ret_max)) >= 0:
                # bounded-size scalar/container result: serialize + the
                # inline-return reply build are cheaper than the
                # executor hop, and cannot block the loop measurably.
                # Budget clamped to the inline-return threshold so this
                # branch can never reach _package_results' store_put
                # (a blocking shm write) on the loop.
                return self._package_results(spec, result)
            return await loop.run_in_executor(
                None, functools.partial(self._package_results, spec,
                                        result))
        except Exception as e:  # noqa: BLE001
            err_type = type(e).__name__
            return self._package_error(spec, e)
        finally:
            # in the finally: covers async-generator streaming (the
            # iteration happens in _package_streaming_async) and errors
            if t_exec is not None:
                _M_EXEC.observe_since(spec.get("method", ""), t_exec)
            if exec_span is not None:
                exec_span.end(trh.ERROR if err_type else trh.OK,
                              error_type=err_type,
                              task_id=TaskID(spec["task_id"]).hex())
            propagate_trace_context(None)
            self.core.release_borrowed(borrowed)

    # ------------------------------------------------- compiled DAG loops
    def _dag_install(self, p: dict) -> dict:
        """``__ray_dag_install__``: start this actor's resident loop for
        one compiled DAG (rides the ordinary pooled actor-task path)."""
        with self._dag_lock:
            if p["dag_id"] in self._dag_runners:
                raise exc.RayTpuError(
                    f"compiled DAG {p['dag_id'][:8]} is already installed "
                    f"on this actor")
            runner = _CompiledDagRunner(self, p)
            self._dag_runners[p["dag_id"]] = runner
        return {"ok": True, "ops": len(runner.ops)}

    def _dag_teardown(self, p: dict) -> dict:
        """``__ray_dag_teardown__``: stop the loop and drop its pins
        (the driver poisoned the channels before calling this)."""
        with self._dag_lock:
            runner = self._dag_runners.pop(p["dag_id"], None)
        if runner is not None:
            runner.shutdown()
        return {"ok": True}

    def _maybe_dag_control(self, spec, args) -> Optional[dict]:
        """Compiled-DAG control methods shared by the sync and async
        actor execution paths; returns a reply dict or None."""
        if spec["method"] == "__ray_dag_install__":
            return self._package_results(spec, self._dag_install(args[0]))
        if spec["method"] == "__ray_dag_teardown__":
            return self._package_results(spec, self._dag_teardown(args[0]))
        return None

    def _execute_actor(self, spec) -> dict:
        from ray_tpu.util.tracing import tracing_helper as trh
        from ray_tpu.util.tracing.tracing_helper import \
            propagate_trace_context
        err, exec_span = self._begin_actor_call(spec)
        if err is not None:
            return err
        borrowed = []
        t_exec = None
        err_type = None
        try:
            args, kwargs, borrowed = self._resolve_args(spec["args"])
            if spec["method"] == "__ray_terminate__":
                import os
                os._exit(0)
            dag_reply = self._maybe_dag_control(spec, args)
            if dag_reply is not None:
                return dag_reply
            method = getattr(self.actor_instance, spec["method"])
            t_exec = rtm.now()
            if self._group_pools is None:
                # sequential actor: resident compiled-DAG loops share
                # this mutex, so actor state never sees two concurrent
                # method frames; threaded concurrency-group actors opted
                # out of that guarantee and skip it.  _package_results
                # stays INSIDE the mutex: a streaming generator's body
                # runs lazily in there and is still this method's frame.
                with self._method_mutex:
                    result = method(*args, **kwargs)
                    return self._package_results(spec, result)
            result = method(*args, **kwargs)
            return self._package_results(spec, result)
        except Exception as e:  # noqa: BLE001
            err_type = type(e).__name__
            return self._package_error(spec, e)
        finally:
            # finally-observed: covers sync-generator streaming (driven
            # inside _package_results) and failed calls
            if t_exec is not None:
                _M_EXEC.observe_since(spec.get("method", ""), t_exec)
            if exec_span is not None:
                exec_span.end(trh.ERROR if err_type else trh.OK,
                              error_type=err_type,
                              task_id=TaskID(spec["task_id"]).hex())
            propagate_trace_context(None)
            self.core.release_borrowed(borrowed)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet-host", required=True)
    parser.add_argument("--raylet-port", type=int, required=True)
    parser.add_argument("--gcs-host", required=True)
    parser.add_argument("--gcs-port", type=int, required=True)
    parser.add_argument("--worker-id", required=True)
    parser.add_argument("--store-path", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--node-id", required=True)
    args = parser.parse_args()
    setup_component_logging("worker", args.session_dir)
    from ray_tpu._private.logging_utils import enable_stack_dumps
    enable_stack_dumps(args.session_dir)
    if os.environ.get("RAY_TPU_PROFILE_STARTUP"):
        import cProfile
        import pstats
        prof = cProfile.Profile()
        prof.enable()
        worker = WorkerProcess(args)
        prof.disable()
        path = os.path.join(args.session_dir, "logs",
                            f"startup-{args.worker_id[:8]}.prof")
        pstats.Stats(prof).dump_stats(path)
        logger.info("startup profile: %s", path)
    else:
        worker = WorkerProcess(args)
    logger.info("worker %s serving at %s", args.worker_id[:8],
                worker.core.address)
    threading.Event().wait()  # serve forever; raylet kills us


if __name__ == "__main__":
    main()
