"""Prefork worker zygote: fork warm worker processes in milliseconds.

On this class of host, interpreter startup is dominated by
environment-mandated imports (a TPU PJRT plugin sitecustomize pulls jax
into EVERY python process: ~8 s each).  The reference amortizes worker
startup with a prestarted pool (worker_pool.cc); the zygote goes
further: ONE process per raylet pays the import cost, then every python
worker is an ``os.fork()`` away (~10 ms), giving this box reference-like
actor/task worker density.

Mechanics:
  - The raylet launches ``python -m ray_tpu.runtime.worker_zygote
    --socket <path>`` once (eagerly, so it warms while the cluster
    boots) and sends framed spawn requests over the unix socket.
  - Each request is ONE fork: the parent replies with the child pid
    immediately (it knows it from fork()), and SIGCHLD is set to
    SIG_IGN so exited workers auto-reap — no zombies, no waitpid, no
    intermediate process.  (The first design double-forked so workers
    reparented to init; that cost two page-table copies of a jax-laden
    process plus a blocking waitpid PER SPAWN, serializing mass actor
    creation at ~80 ms/fork.  The worker resets SIGCHLD to SIG_DFL so
    user subprocess code sees normal child semantics.)
  - The worker child starts a new session, points stdio at its log
    files, swaps env/argv/config, closes inherited sockets, and calls
    ``worker_main.main()`` exactly as an exec'd worker would.

Workers that need a different interpreter (pip runtime envs) or
language (cpp) keep the exec path — the raylet falls back automatically.
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import socket
import struct
import sys

_FRAME = struct.Struct("<I")


def send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj)
    sock.sendall(_FRAME.pack(len(payload)) + payload)


def recv_msg(sock: socket.socket):
    head = _recv_exact(sock, _FRAME.size)
    if head is None:
        return None
    (n,) = _FRAME.unpack(head)
    body = _recv_exact(sock, n)
    return None if body is None else pickle.loads(body)


def _recv_exact(sock: socket.socket, n: int):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _become_worker(req: dict) -> None:
    """Runs in the forked child: turn this fork into a real worker."""
    import signal
    signal.signal(signal.SIGCHLD, signal.SIG_DFL)
    os.setsid()
    try:
        # forked children keep the zygote's cmdline in ps; at least fix
        # the comm name so `ps -C`/top distinguish workers from the
        # zygote (15-char kernel limit)
        with open("/proc/self/comm", "w") as f:
            f.write("ray_tpu_worker")
    except OSError:
        pass
    devnull = os.open(os.devnull, os.O_RDONLY)
    out = os.open(req["stdout"], os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                  0o644)
    err = os.open(req["stderr"], os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                  0o644)
    os.dup2(devnull, 0)
    os.dup2(out, 1)
    os.dup2(err, 2)
    for fd in (devnull, out, err):
        if fd > 2:
            os.close(fd)
    os.chdir(req["cwd"])
    os.environ.clear()
    os.environ.update(req["env"])
    # the zygote's CONFIG was resolved from ITS env; re-resolve from the
    # worker's blob (same raylet -> normally identical, but exact is free)
    from ray_tpu._private.config import CONFIG
    blob = req["env"].get("RAY_TPU_SYSTEM_CONFIG", "")
    try:
        CONFIG.set_overrides(json.loads(blob) if blob else {})
    except (ValueError, TypeError):
        pass
    # the zygote imported jax but never initialized a backend; the env
    # update above covers XLA_FLAGS (read at first backend use), and the
    # platform choice must be re-pinned through jax.config because
    # plugin discovery overrides the plain env var
    plat = req["env"].get("JAX_PLATFORMS")
    if plat:
        try:
            import jax
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass
    sys.argv = req["argv"]
    from ray_tpu.runtime import worker_main
    # os._exit (not sys.exit) everywhere: a forked worker must never run
    # the zygote's atexit/teardown.  But a crash has to be visible —
    # traceback to the redirected stderr (.err log) and a nonzero status.
    try:
        worker_main.main()
    except SystemExit as e:
        code = e.code if isinstance(e.code, int) else (0 if e.code is None
                                                       else 1)
        if code != 0:
            import traceback
            traceback.print_exc()
            sys.stderr.flush()
        os._exit(code)
    except BaseException:
        import traceback
        traceback.print_exc()
        sys.stderr.flush()
        os._exit(1)
    os._exit(0)


def _handle_conn(conn: socket.socket, listener: socket.socket) -> None:
    while True:
        req = recv_msg(conn)
        if req is None:
            return
        sys.stdout.flush()
        sys.stderr.flush()
        pid = os.fork()
        if pid == 0:
            listener.close()
            conn.close()
            _become_worker(req)         # never returns
            os._exit(1)
        send_msg(conn, {"pid": pid})


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--socket", required=True)
    args = ap.parse_args()

    import signal as _signal

    # exited workers auto-reap (children of the zygote under the
    # single-fork protocol); _become_worker resets SIG_DFL in workers
    _signal.signal(_signal.SIGCHLD, _signal.SIG_IGN)
    # die with the raylet: a SIGKILLed raylet must not orphan a warm
    # jax-loaded process forever (PR_SET_PDEATHSIG is cleared on fork,
    # so spawned workers don't inherit the tie)
    try:
        import ctypes
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        PR_SET_PDEATHSIG = 1
        libc.prctl(PR_SET_PDEATHSIG, _signal.SIGKILL)
        if os.getppid() == 1:          # raylet already gone
            return
    except OSError:
        pass

    # the expensive part, paid exactly once per raylet: the runtime (and
    # whatever sitecustomize insists every process imports)
    from ray_tpu.runtime import worker_main       # noqa: F401

    try:
        os.unlink(args.socket)
    except FileNotFoundError:
        pass
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(args.socket)
    listener.listen(8)
    while True:
        conn, _ = listener.accept()
        try:
            _handle_conn(conn, listener)
        except OSError:
            pass
        finally:
            conn.close()


if __name__ == "__main__":
    main()
