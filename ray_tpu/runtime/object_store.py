"""Python client for the native shared-memory object store.

Wraps ``csrc/shmstore.cc`` (built to ``ray_tpu/_core/libshmstore.so``) via
ctypes — the binding role the reference's ``_raylet.pyx`` Cython layer plays
for plasma (/root/reference/python/ray/_raylet.pyx,
src/ray/object_manager/plasma/client.h).  Every local process maps the same
shm segment, so a ``get`` yields a zero-copy memoryview into shared memory
that ``serialization.deserialize`` turns into numpy views without copying.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import threading
import time
from typing import Optional, Tuple

from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import ObjectStoreFullError

_LIB_PATH = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                         "_core", "libshmstore.so")

_DEFAULT_TABLE = 65536
_DEFAULT_FREELIST = 32768


def _load_lib() -> ctypes.CDLL:
    if not os.path.exists(_LIB_PATH):
        import subprocess
        csrc = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(__file__))), "csrc")
        subprocess.run(["make", "-C", csrc], check=True, capture_output=True)
    lib = ctypes.CDLL(_LIB_PATH)
    lib.store_segment_size.restype = ctypes.c_uint64
    lib.store_segment_size.argtypes = [ctypes.c_uint64, ctypes.c_uint32,
                                       ctypes.c_uint32]
    lib.store_init.restype = ctypes.c_int
    lib.store_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                               ctypes.c_uint32, ctypes.c_uint32]
    lib.store_validate.restype = ctypes.c_int
    lib.store_validate.argtypes = [ctypes.c_void_p]
    lib.store_create.restype = ctypes.c_longlong
    lib.store_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint64, ctypes.c_uint64,
                                 ctypes.c_int]
    for name in ("store_seal", "store_release", "store_contains",
                 "store_delete", "store_abort"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.store_get.restype = ctypes.c_int
    lib.store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.POINTER(ctypes.c_uint64)]
    lib.store_seal_count.restype = ctypes.c_uint64
    lib.store_seal_count.argtypes = [ctypes.c_void_p]
    lib.store_stats.restype = None
    lib.store_stats.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_uint64)]
    lib.store_list.restype = ctypes.c_uint32
    lib.store_list.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_uint32]
    return lib


_lib: Optional[ctypes.CDLL] = None


def get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        _lib = _load_lib()
    return _lib


class SharedMemoryStore:
    """One per node.  ``create_segment`` (daemon) / ``attach`` (clients)."""

    def __init__(self, path: str, mm: mmap.mmap, created: bool):
        self._path = path
        self._mm = mm
        self._buf = memoryview(mm)
        self._base = ctypes.addressof(ctypes.c_char.from_buffer(mm))
        self._lib = get_lib()
        self._created = created
        self._prefault_stop = threading.Event()
        self._prefault_thread: Optional[threading.Thread] = None

    def prefault_async(self, chunk_bytes: int = 32 * 1024 * 1024,
                       duty: float = 0.33,
                       initial_delay: float = 3.0) -> None:
        """Touch every segment page from a background thread.

        On VMs with on-demand memory paging (this box: ~28 us per 4 KiB
        first-touch fault, ~0.15 GiB/s) a cold multi-GiB put is fault-
        bound, not memcpy-bound (warm writes run at ~4.5 GiB/s).  The
        kernel can't populate faster either (MADV_POPULATE_WRITE measures
        the same), so the only win is moving the faults OFF the put
        critical path.

        The walk is deliberately polite: it starts after `initial_delay`
        (daemon startup is the worst moment to steal the core on a
        1-core host) and holds a `duty` CPU duty cycle by sleeping
        proportionally to each chunk's measured fault time — the old
        fixed 2 ms yield ran at ~99% duty and cost the foreground
        plasma paths ~40% of their ops/s while it walked."""
        if self._prefault_thread is not None:
            return

        def run():
            try:
                libc = ctypes.CDLL("libc.so.6", use_errno=True)
            except OSError:
                return
            MADV_POPULATE_WRITE = 23
            total = len(self._mm)
            off = 0
            if self._prefault_stop.wait(initial_delay):
                return
            while off < total and not self._prefault_stop.is_set():
                n = min(chunk_bytes, total - off)
                t0 = time.monotonic()
                rc = libc.madvise(ctypes.c_void_p(self._base + off),
                                  ctypes.c_size_t(n),
                                  MADV_POPULATE_WRITE)
                if rc != 0:      # old kernel / unsupported mapping: stop
                    return
                off += n
                busy = time.monotonic() - t0
                # already-resident chunks return in ~us; don't sleep for
                # those, only pay the duty cycle on real fault work
                if busy > 0.001:
                    self._prefault_stop.wait(busy * (1.0 - duty) / duty)

        self._prefault_thread = threading.Thread(
            target=run, name="store-prefault", daemon=True)
        self._prefault_thread.start()

    # ------------------------------------------------------------ lifecycle
    @classmethod
    def create_segment(cls, path: str, capacity: int,
                       table_size: int = _DEFAULT_TABLE,
                       freelist: int = _DEFAULT_FREELIST) -> "SharedMemoryStore":
        lib = get_lib()
        total = lib.store_segment_size(capacity, table_size, freelist)
        fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o600)
        try:
            os.ftruncate(fd, total)
            mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        store = cls(path, mm, created=True)
        rc = lib.store_init(store._base, capacity, table_size, freelist)
        if rc != 0:
            raise OSError(f"store_init failed: {rc}")
        return store

    @classmethod
    def attach(cls, path: str, timeout: float = 10.0) -> "SharedMemoryStore":
        lib = get_lib()
        deadline = time.monotonic() + timeout
        while True:
            try:
                fd = os.open(path, os.O_RDWR)
                size = os.fstat(fd).st_size
                if size > 0:
                    mm = mmap.mmap(fd, size)
                    os.close(fd)
                    store = cls(path, mm, created=False)
                    if lib.store_validate(store._base) == 0:
                        return store
                    store.close()
                else:
                    os.close(fd)
            except FileNotFoundError:
                pass
            if time.monotonic() > deadline:
                raise TimeoutError(f"object store segment not ready: {path}")
            time.sleep(0.02)

    def close(self) -> None:
        self._prefault_stop.set()
        if self._prefault_thread is not None:
            self._prefault_thread.join(timeout=5)
        self._buf.release()
        try:
            self._mm.close()
        except BufferError:
            pass  # outstanding zero-copy views; leave mapping to process exit

    def unlink(self) -> None:
        try:
            os.unlink(self._path)
        except FileNotFoundError:
            pass

    # ------------------------------------------------------------- objects
    def create(self, object_id: ObjectID, size: int, meta: int = 0,
               allow_evict: bool = True) -> memoryview:
        rc = self._lib.store_create(self._base, object_id.binary(), size,
                                    meta, 1 if allow_evict else 0)
        if rc == -1:
            raise FileExistsError(f"object exists: {object_id}")
        if rc in (-2, -3):
            raise ObjectStoreFullError(
                f"cannot allocate {size} bytes (rc={rc})")
        if rc < 0:
            raise OSError(f"store_create failed: {rc}")
        off = int(rc)
        return self._buf[off:off + size]

    def seal(self, object_id: ObjectID) -> None:
        rc = self._lib.store_seal(self._base, object_id.binary())
        if rc != 0:
            raise KeyError(f"seal failed for {object_id}: {rc}")

    def abort(self, object_id: ObjectID) -> None:
        self._lib.store_abort(self._base, object_id.binary())

    def get(self, object_id: ObjectID,
            timeout: Optional[float] = 0.0) -> Optional[Tuple[memoryview, int]]:
        """Returns (buffer, meta) pinning the object, or None if absent.

        ``timeout``: 0 -> non-blocking; None -> wait forever; else seconds.
        """
        out = (ctypes.c_uint64 * 3)()
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.0005
        while True:
            rc = self._lib.store_get(self._base, object_id.binary(), out)
            if rc == 0:
                off, size, meta = out[0], out[1], out[2]
                return self._buf[off:off + size], int(meta)
            if deadline is not None and time.monotonic() >= deadline:
                return None
            time.sleep(delay)
            delay = min(delay * 2, 0.01)

    def release(self, object_id: ObjectID) -> None:
        self._lib.store_release(self._base, object_id.binary())

    def contains(self, object_id: ObjectID) -> bool:
        return self._lib.store_contains(self._base, object_id.binary()) == 1

    def delete(self, object_id: ObjectID) -> bool:
        return self._lib.store_delete(self._base, object_id.binary()) == 0

    def list_objects(self, max_entries: int = 65536) -> list:
        """Sealed objects as (ObjectID, size, lru_tick, pins) tuples — the
        spill manager's victim-selection view (cf. reference eviction-policy
        LRU walk feeding LocalObjectManager::SpillObjectsOfSize)."""
        buf = ctypes.create_string_buffer(40 * max_entries)
        n = self._lib.store_list(self._base, buf, max_entries)
        out = []
        raw = buf.raw
        for i in range(n):
            rec = raw[i * 40:(i + 1) * 40]
            out.append((ObjectID(rec[:20]),
                        int.from_bytes(rec[20:28], "little"),
                        int.from_bytes(rec[28:36], "little"),
                        int.from_bytes(rec[36:40], "little", signed=True)))
        return out

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 5)()
        self._lib.store_stats(self._base, out)
        return {"capacity": out[0], "bytes_in_use": out[1],
                "num_objects": out[2], "free_blocks": out[3],
                "leaked_bytes": out[4]}

    # --------------------------------------------------------- put helpers
    def put_serialized(self, object_id: ObjectID, head_payload: bytes,
                       views, error: bool = False,
                       allow_evict: bool = True) -> None:
        from ray_tpu._private import serialization as ser
        total = ser.serialized_size(head_payload, views)
        buf = self.create(object_id, total, meta=1 if error else 0,
                          allow_evict=allow_evict)
        try:
            ser.write_into(buf, head_payload, views)
        except BaseException:
            buf.release()
            self.abort(object_id)
            raise
        buf.release()
        self.seal(object_id)

    def get_deserialized(self, object_id: ObjectID,
                         timeout: Optional[float] = 0.0):
        """Returns (found, value). Zero-copy for numpy payloads: the
        object stays pinned while the value may hold views into the
        segment (release on GC is the caller's concern).  Payloads with
        NO out-of-band buffers (plain pickled python objects) are fully
        copied out by deserialization, so their pin is released here —
        a long stream of consumed generator items must not keep every
        item pinned in shm."""
        res = self.get(object_id, timeout)
        if res is None:
            return False, None
        buf, _meta = res
        from ray_tpu._private import serialization as ser
        try:
            value, holds_views = ser.deserialize_with_viewinfo(buf)
        except BaseException:
            buf.release()
            self.release(object_id)
            raise
        if not holds_views:
            buf.release()
            self.release(object_id)
        return True, value
